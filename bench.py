"""Benchmark harness — trains the flagship BNN MLP (the reference's
mnist-dist2.py configuration: 784->3072->1536->768->10, Adam) and reports
steady-state training throughput in images/sec.

Baseline (BASELINE.md): the reference's committed run does ~7,270 images/s
(60,000 images / 8.25 s per epoch, batch 64, "PersonalCom" hardware).
``vs_baseline`` is our images/s divided by that number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Flags let the driver/judge vary the setup (--batch-size, --backend,
--steps); defaults are chosen for a single TPU chip.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=2048)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--warmup", type=int, default=5)
    from distributed_mnist_bnns_tpu.ops.xnor_gemm import BACKENDS

    p.add_argument("--backend", default="bf16", choices=list(BACKENDS))
    p.add_argument("--model", default="bnn-mlp-large")
    p.add_argument("--input-shape", type=int, nargs=3, default=None,
                   metavar=("H", "W", "C"),
                   help="default: (28,28,1); xnor-resnet models get the "
                        "CIFAR shape (32,32,3)")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    if args.input_shape is not None:
        input_shape = tuple(args.input_shape)
    elif args.model.startswith("xnor-resnet"):
        input_shape = (32, 32, 3)
    else:
        input_shape = (28, 28, 1)

    config = TrainConfig(
        model=args.model,
        batch_size=args.batch_size,
        optimizer="adam",
        learning_rate=0.01,
        backend=args.backend,
        seed=0,
    )
    trainer = Trainer(config, input_shape=input_shape)

    key = jax.random.PRNGKey(0)
    images = jax.random.normal(
        key, (args.batch_size, *input_shape), jnp.float32
    )
    labels = jax.random.randint(key, (args.batch_size,), 0, 10)
    images = jax.device_put(images)
    labels = jax.device_put(labels)

    # Timing note: on remote-tunneled TPU backends, jax.block_until_ready can
    # return before device execution finishes, inflating throughput by >100x
    # (verified against a known-FLOPs matmul). The only trustworthy sync is a
    # host fetch of a value that depends on the timed work, and the fixed
    # tunnel round-trip must be cancelled out. So: time two runs of different
    # lengths, each ended by fetching the final loss, and report the
    # *marginal* per-step time between them.
    def timed_run(n_steps: int):
        # The train step donates its state argument, so each run continues
        # from (and replaces) trainer.state rather than reusing a donated
        # buffer.
        metrics = None
        t0 = time.perf_counter()
        for _ in range(n_steps):
            trainer.state, metrics = trainer.train_step(
                trainer.state, images, labels, trainer.rng
            )
        loss = float(metrics["loss"])  # host fetch = true device sync
        return time.perf_counter() - t0, loss

    steps = max(1, args.steps)
    base = max(5, args.warmup)
    timed_run(max(1, args.warmup))    # compile + warmup
    t_short, _ = timed_run(base)
    t_long, last_loss = timed_run(base + steps)
    # Floor the marginal delta: with tiny --steps, host/tunnel jitter can
    # make the two runs cross over; never emit a zero/negative step time.
    step_time = max((t_long - t_short) / steps, 1e-9)
    metrics = {"loss": last_loss}
    ips = args.batch_size / step_time
    # The baseline only describes the flagship model (BASELINE.md covers
    # mnist-dist2.py's bnn-mlp-large); any other model has no reference
    # number to compare against.
    baseline_ips = 7270.0 if args.model == "bnn-mlp-large" else None
    metric_name = (
        "train_throughput_mnist_bnn_mlp_large"
        if args.model == "bnn-mlp-large"
        else f"train_throughput_{args.model.replace('-', '_')}"
    )
    result = {
        "metric": metric_name,
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": (
            round(ips / baseline_ips, 2) if baseline_ips else None
        ),
        "batch_size": args.batch_size,
        "step_time_ms": round(step_time * 1e3, 3),
        # epoch-equivalent only defined for the MNIST flagship (60k images)
        "epoch_time_equiv_s": (
            round(60000.0 / ips, 3) if baseline_ips else None
        ),
        "backend": args.backend,
        "device": str(jax.devices()[0]),
        "loss_finite": bool(float(metrics["loss"]) == float(metrics["loss"])),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
