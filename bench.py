"""Benchmark harness — trains the flagship BNN MLP (the reference's
mnist-dist2.py configuration: 784->3072->1536->768->10, Adam) and reports
steady-state training throughput in images/sec.

Baseline (BASELINE.md): the reference's committed run does ~7,270 images/s
(60,000 images / 8.25 s per epoch, batch 64, "PersonalCom" hardware).
``vs_baseline`` is our images/s divided by that number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The extras include the SURVEY §7 crossover analysis: GEMM-level timings of
every binary backend (binary-TOPS) at a compute-bound training shape and a
bandwidth-bound inference shape with pre-packed bitplane weights.

Flags let the driver/judge vary the setup (--batch-size, --backend,
--steps); defaults are chosen for a single TPU chip.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

# Re-assert JAX_PLATFORMS over any sitecustomize that flipped the jax
# config at interpreter start — must run before anything initializes a
# backend; raises if a backend already initialized elsewhere.
from distributed_mnist_bnns_tpu.utils.platform import (
    enable_persistent_compilation_cache,
    pin_platform_from_env,
)

pin_platform_from_env()


def _min_marginal(fn, fetch, n_short: int, n_long: int, reps: int) -> float:
    """Min-of-reps marginal step time.

    On remote-tunneled TPU backends, jax.block_until_ready can return
    before device execution finishes, inflating throughput by >100x
    (verified against a known-FLOPs matmul). The only trustworthy sync is
    a host fetch of a value that depends on the timed work, and the fixed
    tunnel round-trip must be cancelled out. So: time runs of two
    different lengths, each ended by a host fetch, and report the
    *marginal* per-step time between the MINIMA over ``reps`` runs of
    each length — tunnel/host jitter is strictly additive, so the minimum
    is the lowest-noise estimator of the true run time. Can return <= 0
    when the marginal workload is below the jitter floor; callers must
    treat that as "unmeasurable", not as a time."""

    def run(n: int) -> float:
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = fn()
        fetch(r)  # host fetch = true device sync
        return time.perf_counter() - t0

    shorts, longs = [], []
    for _ in range(reps):
        shorts.append(run(n_short))
        longs.append(run(n_short + n_long))
    return (min(longs) - min(shorts)) / n_long


_FLOOR_S = 0.04  # marginal workloads below this are inside tunnel jitter


def _measure(fn, fetch, n_short, n_long, reps, deadline):
    """Marginal time with auto-escalation: if the marginal workload is
    under the jitter floor, rerun with 8x the long run (twice at most).
    Returns (dt_seconds or None-if-unmeasurable, n_long_used). Honors the
    deadline up front: a measurement that would start past it (e.g. a
    fallback after a budget-consuming first attempt) is skipped entirely."""
    if time.monotonic() > deadline:
        return None, n_long
    dt = _min_marginal(fn, fetch, n_short, n_long, reps)
    for _ in range(2):
        if dt > 0 and dt * n_long >= _FLOOR_S:
            return dt, n_long
        if time.monotonic() > deadline:
            break
        n_long *= 8
        dt = _min_marginal(fn, fetch, n_short, n_long, reps)
    if dt > 0 and dt * n_long >= _FLOOR_S:
        return dt, n_long
    return None, n_long


def _bench_train_step(trainer, images, labels, steps, warmup, reps=3,
                      deadline=float("inf")):
    """Per-step-dispatch throughput (one host dispatch per batch)."""
    state = {"metrics": None}

    def one():
        trainer.state, state["metrics"] = trainer.train_step(
            trainer.state, images, labels, trainer.rng
        )
        return state["metrics"]

    def fetch(metrics):
        state["loss"] = float(metrics["loss"])

    for _ in range(max(1, warmup)):
        one()
    fetch(state["metrics"])  # force compile + settle
    dt, _ = _measure(one, fetch, max(5, warmup), max(1, steps), reps, deadline)
    return dt, state["loss"]


def _bench_train_scan(trainer, scan_steps, batch_size, input_shape,
                      dispatches, warmup, reps=3, deadline=float("inf")):
    """Scan-dispatch throughput: ``scan_steps`` train steps fused into one
    lax.scan program (train/trainer.py make_train_scan), so the measured
    time is device execution, not host/tunnel dispatch latency. Data is
    generated on-device (no H2D in the timed region)."""
    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.train import make_train_scan

    scan = make_train_scan(trainer.clamp_mask, loss_fn=trainer._loss_fn)

    @jax.jit
    def make_data(key):
        ki, kl = jax.random.split(key)
        images = jax.random.normal(
            ki, (scan_steps, batch_size, *input_shape), jnp.float32
        )
        labels = jax.random.randint(
            kl, (scan_steps, batch_size), 0, 10
        )
        return images, labels

    images, labels = make_data(jax.random.PRNGKey(0))
    state = {"metrics": None}

    def one():
        trainer.state, state["metrics"] = scan(
            trainer.state, images, labels, trainer.rng
        )
        return state["metrics"]

    def fetch(metrics):
        state["loss"] = float(metrics["loss"])

    for _ in range(max(1, warmup)):
        one()
    fetch(state["metrics"])
    dt, _ = _measure(one, fetch, 2, max(1, dispatches), reps, deadline)
    if dt is None:
        return None, state["loss"]
    return dt / scan_steps, state["loss"]


def _gemm_crossover(jax, jnp, deadline: float, reps: int = 3):
    """GEMM-level crossover (SURVEY §7): binary-TOPS per backend at a
    compute-bound training shape and a bandwidth-bound inference shape.
    All operands are passed as arguments (no constant folding) except the
    'prepacked' rows, which deliberately hoist the weight pack — the
    inference deployment mode of a frozen BNN.

    ``deadline`` (time.monotonic timestamp): remote compiles through the
    tunnel can take minutes when the endpoint is degraded; rows past the
    deadline are marked skipped so the driver's bench run always finishes
    inside its budget (full numbers live in PERF.md)."""
    from distributed_mnist_bnns_tpu.ops.xnor_gemm import (
        prepack_weights,
        xnor_matmul,
        xnor_matmul_packed,
        xnor_matmul_packed_sign,
    )

    def pm1(key, shape):
        return jnp.where(
            jax.random.bernoulli(jax.random.PRNGKey(key), 0.5, shape),
            1.0, -1.0,
        )

    bf16 = jax.jit(
        lambda x, w: jnp.dot(
            x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    )
    int8 = jax.jit(
        lambda x, w: jnp.dot(
            x.astype(jnp.int8), w.astype(jnp.int8),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
    )
    pallas = jax.jit(lambda x, w: xnor_matmul(x, w))

    bf16_pre = jax.jit(
        lambda x, wb: jnp.dot(
            x.astype(jnp.bfloat16), wb, preferred_element_type=jnp.float32
        )
    )

    out = {}
    # (m, k, n, n_short, n_long): small workloads need long runs or the
    # tunnel jitter swamps the marginal.
    shapes = {
        "train_2048x3072x1536": (2048, 3072, 1536, 20, 100),
        "infer_8x8192x4096": (8, 8192, 4096, 50, 400),
    }
    for name, (m, k, n, n_short, n_long) in shapes.items():
        x, w = pm1(1, (m, k)), pm1(2, (k, n))
        wp, _, _ = prepack_weights(w)
        wp = jax.device_put(wp)
        wb = jax.device_put(w.astype(jnp.bfloat16))
        packed = jax.jit(
            lambda x, wp=wp, k=k, n=n: xnor_matmul_packed(x, wp, k, n)
        )
        # fused serving layer: packed GEMM + bias + BN-threshold-sign in
        # one kernel (the frozen hidden-layer op, infer._build_apply) vs
        # the unfused pair — measures the saved (M, N) fp32 round trip
        av = jnp.ones((n,), jnp.float32)
        tv = jnp.zeros((n,), jnp.float32)
        bv = jnp.zeros((n,), jnp.float32)
        fused_sign = jax.jit(
            lambda x, wp=wp, k=k, n=n: xnor_matmul_packed_sign(
                x, wp, k, n, av, tv, bv
            )
        )
        unfused_sign = jax.jit(
            lambda x, wp=wp, k=k, n=n: jnp.where(
                xnor_matmul_packed(x, wp, k, n) + bv >= tv, 1.0, -1.0
            )
        )
        tops = 2.0 * m * k * n
        row = {}
        for bname, fn in (
            ("bf16_cast", lambda x: bf16(x, w)),
            ("bf16_precast_w", lambda x: bf16_pre(x, wb)),
            ("int8_cast", lambda x: int8(x, w)),
            ("pallas_xnor", lambda x: pallas(x, w)),
            ("pallas_xnor_prepacked_w", packed),
            ("packed_sign_fused", fused_sign),
            ("packed_sign_unfused", unfused_sign),
        ):
            if time.monotonic() > deadline:
                row[bname] = "skipped (bench deadline; see PERF.md)"
                continue
            dt, n_used = _measure(
                lambda fn=fn, x=x: fn(x),
                lambda r: float(jnp.sum(r)),
                n_short, n_long, reps, deadline,
            )
            if dt is None:
                row[bname] = (
                    f"below measurement floor ({n_used} calls still "
                    "inside tunnel jitter)"
                )
                continue
            row[bname] = {
                "ms": round(dt * 1e3, 4),
                "binary_tops": round(tops / dt / 1e12, 2),
            }
        out[name] = row
    out["weight_bytes_per_param"] = {
        "bf16": 2.0, "int8": 1.0, "bitplane_packed": 1.0 / 32.0,
    }
    out["note"] = (
        "On TPU the MXU (bf16/int8 on +-1 operands) is the binary engine at "
        "compute-bound training shapes; the VPU XNOR-popcount kernel's "
        "ceiling is bit-op bound. With weights pre-packed (frozen-model "
        "inference), the bitplane kernel reads 32x less weight HBM and wins "
        "the bandwidth-bound small-batch regime."
    )
    return out


def _utc_now(epoch_s: float | None = None) -> str:
    return time.strftime(
        "%Y-%m-%dT%H:%M:%SZ",
        time.gmtime(epoch_s) if epoch_s is not None else time.gmtime(),
    )


# Sections a bench record can contribute independently of its headline
# number. THE single definition — the dead-endpoint carry-over below and
# scripts/window_agenda.py's merge both import this tuple, so the two
# whitelists can no longer drift (a banked train_step_per_backend section
# was silently dropped when they did).
SECTION_MERGE_KEYS = (
    "serving", "lm_flash", "crossover", "stretch_xnor_resnet18_cifar",
    "device_resident_epoch", "train_step_per_backend", "comm",
    "comm_fsdp", "comm_hier", "lm_serve", "serving_p99", "cold_start",
    "device_costs", "fleet_availability",
)


def _emit_events(path: str | None, result: dict,
                 model: str | None = None) -> None:
    """Mirror the bench record into the telemetry event schema
    (obs/events.py): a run manifest, one ``step`` event derived from the
    headline measurement (so `cli telemetry` reports bench latency with
    the same fields as a training run), and the full record as a
    ``bench`` event. Best-effort — an emission failure must never cost
    the bench its JSON line."""
    if not path:
        return
    try:
        from distributed_mnist_bnns_tpu.obs import EventLog

        with EventLog(path) as ev:
            ev.manifest(config={
                "tool": "bench.py", "metric": result.get("metric"),
                "model": model,
                "backend": result.get("backend"),
                "batch_size": result.get("batch_size"),
            })
            step_ms = result.get("step_time_ms")
            if isinstance(step_ms, (int, float)) and step_ms > 0:
                ev.emit(
                    "step",
                    latency_s=step_ms / 1e3,
                    examples_per_sec=result.get("value"),
                    mfu=result.get("mfu"),
                    batch_size=result.get("batch_size"),
                    n_steps=1,
                )
            ev.emit("bench", **result)
    except Exception as e:
        print(f"bench events emission failed: {e!r}", file=sys.stderr)


_PROGRESS_T0 = time.monotonic()
_PROGRESS_ON = False


def _progress(msg: str) -> None:
    """Stage marker on stderr (``--verbose``): the window watchdog sees
    output advance between sections, and a killed run's captured stderr
    names the stage it died in (the 08:31 window post-mortem had only a
    probe line to go on)."""
    if _PROGRESS_ON:
        print(
            f"[bench +{time.monotonic() - _PROGRESS_T0:.0f}s] {msg}",
            file=sys.stderr, flush=True,
        )


def _device_responsive(timeout_s: float) -> bool:
    """Probe the default jax backend in a CHILD process with a hard
    timeout. A degraded remote-TPU tunnel hangs dispatches indefinitely
    and an in-process hung jax call cannot be interrupted — probing in a
    subprocess is the only way bench.py can guarantee it emits its JSON
    line (instead of eating the driver's whole time budget) when the
    endpoint is down."""
    import subprocess

    # Honor JAX_PLATFORMS in the child the same way bench itself does —
    # the image's sitecustomize can flip the platform at interpreter
    # start, overriding the env (see utils/platform.py).
    code = (
        "import os;"
        "from distributed_mnist_bnns_tpu.utils.platform import pin_platform;"
        "p = os.environ.get('JAX_PLATFORMS');"
        "_ = pin_platform(p) if p else None;"
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((128, 128));"
        "print(float(jnp.sum(jnp.dot(x, x))))"
    )
    try:
        subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, check=True, capture_output=True,
        )
        return True
    except Exception:
        return False


def _probe_device_retry(attempt_timeout_s: float, budget_s: float):
    """Probe with retry-and-backoff across ``budget_s``: the tunnel
    endpoint goes down for stretches and comes back (r01: down at bench
    time; r02: up; r03: one 150 s probe failed and the whole round shipped
    without a number). A single give-up-once probe wastes any live window
    later in the budget, so keep probing with growing sleeps until the
    endpoint answers or the budget is spent.

    Returns (alive, probe_log): probe_log is one record per attempt so a
    persistent failure ships with evidence the endpoint stayed dead."""
    log = []
    start = time.monotonic()
    deadline = start + budget_s
    sleep = 30.0
    attempt = 0
    while True:
        attempt += 1
        t0 = time.monotonic()
        ok = _device_responsive(attempt_timeout_s)
        log.append({
            "attempt": attempt,
            "at_s": round(t0 - start, 1),
            "probe_s": round(time.monotonic() - t0, 1),
            "alive": ok,
        })
        # Progress to stderr (stdout stays one JSON line): if the driver
        # times the whole bench out mid-probe, the retry evidence still
        # exists in the captured stderr.
        print(f"bench probe {log[-1]}", file=sys.stderr, flush=True)
        if ok:
            return True, log
        # Stop when another sleep+probe cannot finish inside the budget.
        if time.monotonic() + sleep + attempt_timeout_s > deadline:
            return False, log
        time.sleep(sleep)
        sleep = min(sleep * 2.0, 480.0)


# Chip-peak / MAC / MFU accounting lives in the telemetry subsystem
# (distributed_mnist_bnns_tpu/obs/flops.py — single source shared with
# the trainer's step-level telemetry); these thin aliases keep bench.py's
# long-standing helper names working for the scripts/ harnesses.
from distributed_mnist_bnns_tpu.obs.flops import (  # noqa: E402
    chip_peak as _chip_peak,
    chip_peak_bf16 as _chip_peak_bf16,
    dense_macs_per_example as _dense_macs_per_image,
    mfu as _mfu,
)


def _step_flops(trainer, batch_size: int) -> tuple[float, str] | None:
    """FLOPs of one optimizer step over ``batch_size`` images: analytic
    3x forward GEMM FLOPs — fwd = 2*MACs, plus ~2x fwd for the two
    backward GEMMs per layer (dL/dW and dL/dx), the standard
    training-FLOPs estimate. (XLA's cost_analysis is not used: it is
    unavailable through the remote-compile tunnel backend, and its flop
    count would include optimizer/elementwise noise the MFU convention
    excludes.) Returns (flops, method) or None for models where the
    dense count would undercount (convs)."""
    model = getattr(trainer.config, "model", "")
    if not ("mlp" in model or "qnn" in model):
        # Conv models put most FLOPs outside rank-2 kernels; the dense
        # analytic count would be a large undercount — no MFU claim.
        return None
    macs = _dense_macs_per_image(trainer.state.params)
    if macs > 0:
        return 3.0 * 2.0 * macs * batch_size, "analytic_3x_dense_gemms"
    return None


def _conv_macs_per_image(model, variables, input_shape) -> int:
    """Analytic conv+dense MAC count of one forward pass (delegates to
    obs/flops.jaxpr_macs_per_example — the conv-family counterpart of
    ``_dense_macs_per_image``)."""
    from distributed_mnist_bnns_tpu.obs.flops import jaxpr_macs_per_example

    return jaxpr_macs_per_example(model.apply, variables, input_shape)


def _cpu_fallback_extras(args):
    """When the device endpoint stays dead for the whole probe budget,
    still emit CPU-verifiable evidence: a short flagship train-step run
    on the CPU backend (correctness + a lower-bound throughput, clearly
    labeled — NOT the TPU headline). Only possible because the probe runs
    in subprocesses, so no backend has been initialized in-process yet."""
    from distributed_mnist_bnns_tpu.utils.platform import pin_platform

    if not pin_platform("cpu"):
        return "unavailable (a non-cpu backend is already initialized)"
    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    if args.input_shape is not None:
        input_shape = tuple(args.input_shape)
    elif args.model.startswith("xnor-resnet"):
        input_shape = (32, 32, 3)
    else:
        input_shape = (28, 28, 1)
    bs = min(args.batch_size, 256)  # CPU evidence, keep it quick
    trainer = Trainer(
        TrainConfig(
            model=args.model, batch_size=bs, optimizer="adam",
            learning_rate=0.01, backend="bf16", seed=0,
        ),
        input_shape=input_shape,
    )
    key = jax.random.PRNGKey(0)
    images = jax.random.normal(key, (bs, *input_shape), jnp.float32)
    labels = jax.random.randint(key, (bs,), 0, 10)
    loss = None
    for _ in range(3):  # compile + warm
        trainer.state, m = trainer.train_step(
            trainer.state, images, labels, trainer.rng
        )
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    steps = 10
    for _ in range(steps):
        trainer.state, m = trainer.train_step(
            trainer.state, images, labels, trainer.rng
        )
    loss = float(m["loss"])  # host fetch = sync (trustworthy on CPU)
    dt = (time.perf_counter() - t0) / steps
    return {
        "note": "CPU-backend evidence only: correctness + lower-bound "
                "throughput while the TPU endpoint was unreachable",
        "platform": "cpu",
        "model": args.model,
        "batch_size": bs,
        "input_shape": list(input_shape),
        "images_per_sec": round(bs / dt, 1),
        "step_time_ms": round(dt * 1e3, 3),
        "loss_finite": math.isfinite(loss),
    }


def _bench_comm(args, deadline):
    """Gradient-exchange section (--comm-bench; PERF.md "Gradient
    comms"): the DP train step at each grad_compress mode — fp32 psum
    baseline vs 1-bit sign / sign_ef — reporting wire bytes/step (the
    analytic ring model over the real packed sizes, the same numbers
    the comm_bytes_total counter accumulates) and measured step time.
    Wire savings are topology-independent; the step-time column is only
    meaningful where the interconnect, not compute, bounds the step —
    on a single-host CPU/TPU mesh the collectives are ICI/shared-memory
    and the compression arithmetic usually costs more than it saves."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    n = jax.device_count()
    out = {
        "devices": n,
        "model": args.model,
        "batch_size": args.comm_batch_size,
        "backend": args.backend,
        "device_kind": str(jax.devices()[0].device_kind),
    }
    if n < 2:
        out["note"] = "single device: no gradient exchange to measure"
        return out
    bs = -(-args.comm_batch_size // n) * n
    if args.model.startswith("xnor-resnet"):
        input_shape = (32, 32, 3)
    else:
        input_shape = (28, 28, 1)
    key = jax.random.PRNGKey(0)
    images = np.asarray(jax.random.normal(
        key, (bs, *input_shape), jnp.float32
    ))
    labels = np.asarray(jax.random.randint(key, (bs,), 0, 10))
    modes = {}
    for mode in ("none", "sign", "sign_ef"):
        if time.monotonic() > deadline:
            modes[mode] = "skipped (bench deadline)"
            continue
        trainer = Trainer(
            TrainConfig(
                model=args.model, batch_size=bs, optimizer="adam",
                learning_rate=0.01, backend=args.backend, seed=0,
                data_parallel="auto", grad_compress=mode,
            ),
            input_shape=input_shape,
        )
        dt, loss = _bench_train_step(
            trainer, images, labels, min(args.steps, args.comm_steps),
            args.warmup, args.reps, deadline,
        )
        plan = trainer.comm_plan
        row = {
            "wire_bytes_per_step": plan.wire_bytes_per_step,
            "wire_bytes_rs": plan.wire_bytes_rs,
            "wire_bytes_ag": plan.wire_bytes_ag,
            "wire_ratio_vs_fp32": (
                round(plan.wire_ratio, 5)
                if plan.wire_ratio is not None else None
            ),
            "n_params": plan.n_params,
            "buckets": plan.world * plan.nb,
        }
        if dt is None:
            row["step_time_ms"] = "below measurement floor"
        else:
            row.update(
                step_time_ms=round(dt * 1e3, 3),
                images_per_sec=round(bs / dt, 1),
                loss_finite=math.isfinite(loss),
            )
        modes[mode] = row
    out["modes"] = modes
    sign = modes.get("sign")
    if isinstance(sign, dict) and isinstance(modes.get("none"), dict):
        base_bytes = modes["none"]["wire_bytes_per_step"]
        out["bytes_reduction_sign"] = (
            round(base_bytes / sign["wire_bytes_per_step"], 1)
            if sign["wire_bytes_per_step"] else None
        )
    return out


def _bench_comm_fsdp(args, deadline):
    """Compressed-FSDP section (--comm-bench; PERF.md "Gradient comms —
    compressed FSDP"): fp32 GSPMD FSDP (the reduce-scatter + all-gather
    pair) vs the 1-bit exchange with the ZeRO-sharded base optimizer
    (sign_ef), per-phase wire bytes/step and measured step time, plus
    the fused scan_steps=4 composition with its post-warmup compile
    count (the zero-compile contract the perf gate pins). Same caveat
    as the DP section: on a single-host CPU mesh the step-time column
    is compute-bound, the byte columns are the portable result."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_mnist_bnns_tpu.obs import get_tracker
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    n = jax.device_count()
    out = {
        "devices": n,
        "model": args.model,
        "batch_size": args.comm_batch_size,
        "backend": args.backend,
        "device_kind": str(jax.devices()[0].device_kind),
    }
    if n < 2:
        out["note"] = "single device: no FSDP exchange to measure"
        return out
    bs = -(-args.comm_batch_size // n) * n
    if args.model.startswith("xnor-resnet"):
        input_shape = (32, 32, 3)
    else:
        input_shape = (28, 28, 1)
    key = jax.random.PRNGKey(0)
    images = np.asarray(jax.random.normal(
        key, (bs, *input_shape), jnp.float32
    ))
    labels = np.asarray(jax.random.randint(key, (bs,), 0, 10))
    tracker = get_tracker()
    variants = {}
    for name, mode, scan_steps in (
        ("fp32", "none", 1),
        ("sign_ef", "sign_ef", 1),
        ("sign_ef_scan4", "sign_ef", 4),
    ):
        if time.monotonic() > deadline:
            variants[name] = "skipped (bench deadline)"
            continue
        trainer = Trainer(
            TrainConfig(
                model=args.model, batch_size=bs, optimizer="adam",
                learning_rate=0.01, backend=args.backend, seed=0,
                data_parallel="auto", dp_mode="fsdp",
                grad_compress=mode, scan_steps=scan_steps,
            ),
            input_shape=input_shape,
        )
        steps = min(args.steps, args.comm_steps)
        if scan_steps > 1:
            scan = trainer._get_train_scan()
            s_images = np.broadcast_to(
                images, (scan_steps, *images.shape)
            ).copy()
            s_labels = np.broadcast_to(
                labels, (scan_steps, *labels.shape)
            ).copy()
            state = {"metrics": None}

            def one():
                trainer.state, state["metrics"] = scan(
                    trainer.state, s_images, s_labels, trainer.rng
                )
                return state["metrics"]

            def fetch(metrics):
                state["loss"] = float(metrics["loss"])

            for _ in range(max(1, args.warmup)):
                one()
            fetch(state["metrics"])  # compile + settle = warmup done
            c0 = tracker.count
            dt, _ = _measure(
                one, fetch, max(5, args.warmup),
                max(1, -(-steps // scan_steps)), args.reps, deadline,
            )
            compiles_post_warmup = tracker.count - c0
            loss = state["loss"]
            if dt is not None:
                dt = dt / scan_steps  # amortized per optimizer step
        else:
            # warm separately so the compile count covers ONLY the
            # post-warmup steps (the gated metric)
            for _ in range(max(1, args.warmup)):
                trainer.state, m = trainer.train_step(
                    trainer.state, images, labels, trainer.rng
                )
            float(m["loss"])
            c0 = tracker.count
            dt, loss = _bench_train_step(
                trainer, images, labels, steps,
                args.warmup, args.reps, deadline,
            )
            compiles_post_warmup = tracker.count - c0
        plan = trainer.comm_plan
        row = {
            "layout": plan.layout,
            "scan_steps": scan_steps,
            "wire_bytes_per_step": plan.wire_bytes_per_step,
            "wire_bytes_rs": plan.wire_bytes_rs,
            "wire_bytes_ag": plan.wire_bytes_ag,
            "wire_ratio_vs_fp32": (
                round(plan.wire_ratio, 5)
                if plan.wire_ratio is not None else None
            ),
            "n_params": plan.n_params,
            "compiles_post_warmup": compiles_post_warmup,
        }
        if dt is None:
            row["step_time_ms"] = "below measurement floor"
        else:
            row.update(
                step_time_ms=round(dt * 1e3, 3),
                images_per_sec=round(bs / dt, 1),
                loss_finite=math.isfinite(loss),
            )
        variants[name] = row
    out["variants"] = variants
    comp = variants.get("sign_ef")
    base = variants.get("fp32")
    if isinstance(comp, dict) and isinstance(base, dict):
        out["bytes_reduction_sign_ef"] = (
            round(
                base["wire_bytes_per_step"] / comp["wire_bytes_per_step"],
                1,
            )
            if comp["wire_bytes_per_step"] else None
        )
    return out


def _bench_comm_hier(args, deadline):
    """Two-level hierarchical exchange section (--comm-bench; PERF.md
    "Hierarchical comms"): the DP world factored into (hosts x local)
    — fp32 ring reduce within a host's 'local' mesh axis, 1-bit
    sign_ef exchange over the inter-host axis only. Reports the
    two-level analytic wire model (intra fp32 ring bytes vs inter 1-bit
    bytes, both derived from the real packed sizes like the flat
    sections) plus measured step time and the post-warmup compile
    count. The gated headline: inter-host bytes as a fraction of the
    flat fp32 ring at the SAME total world (<= 1/8 by the multi-host
    acceptance band — the slow-link traffic the hierarchy exists to
    minimize)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_mnist_bnns_tpu.obs import get_tracker
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    n = jax.device_count()
    hosts = 2
    out = {
        "devices": n,
        "hosts": hosts,
        "model": args.model,
        "batch_size": args.comm_batch_size,
        "backend": args.backend,
        "device_kind": str(jax.devices()[0].device_kind),
    }
    if n < 4 or n % hosts:
        out["note"] = (
            f"{n} devices cannot factor into (hosts={hosts} x local>1): "
            "no hierarchical exchange to measure"
        )
        return out
    bs = -(-args.comm_batch_size // n) * n
    if args.model.startswith("xnor-resnet"):
        input_shape = (32, 32, 3)
    else:
        input_shape = (28, 28, 1)
    key = jax.random.PRNGKey(0)
    images = np.asarray(jax.random.normal(
        key, (bs, *input_shape), jnp.float32
    ))
    labels = np.asarray(jax.random.randint(key, (bs,), 0, 10))
    if time.monotonic() > deadline:
        out["hier"] = "skipped (bench deadline)"
        return out
    tracker = get_tracker()
    trainer = Trainer(
        TrainConfig(
            model=args.model, batch_size=bs, optimizer="adam",
            learning_rate=0.01, backend=args.backend, seed=0,
            data_parallel="auto", grad_compress="sign_ef",
            dp_hosts=hosts,
        ),
        input_shape=input_shape,
    )
    # warm separately so the compile count covers ONLY the post-warmup
    # steps (the gated zero-compile contract, as in the fsdp section)
    for _ in range(max(1, args.warmup)):
        trainer.state, m = trainer.train_step(
            trainer.state, images, labels, trainer.rng
        )
    float(m["loss"])
    c0 = tracker.count
    dt, loss = _bench_train_step(
        trainer, images, labels, min(args.steps, args.comm_steps),
        args.warmup, args.reps, deadline,
    )
    compiles_post_warmup = tracker.count - c0
    h = trainer.hier_plan
    row = {
        "hosts": h.hosts,
        "local": h.local,
        "n_params": h.inter.n_params,
        "intra_bytes_per_step": h.intra_bytes_per_step,
        "inter_bytes_per_step": h.inter_bytes_per_step,
        "inter_bytes_rs": h.inter.wire_bytes_rs,
        "inter_bytes_ag": h.inter.wire_bytes_ag,
        "flat_fp32_bytes_per_step": h.flat_fp32_bytes_per_step,
        "inter_ratio_vs_flat_fp32": (
            round(h.inter_ratio_vs_flat_fp32, 5)
            if h.inter_ratio_vs_flat_fp32 is not None else None
        ),
        "compiles_post_warmup": compiles_post_warmup,
    }
    if dt is None:
        row["step_time_ms"] = "below measurement floor"
    else:
        row.update(
            step_time_ms=round(dt * 1e3, 3),
            images_per_sec=round(bs / dt, 1),
            loss_finite=math.isfinite(loss),
        )
    out["hier"] = row
    return out


def _bench_lm(args, deadline):
    """Long-context stack throughput: tokens/sec of a causal BinarizedLM
    train step with the flash-attention kernels (fwd + Pallas backward)
    at a tile-aligned sequence length — the measurable headline for the
    flash/ring stack (--lm-bench; off by default so the driver's
    standard run is unchanged)."""
    import jax
    import optax

    from distributed_mnist_bnns_tpu.models import latent_clamp_mask
    from distributed_mnist_bnns_tpu.models.transformer import (
        BinarizedLM,
        lm_loss,
    )
    from distributed_mnist_bnns_tpu.train import clamp_latent

    b, t = args.lm_batch_size, args.lm_seq_len
    # Real Mosaic lowering on TPU; interpreter elsewhere (CPU smoke runs)
    attention = (
        "flash" if jax.default_backend() == "tpu" else "flash_interpret"
    )
    model = BinarizedLM(
        vocab=256, max_len=t, embed_dim=args.lm_embed_dim,
        depth=args.lm_depth, num_heads=args.lm_heads, attention=attention,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (b, t), 0, 256)
    variables = model.init(
        {"params": jax.random.PRNGKey(1),
         "dropout": jax.random.PRNGKey(2)},
        tokens, train=False,
    )
    params = variables["params"]
    mask = latent_clamp_mask(params)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, tokens):
        def loss_fn(p):
            return lm_loss(
                model.apply({"params": p}, tokens, train=False), tokens
            )

        loss, g = jax.value_and_grad(loss_fn)(params)
        up, opt = tx.update(g, opt, params)
        return clamp_latent(optax.apply_updates(params, up), mask), opt, loss

    holder = {}

    def one():
        nonlocal params, opt
        params, opt, holder["loss"] = step(params, opt, tokens)
        return holder["loss"]

    def fetch(loss):
        holder["lossf"] = float(loss)

    one()
    fetch(holder["loss"])  # compile + settle
    dt, _ = _measure(one, fetch, 3, 10, args.reps, deadline)
    if dt is None:
        return "below measurement floor"
    return {
        "tokens_per_sec": round(b * t / dt, 1),
        "step_time_ms": round(dt * 1e3, 3),
        "batch_size": b, "seq_len": t,
        "depth": args.lm_depth, "embed_dim": args.lm_embed_dim,
        "attention": f"{attention} (pallas fwd + bwd)",
        "loss_finite": math.isfinite(holder["lossf"]),
    }


def _bench_device_epoch(args, deadline):
    """Device-resident full-epoch benchmark: a reference-sized (60k-image)
    epoch as ONE dispatched program over the resident dataset
    (train/trainer.py make_train_epoch_fn) — the number to hold against
    the reference's 8.25 s/epoch (BASELINE.md) with the entire host loop
    removed."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_mnist_bnns_tpu.data.common import ImageClassData
    from distributed_mnist_bnns_tpu.data.mnist import shard_indices
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    n = args.epoch_bench_images
    rng = np.random.RandomState(0)
    data = ImageClassData(
        train_images=rng.rand(n, 28, 28, 1).astype(np.float32),
        train_labels=rng.randint(0, 10, n).astype(np.int32),
        test_images=np.zeros((16, 28, 28, 1), np.float32),
        test_labels=np.zeros(16, np.int32),
    )
    trainer = Trainer(
        TrainConfig(
            model=args.model, batch_size=args.batch_size,
            optimizer="adam", learning_rate=0.01, backend=args.backend,
            seed=0, device_data=True,
        )
    )
    images_all, labels_all = trainer._get_device_dataset(data)
    idx = shard_indices(n, epoch=0, seed=0, host_id=0, num_hosts=1)
    nb = len(idx) // args.batch_size
    idx = jnp.asarray(
        np.asarray(idx[: nb * args.batch_size], np.int32)
        .reshape(nb, args.batch_size)
    )
    epoch_fn = trainer._get_epoch_fn()
    holder = {}

    def one():
        trainer.state, holder["m"] = epoch_fn(
            trainer.state, images_all, labels_all, idx, trainer.rng
        )
        return holder["m"]

    def fetch(m):
        holder["loss"] = float(m["loss"])

    one()
    fetch(holder["m"])  # compile + settle
    dt, _ = _measure(one, fetch, 1, 4, args.reps, deadline)
    if dt is None:
        return "below measurement floor"
    import jax

    n_img = nb * args.batch_size
    flops_info = _step_flops(trainer, n_img)  # whole epoch = one "step"
    return {
        "epoch_time_s": round(dt, 4),
        "images_per_sec": round(n_img / dt, 1),
        "n_images": n_img,
        "batch_size": args.batch_size,
        "dispatches_per_epoch": 1,
        "loss_finite": math.isfinite(holder["loss"]),
        "vs_reference_epoch_s": 8.25,
        "mfu": _mfu(
            flops_info[0] if flops_info else None, dt,
            _chip_peak(jax.devices()[0], args.backend)[0],
        ),
    }


def _bench_serving(args, deadline):
    """End-to-end frozen-model serving benchmark (VERDICT r4 item 1):
    packed img/s for the flagship MLP and the conv stretch at small and
    offline batches vs the live eval forward, KV-cache decode tokens/s,
    and artifact-load-to-first-logit latency — the model-level numbers
    behind SERVING.md's deployment story (the role cuDNN inference plays
    for the reference, models/binarized_modules.py:80).

    Weights are fresh inits (BN stats degenerate): serving throughput is
    weight-value-independent, and training on the bench clock would burn
    the live-window budget the numbers need."""
    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.infer import (
        export_packed,
        freeze_bnn_mlp,
        load_packed,
    )
    from distributed_mnist_bnns_tpu.infer_conv import freeze_xnor_resnet
    from distributed_mnist_bnns_tpu.models import get_model

    interp = jax.default_backend() != "tpu"
    out = {"interpret_mode": interp}
    reps = args.reps

    def time_one(fn, x, n_short=20, n_long=200):
        if time.monotonic() > deadline:
            return None
        r = fn(x)  # compile + settle
        float(jnp.sum(r))
        dt, _ = _measure(
            lambda: fn(x), lambda r: float(jnp.sum(r)),
            n_short, n_long, reps, deadline,
        )
        return dt

    def batch_rows(frozen_fn, live_fn, input_shape, batches):
        rows = {}
        for b in batches:
            x = jax.device_put(jax.random.normal(
                jax.random.PRNGKey(b), (b, *input_shape), jnp.float32
            ))
            row = {}
            dt = time_one(frozen_fn, x)
            if dt is not None:
                row["frozen"] = {
                    "images_per_sec": round(b / dt, 1),
                    "latency_ms": round(dt * 1e3, 4),
                }
            dt = time_one(live_fn, x)
            if dt is not None:
                row["live_eval"] = {
                    "images_per_sec": round(b / dt, 1),
                    "latency_ms": round(dt * 1e3, 4),
                }
            if "frozen" in row and "live_eval" in row:
                row["frozen_speedup"] = round(
                    row["frozen"]["images_per_sec"]
                    / row["live_eval"]["images_per_sec"], 2,
                )
            rows[f"batch_{b}"] = row
        return rows

    # -- flagship MLP -------------------------------------------------
    try:
        model = get_model("bnn-mlp-large")
        x0 = jnp.zeros((2, 28, 28, 1), jnp.float32)
        variables = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            x0, train=True,
        )
        frozen_fn, info = freeze_bnn_mlp(
            model, variables, interpret=interp
        )
        live_fn = jax.jit(
            lambda x: model.apply(variables, x, train=False)
        )
        out["bnn_mlp_large"] = {
            "compression": info["compression"],
            **batch_rows(
                frozen_fn, live_fn, (28, 28, 1), args.serving_batches
            ),
        }
    except Exception as e:
        out["bnn_mlp_large"] = f"failed: {e!r:.300}"
    # artifact load -> first logit (cold-serve latency): disk read +
    # predictor build + first batch-1 call including its compile.
    # Guarded separately so an export/IO failure can't discard the
    # batch-throughput rows measured above.
    try:
        import tempfile

        if (
            isinstance(out.get("bnn_mlp_large"), dict)
            and time.monotonic() < deadline
        ):
            with tempfile.TemporaryDirectory() as d:
                path = os.path.join(d, "mlp.packed")
                export_packed(model, variables, path)
                x1 = jnp.zeros((1, 28, 28, 1), jnp.float32)
                t0 = time.perf_counter()
                fn, _ = load_packed(path, interpret=interp)
                t_load = time.perf_counter()
                float(jnp.sum(fn(x1)))
                t_first = time.perf_counter()
                out["bnn_mlp_large"]["artifact"] = {
                    "bytes_on_disk": os.path.getsize(path),
                    "load_s": round(t_load - t0, 4),
                    "first_logit_s": round(t_first - t0, 4),
                    "note": (
                        "first_logit includes the batch-1 XLA compile "
                        "— or its persistent-cache deserialize when "
                        ".jax_cache is warm (see compilation_cache_"
                        "entries at record top level)"
                    ),
                }
    except Exception as e:
        out["bnn_mlp_large"]["artifact"] = f"failed: {e!r:.300}"

    # -- conv stretch -------------------------------------------------
    try:
        if time.monotonic() < deadline - 120:
            model = get_model("xnor-resnet18")
            x0 = jnp.zeros((2, 32, 32, 3), jnp.float32)
            variables = model.init(
                {"params": jax.random.PRNGKey(0)}, x0, train=True
            )
            frozen_fn, info = freeze_xnor_resnet(
                model, variables, input_shape=(32, 32, 3),
                interpret=interp,
            )
            live_fn = jax.jit(
                lambda x: model.apply(variables, x, train=False)
            )
            out["xnor_resnet18"] = {
                "compression": info["compression"],
                **batch_rows(
                    frozen_fn, live_fn, (32, 32, 3),
                    [b for b in args.serving_batches if b <= 64],
                ),
            }
        else:
            out["xnor_resnet18"] = "skipped (bench deadline)"
    except Exception as e:
        out["xnor_resnet18"] = f"failed: {e!r:.300}"

    # -- KV-cache decode ----------------------------------------------
    try:
        if time.monotonic() < deadline - 60:
            from distributed_mnist_bnns_tpu.infer_transformer import (
                _freeze_lm_tensors,
                make_lm_decoder,
            )
            from distributed_mnist_bnns_tpu.models.transformer import (
                BinarizedLM,
            )

            ctx = args.serving_lm_ctx
            model = BinarizedLM(
                vocab=256, max_len=ctx, embed_dim=args.lm_embed_dim,
                depth=args.lm_depth, num_heads=args.lm_heads,
                attention="xla",
            )
            tokens = jnp.zeros((2, ctx), jnp.int32)
            variables = model.init(
                {"params": jax.random.PRNGKey(0)}, tokens, train=False
            )
            frozen = _freeze_lm_tensors(model, variables)
            init, step = make_lm_decoder(frozen, interpret=interp)
            rows = {}
            for b in (1, 8):
                if time.monotonic() > deadline:
                    break
                caches = init(b)
                toks = jnp.zeros((b,), jnp.int32)
                pos = ctx // 2  # steady-state mid-cache decode step
                holder = {"c": caches}

                def one():
                    holder["c"], lp = step(holder["c"], toks, pos)
                    return lp

                lp = one()
                float(jnp.sum(lp))
                dt, _ = _measure(
                    one, lambda r: float(jnp.sum(r)),
                    20, 200, reps, deadline,
                )
                if dt is not None:
                    rows[f"batch_{b}"] = {
                        "tokens_per_sec": round(b / dt, 1),
                        "step_latency_ms": round(dt * 1e3, 4),
                    }
            out["lm_kv_decode"] = {
                "ctx": ctx, "embed_dim": args.lm_embed_dim,
                "depth": args.lm_depth, **rows,
            }
        else:
            out["lm_kv_decode"] = "skipped (bench deadline)"
    except Exception as e:
        out["lm_kv_decode"] = f"failed: {e!r:.300}"
    return out


def _bench_lm_serve(args, deadline):
    """Continuous-batching LM serving benchmark (--lm-serve-bench):
    decode tokens/sec and inter-token latency percentiles at 1/4/8
    concurrent streams through the serve/lm/ engine (paged KV cache,
    iteration-level scheduling), with the decode GEMMs on pre-packed
    1-bit bitplanes vs the same artifact carried as dense fp32 kernels —
    the model-level measurement of PERF.md §3's claim that packed
    weights win exactly the bandwidth-bound single-position regime
    continuous decode lives in.

    Weights are fresh inits (throughput is weight-value-independent);
    the dense variant unpacks each layer's bitplanes into the 'kernel'
    (carried-fp32) marker, so both variants run the SAME engine,
    scheduler and cache — only the GEMM weight format differs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_mnist_bnns_tpu.infer_transformer import (
        _freeze_lm_tensors,
        make_paged_lm_decoder,
    )
    from distributed_mnist_bnns_tpu.models.transformer import BinarizedLM
    from distributed_mnist_bnns_tpu.obs import MetricsRegistry, Telemetry
    from distributed_mnist_bnns_tpu.ops.bitpack import unpack_bits
    from distributed_mnist_bnns_tpu.serve.lm import LMEngine
    from distributed_mnist_bnns_tpu.serve.lm.engine import (
        DECODE_ITERATION_SECONDS,
    )

    interp = jax.default_backend() != "tpu"
    ctx = args.serving_lm_ctx
    model = BinarizedLM(
        vocab=256, max_len=ctx, embed_dim=args.lm_embed_dim,
        depth=args.lm_depth, num_heads=args.lm_heads, attention="xla",
    )
    tokens = jnp.zeros((1, ctx), jnp.int32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, tokens, train=False
    )
    frozen = _freeze_lm_tensors(model, variables)

    def densify(fz):
        """Packed bitplanes -> the carried-fp32 'kernel' marker: the
        dense-weight baseline through the identical serving stack."""
        blocks = []
        for blk in fz["blocks"]:
            nb = dict(blk)
            for key in ("q", "k", "v", "out", "mlp1", "mlp2"):
                layer = blk[key]
                if "wp" in layer:
                    k, n = int(layer["k"]), int(layer["n"])
                    w = unpack_bits(jnp.asarray(layer["wp"]).T, k)[:n].T
                    nb[key] = {"kernel": np.asarray(w),
                               "bias": layer["bias"]}
            blocks.append(nb)
        out = dict(fz)
        out["blocks"] = blocks
        return out

    n_new = max(8, min(64, ctx // 4))
    out = {
        "ctx": ctx, "embed_dim": args.lm_embed_dim,
        "depth": args.lm_depth, "n_new_tokens_per_stream": n_new,
        "interpret_mode": interp,
        # Both variants run with the Pallas serving path armed: the
        # in-kernel page-table-walk attention is common to both rows,
        # so the packed-vs-dense ratio isolates the GEMM weight format
        # (packed bitplanes — popcount carry at decode M, fused
        # bitplane-unpack at prefill/verify M, FUSED_UNPACK_MIN_M —
        # vs carried fp32).
        "kernels": True,
    }

    def run_streams(fz, streams, spec_k=0, kernels=True):
        """One engine at `streams` concurrent staggered requests;
        returns the throughput/latency row (+ spec acceptance).

        The decode window per stream is the widest that fits the
        context after its prompt, and the whole request batch runs
        TWICE on the warm engine, keeping the attempt with the higher
        throughput: host/scheduler jitter is strictly additive, so the
        minimum-wall attempt is the lowest-noise estimator — the same
        reasoning as ``_min_marginal``'s two-length minima. A 16-token
        window behind an 8-36 token prefill measures mostly prefill
        and thread-wakeup noise (ratios swung 0.6-1.3 run to run);
        the wide window makes the row a decode-throughput number."""
        reg = MetricsRegistry()
        tel = Telemetry(None, registry=reg)
        dec = make_paged_lm_decoder(
            fz, slots=streams, page_size=16,
            prefill_chunk=16, interpret=interp, spec_k=spec_k,
            kernels=kernels,
        )
        eng = LMEngine(dec, queue_depth=streams * 2,
                       telemetry=tel).start()
        try:
            rng = np.random.RandomState(streams)
            prompts = [
                rng.randint(0, 256, size=8 + 4 * i).astype(np.int32)
                for i in range(streams)       # staggered lengths
            ]
            longest = max(len(p) for p in prompts)
            # Spec rows keep the narrow window: the K-wide verify
            # dispatch must not be pushed against max_len.
            n_new_row = (
                n_new if spec_k else max(n_new, ctx - longest - 1)
            )
            best = None
            for _attempt in range(2):
                t0 = time.perf_counter()
                reqs = [
                    eng.submit(p, n_new_row, time.monotonic() + 600)
                    for p in prompts
                ]
                done = 0
                for r in reqs:
                    while True:
                        ev = r.events.get(timeout=600)
                        if ev["kind"] == "done":
                            assert ev["status"] == "ok", ev
                            done += ev["n"]
                            break
                wall = time.perf_counter() - t0
                tps = done / wall
                if best is None or tps > best:
                    best = tps
            hist = reg.histogram(DECODE_ITERATION_SECONDS)
            p50 = hist.percentile(50)
            p99 = hist.percentile(99)
            row = {
                "tokens_per_sec": round(best, 1),
                "n_new_per_stream": int(n_new_row),
                "p50_intertoken_ms": (
                    round(p50 * 1e3, 3) if p50 is not None else None
                ),
                "p99_intertoken_ms": (
                    round(p99 * 1e3, 3) if p99 is not None else None
                ),
                "recompiles_post_warmup": eng.recompiles_post_warmup,
            }
            if spec_k:
                rate = eng.spec_acceptance_rate
                row["acceptance_rate"] = (
                    round(rate, 4) if rate is not None else None
                )
            return row
        finally:
            eng.stop()

    variants = {"packed_1bit": frozen, "dense_fp32": densify(frozen)}
    for vname, fz in variants.items():
        if time.monotonic() > deadline - 30:
            out[vname] = "skipped (bench deadline)"
            continue
        rows = {}
        for streams in (1, 4, 8):
            if time.monotonic() > deadline:
                break
            rows[f"streams_{streams}"] = run_streams(fz, streams)
        out[vname] = rows
    pk, dn = out.get("packed_1bit"), out.get("dense_fp32")
    if isinstance(pk, dict) and isinstance(dn, dict):
        # perf-gate floors (lm_packed_speedup_{1,4,8}_streams): packed
        # must beat dense fp32 at EVERY stream count, not just 8.
        for streams in (1, 4, 8):
            sk = f"streams_{streams}"
            if sk in pk and sk in dn and dn[sk]["tokens_per_sec"]:
                out[f"packed_speedup_{streams}_streams"] = round(
                    pk[sk]["tokens_per_sec"]
                    / dn[sk]["tokens_per_sec"], 2,
                )

    # -- self-speculative decoding (SERVING.md "Speculative decoding"):
    # spec-on (packed 1-bit draft + fixed-K bf16 verify) vs the
    # verifier alone (spec_k=1: one bf16 verify dispatch per token —
    # the engine whose OUTPUT spec mode reproduces token-identically)
    # and vs the plain packed engine above.
    spec_k = 4
    try:
        if time.monotonic() < deadline - 30:
            spec = {"spec_k": spec_k}
            for streams in (1, 4):
                if time.monotonic() > deadline:
                    break
                spec[f"streams_{streams}"] = run_streams(
                    frozen, streams, spec_k=spec_k
                )
            s1 = spec.get("streams_1", {})
            if "acceptance_rate" in s1:
                spec["acceptance_rate"] = s1["acceptance_rate"]
            out["spec"] = spec
            # The reference run costs a whole extra engine build +
            # stream: honour the bench deadline like every section.
            if time.monotonic() < deadline - 30:
                out["verifier_alone"] = {
                    "streams_1": run_streams(frozen, 1, spec_k=1),
                }
                v1 = out["verifier_alone"]["streams_1"]["tokens_per_sec"]
                if s1.get("tokens_per_sec") and v1:
                    out["spec_speedup_vs_verifier_1stream"] = round(
                        s1["tokens_per_sec"] / v1, 2,
                    )
                p1 = (pk or {}).get("streams_1", {}).get(
                    "tokens_per_sec"
                )
                if s1.get("tokens_per_sec") and p1:
                    out["spec_speedup_vs_packed_1stream"] = round(
                        s1["tokens_per_sec"] / p1, 2,
                    )
            else:
                out["verifier_alone"] = "skipped (bench deadline)"
        else:
            out["spec"] = "skipped (bench deadline)"
    except Exception as e:
        out["spec"] = f"failed: {e!r:.300}"

    # -- prefix caching (SERVING.md "Prefix caching"): identical-prompt
    # admissions through one engine — the second is a radix hit whose
    # prefill covers only the uncached suffix. The measured claim:
    # prefill time drops on shared-prefix admission.
    try:
        if time.monotonic() < deadline - 30:
            import tempfile

            from distributed_mnist_bnns_tpu.obs import load_events

            tdir = tempfile.mkdtemp(prefix="bench_lm_prefix_")
            tel = Telemetry(tdir)
            dec = make_paged_lm_decoder(
                frozen, slots=1, page_size=16,
                prefill_chunk=16, interpret=interp,
            )
            eng = LMEngine(dec, queue_depth=4, telemetry=tel,
                           prefix_cache=True).start()
            try:
                plen = max(32, min(ctx - n_new - 1, ctx // 2))
                prompt = np.random.RandomState(7).randint(
                    0, 256, size=plen
                ).astype(np.int32)
                for _ in range(2):        # cold admit, then the hit
                    r = eng.submit(
                        prompt, 8, time.monotonic() + 600
                    )
                    while r.events.get(timeout=600)["kind"] != "done":
                        pass
                stats = eng.prefix_cache_stats()
            finally:
                eng.stop()
                tel.close()
            admits = [
                e for e in load_events(os.path.join(
                    tdir, "events.jsonl"
                )) if e["kind"] == "lm_admit"
            ]
            cold, hit = admits[0], admits[1]
            out["prefix"] = {
                "prompt_tokens": int(plen),
                "cached_tokens": hit["cached_tokens"],
                "cold_prefill_ms": cold["prefill_ms"],
                "hit_prefill_ms": hit["prefill_ms"],
                "cold_prefill_tokens": cold["prefill_tokens"],
                "hit_prefill_tokens": hit["prefill_tokens"],
                "prefill_ms_saved_ratio": round(
                    1.0 - hit["prefill_ms"] / max(
                        cold["prefill_ms"], 1e-9
                    ), 4,
                ),
                "cache_entries": stats["entries"],
            }
        else:
            out["prefix"] = "skipped (bench deadline)"
    except Exception as e:
        out["prefix"] = f"failed: {e!r:.300}"
    return out


def _bench_device_costs(args, deadline):
    """Per-program cost-ledger section (--device-costs-bench; ROADMAP
    item 5's MFU slice, OBSERVABILITY.md "Device profiling"): the
    classifier train step is explicitly lowered + compiled, its
    ``cost_analysis``/``memory_analysis`` banked, the cost-model flops
    reconciled against the analytic obs/flops walk (the two agreeing is
    the tested invariant — XLA's model counts optimizer/elementwise
    noise the 3x2xMACs convention excludes, so the ratio sits near but
    above 1), and measured MFU derived from timed dispatches of the
    same jitted program. ``cost_flops`` is deterministic for a fixed
    model/batch/jax version, so the perf gate bands it EXACTLY (like
    the wire bytes); ``mfu_measured`` gets a wide floor."""
    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.obs import peak_for_default_device
    from distributed_mnist_bnns_tpu.obs.costs import extract_costs
    from distributed_mnist_bnns_tpu.obs.flops import mfu as mfu_of
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    bs = min(args.batch_size, 256)
    input_shape = (28, 28, 1)
    trainer = Trainer(
        TrainConfig(
            model=args.model, batch_size=bs, optimizer="adam",
            learning_rate=0.01, backend="bf16", seed=0,
        ),
        input_shape=input_shape,
    )
    key = jax.random.PRNGKey(0)
    images = jax.random.normal(key, (bs, *input_shape), jnp.float32)
    labels = jax.random.randint(key, (bs,), 0, 10)
    compiled = trainer.train_step.lower(
        trainer.state, images, labels, trainer.rng
    ).compile()
    costs = extract_costs(compiled)
    analytic = trainer._step_flops
    # Timed dispatches of the SAME jitted program (the compile above
    # warmed nothing for the jit — pay its own warmup first).
    for _ in range(3):
        trainer.state, m = trainer.train_step(
            trainer.state, images, labels, trainer.rng
        )
    jax.block_until_ready(m)
    steps = 10
    t0 = time.perf_counter()
    for _ in range(steps):
        trainer.state, m = trainer.train_step(
            trainer.state, images, labels, trainer.rng
        )
    jax.block_until_ready(m)
    mean_s = (time.perf_counter() - t0) / steps
    peak, precision = peak_for_default_device()
    out = {
        "program": "train_step",
        "model": args.model,
        "batch_size": bs,
        "cost_flops": costs.get("flops"),
        "cost_bytes_accessed": costs.get("bytes_accessed"),
        "hbm": costs.get("hbm"),
        "analytic_flops": analytic,
        "flops_method": trainer._flops_method,
        "mean_step_ms": round(mean_s * 1e3, 3),
        "mfu_measured": mfu_of(costs.get("flops"), mean_s, peak),
        "mfu_analytic": mfu_of(analytic, mean_s, peak),
        "peak_precision": precision,
    }
    if costs.get("flops") and analytic:
        out["flops_ratio_cost_over_analytic"] = round(
            costs["flops"] / analytic, 4
        )
    if costs.get("reason"):
        out["cost_reason"] = costs["reason"]
    return out


def _bench_cold_start(args, deadline):
    """Cold-start benchmark (--cold-start-bench; PERF.md "Cold start"):
    time-to-first-token for `cli serve` / `cli serve --lm` and
    time-to-first-step for the trainer, COLD store vs WARM store, each
    measured in a fresh subprocess (aot/coldstart.py) with a fresh jax
    persistent compilation cache — the cold run banks the executables
    the warm run then boots from, so the pair is exactly the
    first-deploy vs every-later-deploy comparison the AOT store exists
    for. The banked claim: warm first_s strictly below cold first_s
    for both serving engines."""
    import subprocess
    import tempfile

    work = tempfile.mkdtemp(prefix="bench_cold_")
    store = os.path.join(work, "aot_store")

    # tiny artifacts — cold-start cost is dominated by trace+compile,
    # which these shapes exercise end to end (shared constructor with
    # scripts/aot_smoke.py; the bench sizes its LM up slightly)
    from distributed_mnist_bnns_tpu.aot.coldstart import (
        make_tiny_artifacts,
    )

    cls_artifact, lm_artifact = make_tiny_artifacts(
        work, lm_vocab=64, lm_max_len=64, lm_embed=64,
    )

    def run(mode, artifact, aot):
        env = {
            **os.environ,
            # fresh XLA persistent cache per run: isolate the AOT
            # store's win over the FULL pipeline, not just the compile
            "JAX_COMPILATION_CACHE_DIR": tempfile.mkdtemp(dir=work),
        }
        cmd = [
            sys.executable, "-m",
            "distributed_mnist_bnns_tpu.aot.coldstart",
            "--mode", mode, "--store", store,
        ]
        if artifact:
            cmd += ["--artifact", artifact]
        if not aot:
            cmd += ["--no-aot"]
        t0 = time.perf_counter()
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"coldstart {mode} (aot={aot}) rc {proc.returncode}: "
                f"{proc.stderr[-500:]}"
            )
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
        rec["wall_s"] = round(wall, 3)
        return rec

    section = {"store": store}
    for mode, artifact in (
        ("serve", cls_artifact), ("lm", lm_artifact), ("train", None),
    ):
        if time.monotonic() > deadline - 120:
            section[mode] = "skipped: budget exhausted"
            continue
        _progress(f"cold_start: {mode} cold (store empty, banks)")
        cold = run(mode, artifact, aot=True)   # empty store: miss+bank
        _progress(f"cold_start: {mode} warm (store hit)")
        warm = run(mode, artifact, aot=True)   # same store: hit
        if cold.get("aot_status") != "miss" or \
                warm.get("aot_status") != "hit":
            raise RuntimeError(
                f"cold_start {mode}: expected miss->hit, got "
                f"{cold.get('aot_status')}->{warm.get('aot_status')}"
            )
        section[mode] = {
            "cold_boot_s": round(cold["boot_s"], 3),
            "cold_first_s": round(cold["first_s"], 3),
            "warm_boot_s": round(warm["boot_s"], 3),
            "warm_first_s": round(warm["first_s"], 3),
            "cold_compiles": cold.get("compiles"),
            "warm_compiles": warm.get("compiles"),
            "first_speedup": round(
                cold["first_s"] / max(warm["first_s"], 1e-9), 2
            ),
            "warm_beats_cold": warm["first_s"] < cold["first_s"],
        }
    return section


def main() -> None:
    # Persist compiled executables across processes/windows: a cold
    # remote compile of the train step can eat a whole short hardware
    # window. In main() (not import scope) so `import bench` for its
    # helpers stays side-effect-free.
    cache_dir = enable_persistent_compilation_cache()
    try:
        cache_entries_at_start = len([
            n for n in os.listdir(cache_dir) if not n.startswith(".")
        ])
    except OSError:
        cache_entries_at_start = 0
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=4096)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--reps", type=int, default=3,
                   help="marginal-timing repetitions (minima taken)")
    p.add_argument("--scan-steps", type=int, default=64,
                   help="train steps fused per dispatch for the headline "
                        "measurement (0 = per-step dispatch only)")
    from distributed_mnist_bnns_tpu.ops.xnor_gemm import BACKENDS

    # bf16 is the measured-fastest headline backend for TRAINING: the
    # backward GEMMs (gradients are not +-1) must run bf16 regardless,
    # and an interleaved on-chip A/B (PERF.md, round 4) shows the pure
    # bf16 step beats the mixed int8-forward step by ~12%.
    p.add_argument("--backend", default="bf16", choices=list(BACKENDS))
    p.add_argument("--model", default="bnn-mlp-large")
    p.add_argument("--input-shape", type=int, nargs=3, default=None,
                   metavar=("H", "W", "C"),
                   help="default: (28,28,1); xnor-resnet models get the "
                        "CIFAR shape (32,32,3)")
    p.add_argument("--all-backends", action="store_true",
                   help="also bench the train step on every backend")
    p.add_argument("--no-crossover", action="store_true",
                   help="skip the GEMM-level crossover extras")
    p.add_argument("--budget-s", type=float, default=420.0,
                   help="wall-clock budget: stretch/crossover stages past "
                        "it are skipped (best-effort — an in-flight "
                        "compile cannot be interrupted)")
    p.add_argument("--stretch", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="also bench the xnor-resnet18 CIFAR stretch config "
                        "(BinarizedConv + im2col bit-GEMM)")
    p.add_argument("--stretch-batch-size", type=int, default=256)
    p.add_argument("--epoch-bench", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="also time a reference-sized device-resident epoch "
                        "(one dispatch) on the flagship model")
    p.add_argument("--epoch-bench-images", type=int, default=60000,
                   help="epoch size for --epoch-bench (reference: 60k)")
    p.add_argument("--lm-bench", action="store_true",
                   help="also bench the causal BinarizedLM train step "
                        "(flash attention fwd + Pallas bwd, tokens/sec)")
    p.add_argument("--serving-bench", action="store_true",
                   help="also bench end-to-end frozen-model serving: "
                        "packed img/s at batch 1/8/64 vs live eval, "
                        "KV-decode tokens/s, artifact cold-start latency")
    p.add_argument("--lm-serve-bench", action="store_true",
                   help="also bench continuous-batching LM serving "
                        "(serve/lm/): decode tokens/sec + p99 "
                        "inter-token latency at 1/4/8 concurrent "
                        "streams, packed-bitplane vs dense decode "
                        "weights")
    p.add_argument("--serve-p99-bench", action="store_true",
                   help="also bench classifier request p99 under "
                        "saturation through the real serving engine "
                        "(serve/harness.py): the gateable Tail-at-Scale "
                        "number the perf gate bands (ROADMAP item 5)")
    p.add_argument("--fleet-avail-bench", action="store_true",
                   help="also probe fleet availability under chaos "
                        "(serve/fleet/harness.py): a saturated "
                        "3-replica fleet through the real router has "
                        "one replica chaos-stalled then KILLED "
                        "mid-window; the end-to-end success fraction "
                        "is the perf gate's "
                        "fleet_availability_under_chaos floor")
    p.add_argument("--device-costs-bench", action="store_true",
                   help="per-program HLO cost-ledger section "
                        "(OBSERVABILITY.md 'Device profiling'): "
                        "cost-analysis flops vs the analytic walk for "
                        "the train step, plus measured MFU — the "
                        "perf gate's MFU-floor feed")
    p.add_argument("--cold-start-bench", action="store_true",
                   help="measure cold-store vs warm-store boot: "
                        "time-to-first-token for cli serve and cli "
                        "serve --lm, time-to-first-step for the "
                        "trainer, each in a fresh subprocess against "
                        "the AOT executable store (aot/, PERF.md "
                        "'Cold start')")
    p.add_argument("--comm-bench", action="store_true",  # + comm_fsdp
                   help="also bench the DP gradient exchange: fp32 psum "
                        "vs 1-bit sign/sign_ef compression (wire "
                        "bytes/step + step time per mode; PERF.md "
                        "'Gradient comms')")
    p.add_argument("--comm-batch-size", type=int, default=512,
                   help="global batch for the comm section (rounded up "
                        "to a device multiple)")
    p.add_argument("--comm-steps", type=int, default=20,
                   help="timed steps per comm mode")
    p.add_argument("--serving-lm-ctx", type=int, default=256,
                   help="KV-cache length for the serving decode bench")
    p.add_argument("--serving-batches", type=int, nargs="+",
                   default=[1, 8, 64, 4096],
                   help="batch sizes for the serving bench (the conv "
                        "stretch caps at 64)")
    p.add_argument("--lm-seq-len", type=int, default=1024)
    p.add_argument("--lm-batch-size", type=int, default=8)
    p.add_argument("--lm-depth", type=int, default=4)
    p.add_argument("--lm-embed-dim", type=int, default=256)
    p.add_argument("--lm-heads", type=int, default=4)
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--probe-timeout", type=float, default=90.0,
                   help="seconds per device-responsiveness probe attempt "
                        "(first compile included); 0 skips probing")
    p.add_argument("--probe-budget-s", type=float, default=1500.0,
                   help="total wall-clock budget for probe retries with "
                        "backoff before declaring the endpoint dead "
                        "(sleeps 30s doubling to 480s between attempts)")
    p.add_argument("--events", default=None,
                   help="also mirror the bench record into a telemetry "
                        "JSONL event log at this path (same schema as "
                        "training's --telemetry-dir; OBSERVABILITY.md), "
                        "so bench and training runs are comparable via "
                        "`cli telemetry`. Live-endpoint runs only: the "
                        "dead-endpoint record skips the mirror (its "
                        "manifest would re-dial the dead backend)")
    args = p.parse_args()
    global _PROGRESS_ON
    _PROGRESS_ON = args.verbose

    probe_log = None
    if args.probe_timeout > 0:
        alive, probe_log = _probe_device_retry(
            args.probe_timeout, args.probe_budget_s
        )
        if not alive:
            result = {
                "metric": "train_throughput_mnist_bnn_mlp_large",
                "ts": _utc_now(),
                "value": None, "unit": "images/sec", "vs_baseline": None,
                "note": "device endpoint unresponsive: a 128x128 matmul "
                        f"did not complete in {args.probe_timeout:.0f}s in "
                        f"any of {len(probe_log)} probe subprocesses "
                        f"retried with backoff over "
                        f"{args.probe_budget_s:.0f}s; no TPU measurement "
                        "possible",
                "probe_log": probe_log,
            }
            # The endpoint comes and goes in windows (ENDPOINT_LOG.md).
            # If a full hardware measurement was captured during a live
            # window (the builder saves bench output as
            # BENCH_LOCAL_r*.json), point at the BEST saved record so a
            # dead end-of-round window doesn't erase the hardware
            # evidence. Best-by-value, not newest-by-round: a later
            # round can legitimately bank a weaker headline from a
            # degraded tunnel window (round-5 window #1 probed 7 s for
            # a 128x128 matmul vs 1.8 s in round 4), and the weaker
            # record must not shadow the stronger certified one — the
            # source filename keeps provenance explicit. captured_at
            # prefers the record's own "ts" stamp, falling back to
            # mtime only for records written before the stamp existed.
            import glob

            here = os.path.dirname(os.path.abspath(__file__))
            best = None
            for local in glob.glob(
                    os.path.join(here, "BENCH_LOCAL_r*.json")):
                try:
                    with open(local) as f:
                        rec = json.load(f)
                except Exception:
                    continue
                if rec.get("value") is None:
                    continue  # a saved dead-window record is not evidence
                if rec.get("metric") != result["metric"]:
                    continue  # different benchmark, not this evidence
                if best is None or rec["value"] > best[1].get("value"):
                    best = (local, rec)
            if best is not None:
                local, rec = best
                result["best_hardware_measurement"] = {
                    "source": os.path.basename(local),
                    "metric": rec.get("metric"),
                    "captured_at": rec.get("ts") or _utc_now(
                        os.path.getmtime(local)
                    ),
                    "value": rec.get("value"),
                    "unit": rec.get("unit"),
                    "vs_baseline": rec.get("vs_baseline"),
                    "mfu": rec.get("mfu"),
                    "device": rec.get("device"),
                    "note": "best saved record across this harness's "
                            "live endpoint windows (best-by-value, "
                            "not newest; source file holds the full "
                            "record)",
                }
                # Sections (serving, lm_flash, ...) may have been banked
                # by a different window than the best headline — e.g. a
                # round-5 serving-only window with a weaker tunnel. Carry
                # each section from the newest saved record that has it,
                # so best-by-value headline selection cannot shadow
                # banked section evidence.
                sections = {}
                for local in sorted(
                        glob.glob(os.path.join(
                            here, "BENCH_LOCAL_r*.json"))):
                    try:
                        with open(local) as f:
                            rec2 = json.load(f)
                    except Exception:
                        continue
                    if rec2.get("metric") != result["metric"]:
                        continue
                    for k in SECTION_MERGE_KEYS:
                        if isinstance(rec2.get(k), dict):
                            sections[k] = {
                                "source": os.path.basename(local),
                                # mtime fallback mirrors the best-record
                                # path above: records written before the
                                # "ts" stamp existed must not yield
                                # captured_at: null.
                                "captured_at": rec2.get("ts") or _utc_now(
                                    os.path.getmtime(local)
                                ),
                                **rec2[k],
                            }
                if sections:
                    result["best_hardware_measurement"][
                        "sections"] = sections
            try:
                result["cpu_fallback"] = _cpu_fallback_extras(args)
            except Exception as e:
                result["cpu_fallback"] = f"failed: {e!r:.300}"
            # NO events mirror here: _emit_events touches jax
            # (process_index / jax.devices() for the manifest), and on
            # this dead-endpoint path an in-process backend init can
            # hang forever — uncatchable, burning the window harness's
            # whole timeout on a path engineered to exit promptly. The
            # JSON line is the record; the mirror only exists for runs
            # that measured something.
            print(json.dumps(result), flush=True)
            return
    deadline = time.monotonic() + args.budget_s
    _progress(
        f"headline: model={args.model} backend={args.backend} "
        f"batch={args.batch_size} scan={args.scan_steps} "
        "(first compile may take minutes on a remote backend)")

    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    if args.input_shape is not None:
        input_shape = tuple(args.input_shape)
    elif args.model.startswith("xnor-resnet"):
        input_shape = (32, 32, 3)
    else:
        input_shape = (28, 28, 1)

    key = jax.random.PRNGKey(0)
    images = jax.device_put(jax.random.normal(
        key, (args.batch_size, *input_shape), jnp.float32
    ))
    labels = jax.device_put(
        jax.random.randint(key, (args.batch_size,), 0, 10)
    )

    def make_trainer(backend: str):
        return Trainer(
            TrainConfig(
                model=args.model,
                batch_size=args.batch_size,
                optimizer="adam",
                learning_rate=0.01,
                backend=backend,
                seed=0,
            ),
            input_shape=input_shape,
        )

    def bench_backend(backend: str):
        """Scan-dispatch timing (device-bound); falls back to per-step
        dispatch when --scan-steps 0 or the scan is unmeasurable. Returns
        (per-step seconds, loss, scan_steps actually used: 0 = per-step
        dispatch, trainer) so the output never misattributes the mode."""
        trainer = make_trainer(backend)
        if args.scan_steps > 0:
            dispatches = max(1, -(-args.steps // args.scan_steps))
            dt, loss = _bench_train_scan(
                trainer, args.scan_steps, args.batch_size, input_shape,
                dispatches, args.warmup, args.reps, deadline,
            )
            if dt is not None:
                return dt, loss, args.scan_steps, trainer
            if time.monotonic() > deadline:
                # Budget already consumed by the scan attempt: the per-step
                # fallback would compile + warm a second program past the
                # --budget-s contract. Report unmeasurable instead.
                return None, loss, 0, trainer
        dt, loss = _bench_train_step(
            trainer, images, labels, args.steps, args.warmup, args.reps,
            deadline,
        )
        return dt, loss, 0, trainer

    step_time, last_loss, scan_used, headline_trainer = bench_backend(
        args.backend
    )
    if step_time is None:
        print(json.dumps({
            "metric": "train_throughput_unmeasurable",
            "value": None, "unit": "images/sec", "vs_baseline": None,
            "note": "all timed workloads were below the tunnel jitter "
                    "floor; endpoint too degraded to measure",
        }))
        return
    per_step_dispatch_ms = None
    if scan_used > 0 and time.monotonic() < deadline:
        # Also record the per-step-dispatch time: the scan-vs-dispatch gap
        # is the host/tunnel overhead the device-resident loop removes.
        dispatch_dt, _ = _bench_train_step(
            make_trainer(args.backend), images, labels,
            min(args.steps, 50), args.warmup, args.reps, deadline,
        )
        if dispatch_dt is not None:
            per_step_dispatch_ms = round(dispatch_dt * 1e3, 3)
    ips = args.batch_size / step_time
    _progress(f"headline measured: {ips:.0f} img/s "
              f"(scan_steps={scan_used})")
    # The baseline only describes the flagship model (BASELINE.md covers
    # mnist-dist2.py's bnn-mlp-large); any other model has no reference
    # number to compare against.
    baseline_ips = 7270.0 if args.model == "bnn-mlp-large" else None
    metric_name = (
        "train_throughput_mnist_bnn_mlp_large"
        if args.model == "bnn-mlp-large"
        else f"train_throughput_{args.model.replace('-', '_')}"
    )
    result = {
        "metric": metric_name,
        "ts": _utc_now(),
        # entry count when this run started: >0 means cold-start numbers
        # (e.g. serving first_logit_s) may reflect persistent-cache
        # deserialization rather than a true XLA compile
        "compilation_cache_entries": cache_entries_at_start,
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": (
            round(ips / baseline_ips, 2) if baseline_ips else None
        ),
        "batch_size": args.batch_size,
        "step_time_ms": round(step_time * 1e3, 3),
        # epoch-equivalent only defined for the MNIST flagship (60k images)
        "epoch_time_equiv_s": (
            round(60000.0 / ips, 3) if baseline_ips else None
        ),
        "backend": args.backend,
        "device": str(jax.devices()[0]),
        "loss_finite": math.isfinite(last_loss),
        # 0 = per-step dispatch (scan disabled or fell below the
        # measurement floor); >0 = device-resident scan of that length.
        "scan_steps": scan_used,
    }
    # MFU: achieved model FLOPs/s over the chip's precision-matched MXU
    # peak (int8 pipeline peak for the int8 backend, dense bf16 peak
    # otherwise).
    chip_peak, peak_precision = _chip_peak(jax.devices()[0], args.backend)
    flops_info = _step_flops(headline_trainer, args.batch_size)
    if flops_info is not None:
        step_flops, flops_method = flops_info
        result["mfu"] = _mfu(step_flops, step_time, chip_peak)
        result["mfu_detail"] = {
            "step_flops": step_flops,
            "flops_method": flops_method,
            "model_tflops_per_sec": round(step_flops / step_time / 1e12, 2),
            "chip_peak_tflops": (
                round(chip_peak / 1e12, 1) if chip_peak else None
            ),
            "peak_precision": peak_precision,
            "note": "MFU vs the precision-matched MXU peak for the "
                    "headline backend",
        }
    if probe_log is not None:
        result["probe_attempts"] = len(probe_log)
    if per_step_dispatch_ms is not None:
        # dispatch-bound per-step time vs device-bound scan time: the
        # difference is host/tunnel dispatch latency (see PERF.md).
        result["per_step_dispatch_ms"] = per_step_dispatch_ms
    # Require generous headroom before starting the stretch: its first
    # compile (many BinarizedConv shapes -> Pallas kernels) can take
    # minutes on a remote-compile backend and cannot be interrupted, so
    # the budget is best-effort once a compile is in flight.
    if args.stretch and time.monotonic() < deadline - 240:
        _progress("stretch: xnor-resnet18 CIFAR-shape (bf16)")
        # BASELINE.json stretch config: XNOR-ResNet-18 at CIFAR shape on
        # the measured-fastest backend (bf16 MXU — round 5; PERF.md shows
        # pallas_xnor loses training shapes to bf16 by ~2x), with conv
        # MFU from the analytic jaxpr MAC count. The full backend A/B
        # lives in scripts/bench_stretch_bf16.py.
        try:
            st_trainer = Trainer(
                TrainConfig(
                    model="xnor-resnet18",
                    batch_size=args.stretch_batch_size,
                    optimizer="adam",
                    learning_rate=0.01,
                    backend="bf16",
                    seed=0,
                ),
                input_shape=(32, 32, 3),
            )
            st_images = jax.device_put(jax.random.normal(
                key, (args.stretch_batch_size, 32, 32, 3), jnp.float32
            ))
            st_labels = jax.device_put(jax.random.randint(
                key, (args.stretch_batch_size,), 0, 10
            ))
            st_dt, st_loss = _bench_train_step(
                st_trainer, st_images, st_labels,
                min(args.steps, 30), args.warmup, args.reps, deadline,
            )
            if st_dt is None:
                result["stretch_xnor_resnet18_cifar"] = (
                    "below measurement floor"
                )
            else:
                st_macs = _conv_macs_per_image(
                    st_trainer.model,
                    {"params": st_trainer.state.params,
                     "batch_stats": st_trainer.state.batch_stats},
                    (32, 32, 3),
                )
                result["stretch_xnor_resnet18_cifar"] = {
                    "images_per_sec": round(
                        args.stretch_batch_size / st_dt, 1
                    ),
                    "step_time_ms": round(st_dt * 1e3, 3),
                    "batch_size": args.stretch_batch_size,
                    "backend": "bf16",
                    "loss_finite": math.isfinite(st_loss),
                    "mfu": _mfu(
                        3.0 * 2.0 * st_macs * args.stretch_batch_size,
                        st_dt,
                        _chip_peak(jax.devices()[0], "bf16")[0],
                    ),
                    "flops_method": "analytic_3x_conv_and_dense_from_jaxpr",
                }
        except Exception as e:  # never let the stretch kill the bench line
            result["stretch_xnor_resnet18_cifar"] = f"failed: {e!r:.300}"

    if (
        args.epoch_bench
        and args.model == "bnn-mlp-large"
        and time.monotonic() < deadline - 60
    ):
        try:
            _progress("device_resident_epoch: one-dispatch epoch")
            result["device_resident_epoch"] = _bench_device_epoch(
                args, deadline
            )
        except Exception as e:  # never let the extra kill the bench line
            result["device_resident_epoch"] = f"failed: {e!r:.300}"

    if args.lm_bench and time.monotonic() < deadline - 60:
        try:
            _progress("lm_flash: causal-LM flash train step")
            result["lm_flash"] = _bench_lm(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["lm_flash"] = f"failed: {e!r:.300}"

    if args.serving_bench and time.monotonic() < deadline - 60:
        try:
            _progress("serving: frozen-model end-to-end section")
            result["serving"] = _bench_serving(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["serving"] = f"failed: {e!r:.300}"

    if args.lm_serve_bench and time.monotonic() < deadline - 60:
        try:
            _progress("lm_serve: continuous-batching decode section")
            result["lm_serve"] = _bench_lm_serve(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["lm_serve"] = f"failed: {e!r:.300}"

    if args.serve_p99_bench and time.monotonic() < deadline - 60:
        # Classifier p99-under-saturation through the REAL engine
        # (admission queue + micro-batcher). Lives in the importable
        # serve/harness so the perf gate bands the same measurement
        # this record reports (ROADMAP item 5).
        try:
            _progress("serving_p99: engine saturation-latency section")
            from distributed_mnist_bnns_tpu.serve.harness import (
                serving_p99_section,
            )

            # With an events mirror requested, give the probe's engine
            # its own traced telemetry dir next to the mirror: the perf
            # gate reads the request span trees from it to EXPLAIN a
            # tripped serving band (`cli trace` tail attribution).
            p99_tel = None
            p99_dir = None
            if args.events:
                from distributed_mnist_bnns_tpu.obs import Telemetry

                p99_dir = os.path.join(
                    os.path.dirname(os.path.abspath(args.events)) or ".",
                    "serving_p99",
                )
                p99_tel = Telemetry(
                    p99_dir, heartbeat=False, trace=True
                )
            try:
                result["serving_p99"] = serving_p99_section(
                    interpret=jax.default_backend() != "tpu",
                    telemetry=p99_tel,
                )
                if p99_dir is not None:
                    result["serving_p99"]["events_dir"] = p99_dir
            finally:
                if p99_tel is not None:
                    p99_tel.close()
        except Exception as e:  # never let the extra kill the bench line
            result["serving_p99"] = f"failed: {e!r:.300}"

    if args.fleet_avail_bench and time.monotonic() < deadline - 60:
        # Fleet availability under chaos through the REAL router
        # dispatch policy (serve/fleet/harness.py) — the gateable
        # fleet number (ROADMAP items 1+5; perf gate bands it as
        # fleet_availability_under_chaos with a 0.99 floor).
        try:
            _progress("fleet_availability: router failover-under-kill "
                      "section")
            from distributed_mnist_bnns_tpu.serve.fleet.harness import (
                fleet_availability_section,
            )

            section = fleet_availability_section(
                interpret=jax.default_backend() != "tpu",
            )
            result["fleet_availability"] = section
            _progress(
                "fleet_availability: %s over %s requests (%d control-"
                "plane decisions, %d slo_alerts captured — a perf-gate "
                "trip prints the timeline)" % (
                    section.get("availability"),
                    section.get("requests_total"),
                    len(section.get("decisions") or []),
                    len(section.get("slo_alerts") or []),
                )
            )
        except Exception as e:  # never let the extra kill the bench line
            result["fleet_availability"] = f"failed: {e!r:.300}"

    if args.device_costs_bench and time.monotonic() < deadline - 60:
        try:
            _progress("device_costs: per-program cost-ledger section")
            result["device_costs"] = _bench_device_costs(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["device_costs"] = f"failed: {e!r:.300}"

    if args.comm_bench and time.monotonic() < deadline - 60:
        try:
            _progress("comm: DP gradient-exchange compression section")
            result["comm"] = _bench_comm(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["comm"] = f"failed: {e!r:.300}"
        try:
            _progress("comm_fsdp: compressed-FSDP exchange section")
            result["comm_fsdp"] = _bench_comm_fsdp(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["comm_fsdp"] = f"failed: {e!r:.300}"
        try:
            _progress("comm_hier: hierarchical two-level exchange section")
            result["comm_hier"] = _bench_comm_hier(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["comm_hier"] = f"failed: {e!r:.300}"

    if args.cold_start_bench and time.monotonic() < deadline - 60:
        try:
            _progress("cold_start: AOT store cold-vs-warm boot section")
            result["cold_start"] = _bench_cold_start(args, deadline)
        except Exception as e:  # never let the extra kill the bench line
            result["cold_start"] = f"failed: {e!r:.300}"

    if args.all_backends:
        per_backend = {}
        for b in BACKENDS:
            if b == args.backend:
                per_backend[b] = {
                    "images_per_sec": round(ips, 1),
                    "step_time_ms": round(step_time * 1e3, 3),
                    "scan_steps": scan_used,
                    "mfu": result.get("mfu"),
                }
                continue
            dt, _, b_scan, b_trainer = bench_backend(b)
            if dt is None:
                per_backend[b] = "below measurement floor"
                continue
            b_flops = _step_flops(b_trainer, args.batch_size)
            b_peak, _ = _chip_peak(jax.devices()[0], b)
            per_backend[b] = {
                "images_per_sec": round(args.batch_size / dt, 1),
                "step_time_ms": round(dt * 1e3, 3),
                "scan_steps": b_scan,
                "mfu": _mfu(
                    b_flops[0] if b_flops else None, dt, b_peak
                ),
            }
        result["train_step_per_backend"] = per_backend
    if not args.no_crossover:
        if time.monotonic() > deadline:
            result["crossover"] = "skipped (bench deadline; see PERF.md)"
        else:
            _progress("crossover: GEMM-level backend sweep")
            result["crossover"] = _gemm_crossover(
                jax, jnp, deadline, args.reps
            )
    _progress("sections complete; emitting record")
    # Record first, telemetry mirror second (same ordering rule as the
    # dead-endpoint path: nothing may stand between the measurement and
    # its JSON line).
    print(json.dumps(result), flush=True)
    _emit_events(args.events, result, model=args.model)


if __name__ == "__main__":
    sys.exit(main())
