"""Pallas kernels on real TPU hardware, un-interpreted (VERDICT r2 item 3:
the repo must itself prove the Mosaic lowering it ships — the role cuDNN's
own test suite plays for the reference's nn.functional.linear,
models/binarized_modules.py:80).

Covers the XNOR-popcount GEMM at flagship BNN-MLP shapes, flash attention
at aligned and deliberately awkward (padded) shapes, and the end-to-end
binarized layers on the pallas_xnor backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _pm1(key, shape):
    return jnp.where(
        jax.random.bernoulli(jax.random.PRNGKey(key), 0.5, shape), 1.0, -1.0
    ).astype(jnp.float32)


# Flagship BNN MLP GEMM shapes (784->3072->1536->768->10, bs=64/2048)
FLAGSHIP_SHAPES = [
    (64, 784, 3072),
    (64, 3072, 1536),
    (2048, 1536, 768),
    (2048, 768, 10),
    (100, 123, 77),  # deliberately unaligned M/K/N
]


@pytest.mark.parametrize("m,k,n", FLAGSHIP_SHAPES)
def test_xnor_matmul_on_chip_bit_exact(m, k, n):
    from distributed_mnist_bnns_tpu.ops.xnor_gemm import xnor_matmul

    x = _pm1(m * 7 + 1, (m, k))
    w = _pm1(n * 13 + 2, (k, n))
    got = np.asarray(xnor_matmul(x, w))  # interpret=False: real Mosaic
    want = np.asarray(jnp.dot(x, w, preferred_element_type=jnp.float32))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize(
    "b,h,lq,lk,d,causal",
    [
        (2, 4, 256, 256, 64, False),
        (2, 4, 256, 256, 64, True),
        (1, 2, 512, 512, 128, True),
        (1, 1, 7, 7, 16, False),     # everything unaligned -> fully padded
        (1, 2, 200, 333, 64, False), # unaligned L, Lq != Lk
        (1, 2, 96, 128, 64, True),   # causal with Lq < Lk (offset path)
    ],
)
def test_flash_attention_on_chip_matches_oracle(b, h, lq, lk, d, causal):
    from distributed_mnist_bnns_tpu.ops.flash_attention import (
        _oracle_with_lse,
        flash_attention_with_lse,
    )

    kq, kk_, kv = jax.random.split(jax.random.PRNGKey(lq * 31 + lk), 3)
    q = jax.random.normal(kq, (b, lq, h, d), jnp.float32)
    k = jax.random.normal(kk_, (b, lk, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, lk, h, d), jnp.float32)
    out, lse = flash_attention_with_lse(q, k, v, causal)  # real Mosaic
    want, want_lse = _oracle_with_lse(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(want_lse), atol=2e-5, rtol=2e-5
    )


def test_binarized_dense_pallas_backend_on_chip():
    """BinarizedDense with backend='pallas_xnor' end to end on the chip,
    bit-exact vs the fp32 xla path."""
    from distributed_mnist_bnns_tpu.models import BinarizedDense

    x = jax.random.normal(jax.random.PRNGKey(0), (64, 784))
    ref = BinarizedDense(3072, binarize_input=True, backend="xla")
    variables = ref.init({"params": jax.random.PRNGKey(1)}, x)
    want = ref.apply(variables, x)
    got = BinarizedDense(3072, binarize_input=True, backend="pallas_xnor").apply(
        variables, x
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_binarized_conv_im2col_pallas_backend_on_chip():
    """BinarizedConv on the bitplane path (im2col + pallas GEMM), exact vs
    the xla path — the XNOR-ResNet building block."""
    from distributed_mnist_bnns_tpu.models import BinarizedConv

    x = jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 64))
    ref = BinarizedConv(64, (3, 3), binarize_input=True, backend="xla")
    variables = ref.init({"params": jax.random.PRNGKey(1)}, x)
    want = ref.apply(variables, x)
    got = BinarizedConv(
        64, (3, 3), binarize_input=True, backend="pallas_xnor"
    ).apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=0
    )


def test_prepacked_xnor_matmul_on_chip():
    """The inference fast path (pre-packed weights) un-interpreted on the
    chip at a bandwidth-bound shape."""
    from distributed_mnist_bnns_tpu.ops import prepack_weights
    from distributed_mnist_bnns_tpu.ops.xnor_gemm import xnor_matmul_packed

    x = _pm1(3, (8, 8192))
    w = _pm1(4, (8192, 4096))
    wp, k, n = prepack_weights(w)
    got = np.asarray(xnor_matmul_packed(x, wp, k, n))
    want = np.asarray(jnp.dot(x, w, preferred_element_type=jnp.float32))
    np.testing.assert_array_equal(got, want)


def test_fused_sign_epilogue_on_chip():
    """xnor_matmul_packed_sign un-interpreted: the GEMM + bias +
    BN-threshold-sign epilogue must lower through Mosaic and stay exact
    vs the unfused pair, including a partial final K chunk (K=4160 —
    the round-4 grid-truncation regression) and g<0 / g==0 columns."""
    from distributed_mnist_bnns_tpu.infer import (
        _bn_sign_epilogue,
        _bn_sign_fn,
    )
    from distributed_mnist_bnns_tpu.ops import prepack_weights
    from distributed_mnist_bnns_tpu.ops.xnor_gemm import (
        xnor_matmul_packed,
        xnor_matmul_packed_sign,
    )

    for m, k, n in ((8, 3072, 1536), (8, 4160, 256)):
        x = _pm1(5, (m, k))
        w = _pm1(6, (k, n))
        wp, kk, nn_ = prepack_weights(w)
        bias = np.random.RandomState(7).randn(n).astype(np.float32)
        g = np.linspace(-1.0, 1.0, n).astype(np.float32)
        g[n // 2] = 0.0
        bn_params = {
            "scale": jnp.asarray(g),
            "bias": jnp.asarray(
                np.random.RandomState(8).randn(n).astype(np.float32)
            ),
        }
        bn_stats = {
            "mean": jnp.asarray(
                np.random.RandomState(9).randn(n).astype(np.float32) * 8
            ),
            "var": jnp.asarray(
                np.abs(np.random.RandomState(10).randn(n)).astype(
                    np.float32
                ) + 0.5
            ),
        }
        a, t = _bn_sign_epilogue(bn_params, bn_stats)
        got = np.asarray(
            xnor_matmul_packed_sign(x, wp, kk, nn_, a, t, bias)
        )
        want = np.asarray(
            _bn_sign_fn(bn_params, bn_stats)(
                xnor_matmul_packed(x, wp, kk, nn_) + bias
            )
        )
        np.testing.assert_array_equal(got, want, err_msg=f"{(m, k, n)}")

        # the affine+clip epilogue variant on the same operands
        from distributed_mnist_bnns_tpu.infer import (
            _bn_affine_fn,
            _bn_affine_params,
        )
        from distributed_mnist_bnns_tpu.ops.xnor_gemm import (
            xnor_matmul_packed_affine,
        )

        aa, cc = _bn_affine_params(bn_params, bn_stats)
        got_a = np.asarray(
            xnor_matmul_packed_affine(x, wp, kk, nn_, aa, cc, bias)
        )
        want_a = np.asarray(jnp.clip(
            _bn_affine_fn(bn_params, bn_stats)(
                xnor_matmul_packed(x, wp, kk, nn_) + bias
            ), -1.0, 1.0,
        ))
        np.testing.assert_allclose(
            got_a, want_a, atol=1e-6, rtol=1e-6, err_msg=f"{(m, k, n)}"
        )


def test_bnn_vit_flash_forward_on_chip():
    """BinarizedTransformer with attention='flash' (real Mosaic lowering)
    matches its attention='xla' twin on identical params — the model-level
    proof that the flash kernel composes with the binarized stack on
    hardware.

    Per the repo numerics policy (tests/test_transformer.py:176): compare
    the *pre-sign* attn_core intermediates, not end-to-end logits —
    downstream binarized layers sign() the attention output, so few-ulp
    kernel differences legitimately flip near-zero bits and final logits
    are not a meaningful equality target."""
    from distributed_mnist_bnns_tpu.models import BinarizedTransformer

    xla = BinarizedTransformer(
        depth=1, embed_dim=128, num_heads=4, attention="xla", backend="bf16"
    )
    flash = BinarizedTransformer(
        depth=1, embed_dim=128, num_heads=4, attention="flash",
        backend="bf16",
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 28, 28, 1), jnp.float32)
    variables = xla.init(
        {"params": jax.random.PRNGKey(1), "dropout": jax.random.PRNGKey(2)},
        x,
        train=False,
    )

    def attn_cores(model):
        out, state = jax.jit(
            lambda v, x: model.apply(
                v, x, train=False, mutable=["intermediates"]
            )
        )(variables, x)
        caps = jax.tree.leaves(state["intermediates"])
        assert len(caps) == 1  # one attn_core sow for the single block
        assert np.isfinite(np.asarray(out)).all()
        return np.asarray(caps[0])

    got, want = attn_cores(flash), attn_cores(xla)
    # Tolerance is hardware-scaled, not the fp32-level 5e-4 the interpret
    # path satisfies: on a real chip BOTH attention paths feed the MXU,
    # which rounds fp32 operands to bf16 under jax's default matmul
    # precision, and the two contraction schedules (blockwise online
    # softmax vs one-shot) accumulate those roundings differently. The
    # divergence bound is a few bf16 ulps of the tensor scale
    # (eps_bf16 = 2^-8 ~ 3.9e-3; measured max |diff| ~ 0.07 at scale ~28,
    # i.e. ~0.6 ulp). atol = 1e-2 * scale keeps the assertion meaningful
    # (an indexing or masking bug shifts values by O(scale), 100x above).
    scale = float(np.abs(want).max())
    np.testing.assert_allclose(got, want, atol=1e-2 * scale, rtol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_kernels_on_chip(causal):
    """The Pallas backward kernel pair (dq and dk/dv), un-interpreted on
    real hardware, against the fp32 oracle VJP — including the lse
    cotangent (the ring-merge weight gradient)."""
    import importlib

    fa = importlib.import_module(
        "distributed_mnist_bnns_tpu.ops.flash_attention"
    )
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    b, l, h, d = 2, 256, 4, 64
    q = jax.random.normal(ks[0], (b, l, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, l, h, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, l, h, d), jnp.float32)

    def loss_flash(q, k, v):
        out, lse = fa.flash_attention_with_lse(q, k, v, causal, False)
        return (out ** 2).sum() + (lse * 0.3).sum()

    def loss_ref(q, k, v):
        out, lse = fa._oracle_with_lse(q, k, v, causal)
        return (out ** 2).sum() + (lse * 0.3).sum()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, want in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(want), atol=2e-3, rtol=2e-3
        )
