"""Hardware-gated tests: unlike tests/ (pinned to a virtual CPU mesh),
this suite runs on the real TPU chip and is skipped entirely elsewhere.

Run with plain ``python -m pytest tests_tpu -q`` — no env pinning — so the
platform resolution matches what bench.py sees.
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() != "tpu":
        skip = pytest.mark.skip(reason="requires a real TPU chip")
        for item in items:
            item.add_marker(skip)
