"""Hardware-gated tests: unlike tests/ (pinned to a virtual CPU mesh),
this suite runs on the real TPU chip and is skipped entirely elsewhere.

Run with plain ``python -m pytest tests_tpu -q`` — no env pinning — so the
platform resolution matches what bench.py sees.

Endpoint-flake tolerance: the remote-TPU tunnel can hang dispatches
indefinitely (a hung in-process jax call cannot be interrupted, and even
``jax.default_backend()`` initializes the backend). The skip decision is
therefore made from a CHILD process with a hard timeout — the same
pattern bench.py's device probe uses — so a dead endpoint skips the
suite instead of hanging collection.
"""

from __future__ import annotations

import subprocess
import sys

import pytest

_PROBE = (
    "import os;"
    "from distributed_mnist_bnns_tpu.utils.platform import pin_platform;"
    "p = os.environ.get('JAX_PLATFORMS');"
    "_ = pin_platform(p) if p else None;"
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((128, 128));"
    "print(float(jnp.sum(jnp.dot(x, x))));"
    "print('BACKEND=' + jax.default_backend())"
)


def _probe_backend(timeout_s: float = 120.0):
    """The default backend name if a probe matmul completes in time, else
    None (endpoint hung/unreachable). A probe that CRASHES (import error,
    broken install) is not an endpoint flake — re-raise with the child's
    stderr so a healthy-hardware misconfiguration fails loudly instead of
    silently skipping the whole suite."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE],
            timeout=timeout_s, check=True, capture_output=True, text=True,
        ).stdout
    except subprocess.TimeoutExpired:
        return None
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"tests_tpu backend probe crashed (rc={e.returncode}) — not "
            f"an endpoint timeout:\n{e.stderr}"
        ) from None
    for line in out.splitlines():
        if line.startswith("BACKEND="):
            return line.split("=", 1)[1].strip()
    return None


def pytest_collection_modifyitems(config, items):
    if not items:
        return
    backend = _probe_backend()
    if backend is None:
        skip = pytest.mark.skip(
            reason="TPU endpoint unresponsive (probe matmul timed out "
                   "in a subprocess)"
        )
    elif backend != "tpu":
        skip = pytest.mark.skip(reason="requires a real TPU chip")
    else:
        return
    for item in items:
        item.add_marker(skip)
