"""Round-3 dispatch modes on real TPU hardware (VERDICT r3 item 10):
scan-fused train steps, device-resident epochs and eval, and the packed
1-bit inference path — certified on-chip, not only on the CPU mesh.

Numerics policy (tests/README + memory): exact-trajectory comparisons
(scan vs per-step, device-data vs streaming) hold bit-tight because the
op order is identical; live-vs-frozen comparisons cross different
compiled programs, so assertions target exact integer aggregates and
high prediction agreement instead of logit equality."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _data(n_train=512, n_test=256, seed=0):
    from distributed_mnist_bnns_tpu.data.common import (
        ImageClassData,
        synthetic_blobs,
    )

    tr_x, tr_y, te_x, te_y = synthetic_blobs(
        (28, 28, 1), n_train, n_test, seed=seed
    )
    return ImageClassData(
        tr_x.astype(np.float32) / 255.0, tr_y,
        te_x.astype(np.float32) / 255.0, te_y,
    )


def _trainer(**kw):
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    cfg = dict(
        model="bnn-mlp-small", model_kwargs={"infl_ratio": 1},
        epochs=1, batch_size=64, optimizer="adam", learning_rate=0.01,
        backend="bf16", seed=0,
    )
    cfg.update(kw)
    return Trainer(TrainConfig(**cfg))


def test_scan_epoch_matches_per_step_on_chip():
    """scan_steps>1 fuses the same step body into one program: identical
    op order, so the on-chip trajectory must match per-step dispatch to
    float tolerance."""
    data = _data()
    t_step = _trainer()
    t_scan = _trainer(scan_steps=4)
    h_step = t_step.fit(data)
    h_scan = t_scan.fit(data)
    assert np.isfinite(h_scan[0]["train_loss"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)),
            rtol=2e-5, atol=2e-5,
        ),
        t_step.state.params, t_scan.state.params,
    )
    assert h_scan[0]["test_acc"] == h_step[0]["test_acc"]


def test_device_resident_epoch_and_eval_on_chip():
    """device_data=True: ONE dispatch per epoch over the resident
    dataset, and the one-dispatch masked eval; trajectory and exact eval
    aggregates must match the streaming path."""
    data = _data()
    t_stream = _trainer()
    t_dev = _trainer(device_data=True)
    h_stream = t_stream.fit(data)
    h_dev = t_dev.fit(data)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)),
            rtol=2e-5, atol=2e-5,
        ),
        t_stream.state.params, t_dev.state.params,
    )
    # correct-count aggregates are integers: exact equality required
    assert h_dev[0]["test_acc"] == h_stream[0]["test_acc"]
    assert h_dev[0]["test_acc_top5"] == h_stream[0]["test_acc_top5"]


def test_packed_inference_on_chip_latency_and_agreement():
    """The frozen 1-bit serving path (real Mosaic packed kernel): runs
    on-chip, agrees with the live model on essentially every prediction
    (threshold ties across different compiled programs are measure-zero
    but not impossible — exact logit equality is not the contract), and
    the bandwidth-bound small-batch latency is recorded."""
    from distributed_mnist_bnns_tpu.infer import freeze_bnn_mlp
    from distributed_mnist_bnns_tpu.models.mlp import bnn_mlp_small

    model = bnn_mlp_small(backend="bf16")
    data = _data()
    x = jnp.asarray(data.test_images[:128])
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x[:1], train=True,
    )
    frozen_fn, info = freeze_bnn_mlp(model, variables)
    live = np.asarray(
        model.apply(variables, x, train=False)
    )
    packed = np.asarray(frozen_fn(x))
    assert packed.shape == live.shape
    assert np.isfinite(packed).all()
    agreement = float(
        (packed.argmax(-1) == live.argmax(-1)).mean()
    )
    assert agreement >= 0.99, agreement
    # total compression is first-layer-dominated for the 192-wide model:
    # the fp32 passthrough 784x192 kernel stays 4 bytes/param, so the
    # whole-artifact ratio lands ~1.47 (tests/test_infer.py:42-44); the
    # >5x ratios belong to the conv families whose hidden weights dominate
    assert info["compression"] > 1.4

    # latency smoke: small-batch packed inference, host-fetch synced
    small = x[:8]
    frozen_fn(small).block_until_ready()  # compile
    t0 = time.perf_counter()
    reps = 50
    for _ in range(reps):
        out = frozen_fn(small)
    float(jnp.sum(out))  # host fetch = true sync through the tunnel
    dt = (time.perf_counter() - t0) / reps
    print(f"packed bs=8 latency {dt * 1e3:.3f} ms/call")
    assert dt < 5.0  # sanity only: tunnel jitter dominates small calls
