"""Round-4 transformer serving on real TPU hardware: the frozen vit
forward (packed Mosaic kernels, un-interpreted) and the KV-cache LM
decoder certified on-chip.

Numerics policy (tests/README): live-vs-frozen crosses different compiled
programs, so assertions target prediction agreement, not logit equality;
incremental-vs-full decoding shares one artifact and one kernel path, so
its log-probs are compared with a bf16-scale tolerance."""

import time

import jax
import jax.numpy as jnp
import numpy as np


def test_frozen_vit_on_chip_agreement():
    """Frozen packed vit runs the real (non-interpret) bitplane kernels
    and agrees with the live model's predictions."""
    from distributed_mnist_bnns_tpu.infer_transformer import freeze_bnn_vit
    from distributed_mnist_bnns_tpu.models.transformer import bnn_vit_tiny

    # backend="xla": fp32 patch embed in both live and frozen graphs
    # (the bf16 default casts raw pixels — tests/test_infer_transformer).
    model = bnn_vit_tiny(attention="xla", backend="xla")
    x = jax.random.normal(jax.random.PRNGKey(3), (64, 28, 28, 1))
    variables = model.init({"params": jax.random.PRNGKey(0)}, x[:1])
    frozen_fn, info = freeze_bnn_vit(model, variables)  # interpret=False
    live = np.asarray(model.apply(variables, x, train=False))
    packed = np.asarray(frozen_fn(x))
    assert np.isfinite(packed).all()
    # No BN->threshold folding in this family (LN stays live), and ±1
    # GEMMs are exact in both programs — unlike the MLP's tie-prone
    # threshold compare, log-probs here should agree to float noise.
    np.testing.assert_allclose(packed, live, atol=5e-3, rtol=5e-3)
    assert info["compression"] > 5


def test_lm_kv_decoder_on_chip():
    """KV-cache incremental decoding on the real chip: matches the
    full-window frozen forward position by position (same artifact, same
    packed kernels) and records the per-token decode latency."""
    from distributed_mnist_bnns_tpu.infer_transformer import (
        _build_transformer_apply,
        _freeze_lm_tensors,
        make_lm_decoder,
    )
    from distributed_mnist_bnns_tpu.models.transformer import BinarizedLM

    model = BinarizedLM(
        vocab=64, max_len=32, embed_dim=128, depth=2, num_heads=4,
        attention="xla", backend="xla",
    )
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 32), 0, 64)
    variables = model.init({"params": jax.random.PRNGKey(0)}, tokens)
    frozen = _freeze_lm_tensors(model, variables)

    full = np.asarray(_build_transformer_apply(frozen, False)(tokens))
    init, step = make_lm_decoder(frozen)
    caches = init(tokens.shape[0])
    for t in range(8):  # prefix is enough on-chip (compile cost dominates)
        caches, lp = step(caches, tokens[:, t], t)
        np.testing.assert_allclose(
            np.asarray(lp), full[:, t], atol=5e-3, rtol=5e-3,
        )

    # per-token decode latency (one single-position forward per token)
    t0 = time.perf_counter()
    reps = 20
    for i in range(reps):
        caches, lp = step(caches, tokens[:, 8], 8 + (i % 4))
    float(jnp.sum(lp))  # host fetch = true sync through the tunnel
    dt = (time.perf_counter() - t0) / reps
    print(f"kv-decode per-token latency {dt * 1e3:.3f} ms")
    assert dt < 5.0  # sanity only: tunnel jitter dominates small calls


def test_qnn_int8_serving_on_chip():
    """The k-bit QNN's int8 x int8 -> int32 serving GEMMs on the real
    MXU int8 pipeline: frozen predictions agree with the live fp32
    forward (exact integer accumulation vs fp32 summation noise)."""
    from distributed_mnist_bnns_tpu.infer_qnn import freeze_qnn_mlp
    from distributed_mnist_bnns_tpu.models.mlp import QnnMLP

    model = QnnMLP(hidden=(256, 128, 64))
    x = jax.random.normal(jax.random.PRNGKey(3), (64, 28, 28, 1))
    variables = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        x[:1], train=True,
    )
    live = np.asarray(model.apply(variables, x, train=False))
    frozen_fn, info = freeze_qnn_mlp(model, variables)
    got = np.asarray(frozen_fn(x))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, live, atol=5e-3, rtol=5e-3)
    assert info["compression"] == 4.0
