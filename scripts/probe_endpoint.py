"""Background TPU-endpoint availability probe (evidence for ENDPOINT_LOG.md).

Appends one JSON line per probe to the path given as argv[1] (default
endpoint_probes.jsonl). Each probe reuses bench.py's ``_device_responsive``
— a subprocess running a 128x128 matmul under a hard timeout — with
``JAX_PLATFORMS`` forced to the remote-TPU platform (``axon``) so a CPU
fallback can never be logged as a live endpoint. Run it nohup'd during
build sessions so chip-availability windows (and outages) are documented
wall-to-wall; fold the resulting lines into ENDPOINT_LOG.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (the repo-root harness; shares its probe)


def probe_once(timeout_s: float) -> dict:
    t0 = time.time()
    alive = bench._device_responsive(timeout_s)
    return {
        "ts": bench._utc_now(),
        "alive": alive,
        "probe_s": round(time.time() - t0, 1),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("out", nargs="?", default="endpoint_probes.jsonl")
    p.add_argument("--interval-s", type=float, default=600.0)
    p.add_argument("--timeout", type=float, default=90.0)
    p.add_argument("--count", type=int, default=0,
                   help="number of probes (0 = run forever)")
    p.add_argument("--platform", default="axon",
                   help="JAX platform the probe subprocess pins (the "
                        "remote-TPU plugin registers as 'axon')")
    args = p.parse_args()
    # _device_responsive's child honors JAX_PLATFORMS via pin_platform;
    # force it here so the probe answers "is the TPU endpoint up", not
    # "does any backend work".
    os.environ["JAX_PLATFORMS"] = args.platform
    n = 0
    while True:
        rec = probe_once(args.timeout)
        with open(args.out, "a") as f:
            f.write(json.dumps(rec) + "\n")
        n += 1
        if args.count and n >= args.count:
            break
        time.sleep(max(0.0, args.interval_s - rec["probe_s"]))


if __name__ == "__main__":
    main()
