"""Serve smoke (tier-1 / CI): the resilient server must survive chaos.

The serving mirror of scripts/chaos_smoke.py: exports a tiny bnn-mlp
artifact, starts `cli serve` as a real subprocess with a chaos spec
injecting backend errors and stalls, hammers it with concurrent
requests at saturation, hot-reloads the artifact mid-traffic (responses
must be bitwise identical for unchanged weights), then sends SIGTERM
and requires a graceful drain with **exit 0**. Asserts from the obs
event log that the server shed explicitly (never queue collapse), the
circuit breaker opened AND closed again, and the drain flushed
(SERVING.md "Live serving", RESILIENCE.md).

Tracing rides the whole scenario (``--trace``, OBSERVABILITY.md
"Tracing"): every completed request must leave a CLOSED span tree
joined to its ``request`` event by id (root + resolvable children), a
client-minted ``x-jg-trace`` context must be adopted by the server,
and ``cli trace --export`` must render Perfetto-loadable
Chrome-trace JSON from the same log.

Usage: python scripts/serve_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAOS_SPEC = (
    "infer_error@step=4,times=3"            # batches 4-6: breaker trips
    ";infer_slow@step=10,times=2,delay_s=0.3"  # stalls: queue backs up
)
EXPECTED_KINDS = (
    "request", "shed", "breaker_open", "breaker_close", "drain",
    "fault_injected",
)
HAMMER_THREADS = 10
HAMMER_SECONDS = 4.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None,
                        help="work dir (default: a fresh temp dir)")
    parser.add_argument("--keep", action="store_true",
                        help="keep the work dir for inspection")
    args = parser.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="serve_smoke_")
    tel_dir = os.path.join(work, "telemetry")
    artifact = os.path.join(work, "model_packed.msgpack")

    import jax

    from distributed_mnist_bnns_tpu.infer import export_packed
    from distributed_mnist_bnns_tpu.models import bnn_mlp_small
    from distributed_mnist_bnns_tpu.obs import load_events
    from distributed_mnist_bnns_tpu.serve import client as sc

    model = bnn_mlp_small(backend="xla")
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 28, 28, 1))
    variables = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        x, train=True,
    )
    export_packed(model, variables, artifact)

    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
            "serve",
            "--artifact", artifact,
            "--port", str(port),
            "--batch-size", "8",
            "--queue-depth", "4",
            "--deadline-ms", "400",
            "--stall-timeout-s", "0.15",
            "--breaker-threshold", "3",
            "--breaker-reset-s", "0.4",
            "--telemetry-dir", tel_dir,
            "--trace",
            "--chaos", CHAOS_SPEC,
            "--interpret",
            "--log-file", os.path.join(work, "serve.log"),
        ],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    )

    failures = []
    try:
        # jax import + warmup compile make startup slow on CI runners
        for _ in range(240):
            try:
                if sc.healthz(base, timeout=2)[0] == 200:
                    break
            except OSError:
                pass
            if proc.poll() is not None:
                print(f"FAIL: server died at startup (rc {proc.returncode})",
                      file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print("FAIL: server never became healthy", file=sys.stderr)
            return 1

        rng_imgs = [[[[0.1 * ((i + j) % 7)] for j in range(28)]
                     for i in range(28)]]

        codes = []
        lock = threading.Lock()
        stop_at = time.monotonic() + HAMMER_SECONDS

        def hammer(tid: int) -> None:
            while time.monotonic() < stop_at:
                try:
                    code, _ = sc.predict(
                        base, rng_imgs * 2, deadline_ms=250, timeout=10
                    )
                except OSError as e:
                    code = -1
                    print(f"hammer[{tid}]: transport error {e}",
                          file=sys.stderr)
                with lock:
                    codes.append(code)
                time.sleep(0.01)

        threads = [
            threading.Thread(target=hammer, args=(i,))
            for i in range(HAMMER_THREADS)
        ]
        for t in threads:
            t.start()

        # mid-traffic hot reload + bitwise identity probe; the before-
        # probe also exercises the x-jg-trace client half (minted
        # context, server must adopt it — asserted from the log below)
        from distributed_mnist_bnns_tpu.obs import mint_context

        probe_ctx = mint_context()
        time.sleep(HAMMER_SECONDS / 2)
        probe_before = sc.predict(base, rng_imgs, deadline_ms=5000,
                                  timeout=10, trace=probe_ctx)
        reload_code, _ = sc.reload_artifact(base, timeout=60)
        probe_after = sc.predict(base, rng_imgs, deadline_ms=5000,
                                 timeout=10)
        for t in threads:
            t.join(timeout=60)
        if any(t.is_alive() for t in threads):
            failures.append("hammer thread hung (deadline-less wait)")
        if reload_code != 200:
            failures.append(f"hot reload returned {reload_code}")
        if probe_before[0] == probe_after[0] == 200:
            if probe_before[1] != probe_after[1]:
                failures.append(
                    "responses not bitwise identical across hot reload"
                )
        else:
            failures.append(
                f"reload probes failed: {probe_before[0]}/{probe_after[0]}"
            )

        by_code = {c: codes.count(c) for c in sorted(set(codes))}
        if -1 in by_code:
            failures.append(
                f"{by_code[-1]} transport-level failures (shedding must "
                "be an explicit HTTP response)"
            )
        if not by_code.get(200):
            failures.append(f"no request ever succeeded: {by_code}")

        # graceful drain: SIGTERM -> flush -> exit 0
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            rc = proc.wait()
            failures.append("server did not drain within 60s of SIGTERM")
        if rc != 0:
            failures.append(f"server exited {rc} after SIGTERM (want 0)")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    events = load_events(os.path.join(tel_dir, "events.jsonl"))
    kinds = {e["kind"] for e in events}
    for kind in EXPECTED_KINDS:
        if kind not in kinds:
            failures.append(f"event log is missing a {kind!r} event")
    sheds = [e for e in events if e["kind"] == "shed"]
    if not any(e.get("reason") == "queue_full" for e in sheds):
        failures.append(
            "saturation never shed on the bounded queue (reasons: "
            f"{sorted({e.get('reason') for e in sheds})})"
        )
    drains = [e for e in events if e["kind"] == "drain"]
    if drains and not drains[-1].get("flushed"):
        failures.append("drain did not flush in-flight work")

    # -- tracing acceptance (OBSERVABILITY.md "Tracing") ----------------
    from distributed_mnist_bnns_tpu.obs.trace import unresolved_parents

    spans = [e for e in events if e["kind"] == "span"]
    if not spans:
        failures.append("tracing was enabled but no span events landed")
    roots = {}
    for s in spans:
        if s.get("span_kind") == "request":
            rid = (s.get("attrs") or {}).get("id")
            if rid is not None:
                roots[rid] = s
    req_events = [e for e in events if e["kind"] == "request"]
    missing = [e["id"] for e in req_events if e["id"] not in roots]
    if missing:
        failures.append(
            f"{len(missing)} completed request(s) have no root span "
            f"(e.g. {missing[:3]}) — every admitted request must leave "
            "a closed span tree"
        )
    parents = {(s.get("trace"), s.get("parent")) for s in spans}
    admitted = {e["id"] for e in req_events}
    # Shed-at-admission roots are legitimately leaf-only (the request
    # never entered the engine); every ADMITTED request must decompose.
    childless = [
        rid for rid, s in roots.items()
        if rid in admitted
        and (s.get("trace"), s.get("span")) not in parents
    ]
    if childless:
        failures.append(
            f"{len(childless)} request root span(s) have no children "
            f"(e.g. {childless[:3]}) — admit->queue->dispatch->respond "
            "must decompose the request"
        )
    broken = unresolved_parents(spans)
    if broken:
        failures.append(
            f"{len(broken)} span(s) reference a parent missing from "
            "the log — span trees must close"
        )
    if not any(s.get("trace") == probe_ctx.trace_id for s in spans):
        failures.append(
            "the client-minted x-jg-trace context was not adopted "
            "(no span carries its trace id)"
        )
    if not any(s.get("span_kind") == "stall" for s in spans):
        failures.append(
            "chaos stalls fired but no stall span landed — fault->"
            "latency causality must be trace-visible"
        )
    # Perfetto-loadable export through the real CLI
    export_path = os.path.join(work, "chrome_trace.json")
    cli = subprocess.run(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
         "trace", tel_dir, "--export", export_path],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if cli.returncode != 0:
        failures.append(f"cli trace failed: {cli.stderr[-300:]}")
    else:
        try:
            with open(export_path) as f:
                chrome = json.load(f)
            assert chrome["traceEvents"], "empty traceEvents"
            for ev in chrome["traceEvents"]:
                assert ev["ph"] in ("X", "M"), ev
                assert {"name", "pid", "tid"} <= set(ev), ev
                if ev["ph"] == "X":
                    assert ev["dur"] >= 0 and "ts" in ev, ev
        except (OSError, ValueError, KeyError, AssertionError) as e:
            failures.append(f"Chrome-trace export invalid: {e!r}")

    summary = {
        "responses_by_code": by_code,
        "events": {
            k: sum(1 for e in events if e["kind"] == k)
            for k in EXPECTED_KINDS
        },
        "spans": len(spans),
        "request_span_trees": len(roots),
        "drain": drains[-1] if drains else None,
        "ok": not failures,
    }
    print(json.dumps(summary, indent=2, default=str))
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not args.keep and args.dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
