"""Fleet smoke (CI): the multi-replica serving fleet must hide chaos.

The fleet mirror of scripts/serve_smoke.py (SERVING.md "Fleet"):
exports a tiny artifact, banks it in a fresh AOT store, then runs a
REAL ``cli fleet`` — 3 ``cli serve`` replica subprocesses booted
``--aot`` with chaos stalls + backend errors scripted into every
replica — and hammers the ROUTER with the retrying client at
saturation while the scenario unfolds:

  * replica sheds + breaker trips happen (asserted from replica event
    logs) but NO client request fails: retry/failover absorbs them;
  * one replica is SIGKILL'd mid-traffic — the supervisor respawns it
    from the warm AOT store (router /healthz must show it back with
    ``aot: hit`` and ``recompiles_post_boot == 0``), again with zero
    failed client requests;
  * a mid-traffic rolling reload of a byte-identical artifact promotes
    through canary → fleet with responses BITWISE unchanged;
  * a forced-bad-artifact rollout trips the canary gate and rolls the
    whole fleet back (still serving 200s afterward);
  * a client-minted ``x-jg-trace`` context is adopted by the router
    AND the replica that served it — one trace id across both event
    logs (the every-hop-joins-one-trace contract);
  * the fleet-merged ``/metrics`` reconciles EXACTLY with the sum of
    the replicas' own ``/metrics`` counters once traffic quiesces;
  * every supervisor respawn and router breaker transition left a
    ``decision`` audit event, and `cli trace` over the router dir plus
    the replica dirs stitches at least one joined request tree;
  * SIGTERM drains the whole fleet, exit 0;
  * phase two (ISSUE 16): a min fleet (1 replica, second process) with
    1 s/3 s SLO windows — SIGKILLing the sole replica must OPEN the
    availability ``slo_alert`` (every request 503s until the respawn)
    and the respawn must CLOSE it, all visible in ``/healthz``'s
    ``slo_open_alerts`` and the event log.

Usage: python scripts/fleet_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAOS_SPEC = (
    "infer_slow@step=6,times=2,delay_s=0.3"   # straggler batches
    ";infer_error@step=12,times=3"            # breaker trip + close
)
HAMMER_THREADS = 8


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _healthz(base: str, timeout: float = 5.0) -> dict:
    import urllib.request

    with urllib.request.urlopen(base + "/healthz",
                                timeout=timeout) as resp:
        return json.loads(resp.read())


def _wait(predicate, budget_s: float, interval_s: float = 0.5) -> bool:
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        try:
            if predicate():
                return True
        except OSError:
            pass
        time.sleep(interval_s)
    return False


def _get_json(url: str, timeout: float = 10.0) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _counter_series(snapshot: dict, name: str) -> dict:
    """{sorted-label-key: value} for one counter in a /metrics body."""
    metric = snapshot.get(name) or {}
    return {
        tuple(sorted((s.get("labels") or {}).items())): s["value"]
        for s in metric.get("series") or []
    }


def _post(base: str, path: str, payload: dict, timeout: float = 300.0):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None)
    parser.add_argument("--keep", action="store_true")
    args = parser.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="fleet_smoke_")
    os.makedirs(work, exist_ok=True)
    tel_dir = os.path.join(work, "telemetry")
    aot_dir = os.path.join(work, "aot")
    artifact = os.path.join(work, "model_packed.msgpack")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    import jax

    from distributed_mnist_bnns_tpu.infer import export_packed
    from distributed_mnist_bnns_tpu.models import bnn_mlp_small
    from distributed_mnist_bnns_tpu.obs import load_events, mint_context
    from distributed_mnist_bnns_tpu.obs.trace import format_header
    from distributed_mnist_bnns_tpu.serve import client as sc

    model = bnn_mlp_small(backend="xla")
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 28, 28, 1))
    variables = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        x, train=True,
    )
    export_packed(model, variables, artifact)

    # Warm AOT store: replicas (and respawns) boot with zero compiles.
    build = subprocess.run(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
         "aot", "build", "--store", aot_dir, "--artifact", artifact,
         "--batch-size", "8", "--input-shape", "28", "28", "1",
         "--interpret"],
        env=env, cwd=repo, capture_output=True, text=True,
    )
    if build.returncode != 0:
        print(f"FAIL: aot build rc {build.returncode}:\n"
              f"{build.stderr[-2000:]}", file=sys.stderr)
        return 1

    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
            "fleet",
            "--artifact", artifact,
            "--port", str(port),
            "--replicas", "3",
            "--min-replicas", "3", "--max-replicas", "3",
            "--no-autoscale",          # membership churn is scripted here
            "--deadline-ms", "3000",
            "--probe-interval-s", "0.1",
            "--breaker-reset-s", "0.5",
            "--boot-timeout-s", "150",
            "--batch-size", "8",
            "--queue-depth", "4",
            "--stall-timeout-s", "0.15",
            "--chaos", CHAOS_SPEC,
            "--interpret",
            "--aot", "--aot-dir", aot_dir,
            "--telemetry-dir", tel_dir,
            "--trace",
            "--replica-arg=--breaker-threshold", "--replica-arg=3",
            "--replica-arg=--breaker-reset-s", "--replica-arg=0.4",
            "--log-file", os.path.join(work, "fleet.log"),
        ],
        env=env, cwd=repo,
    )

    failures = []
    stop_hammer = threading.Event()
    codes = []
    lock = threading.Lock()
    imgs = [[[[0.1 * ((i + j) % 7)] for j in range(28)]
             for i in range(28)]]

    def hammer(tid: int) -> None:
        while not stop_hammer.is_set():
            try:
                code, _ = sc.predict_with_retries(
                    base, imgs * 2, deadline_ms=8000.0,
                    max_attempts=10, timeout=15.0,
                    tier="batch" if tid % 2 else "interactive",
                )
            except OSError as e:
                code = -1
                print(f"hammer[{tid}]: transport error {e}",
                      file=sys.stderr)
            with lock:
                codes.append(code)
            time.sleep(0.01)

    try:
        if not _wait(
            lambda: _healthz(base).get("live") == 3, budget_s=180
        ):
            print("FAIL: fleet never reached 3 live replicas",
                  file=sys.stderr)
            return 1

        threads = [
            threading.Thread(target=hammer, args=(i,), daemon=True)
            for i in range(HAMMER_THREADS)
        ]
        for t in threads:
            t.start()
        time.sleep(2.0)       # let chaos stalls/errors fire under load

        # -- traced probe through router AND replica ---------------------
        probe_ctx = mint_context()
        code, probe_a = sc.predict(
            base, imgs, deadline_ms=8000, timeout=15,
            trace=format_header(probe_ctx),
        )
        if code != 200:
            failures.append(f"traced probe returned {code}")

        # -- kill a replica: supervisor must respawn from the AOT store --
        rows = _healthz(base)["replicas"]
        victim = next(r for r in rows if r["healthy"])
        os.kill(victim["pid"], signal.SIGKILL)
        t_kill = time.monotonic()

        def respawned() -> bool:
            h = _healthz(base)
            ids = {r["id"] for r in h["replicas"]}
            return h["live"] == 3 and victim["id"] not in ids

        if not _wait(respawned, budget_s=150):
            failures.append(
                "killed replica was not respawned to 3 live"
            )
        else:
            print(f"respawn took {time.monotonic() - t_kill:.1f}s "
                  "(kill -> 3 live)", file=sys.stderr)
            new_rows = _healthz(base)["replicas"]
            fresh = [r for r in new_rows
                     if r["id"] not in {x["id"] for x in rows}]
            if not fresh:
                failures.append("no fresh replica row after respawn")
            else:
                if fresh[0].get("aot") != "hit":
                    failures.append(
                        f"respawned replica booted aot={fresh[0].get('aot')!r}"
                        " (want 'hit' — the warm-store respawn contract)"
                    )
                if fresh[0].get("recompiles_post_boot") != 0:
                    failures.append(
                        "respawned replica recompiles_post_boot = "
                        f"{fresh[0].get('recompiles_post_boot')} (want 0)"
                    )

        # Let the respawned replica's scripted chaos burst exhaust
        # under the hammer traffic (a fresh process replays the chaos
        # spec from batch 0) before gating a rollout on its error rate.
        time.sleep(3.0)

        # -- rolling reload, byte-identical artifact ---------------------
        artifact2 = os.path.join(work, "model_packed_v2.msgpack")
        shutil.copyfile(artifact, artifact2)
        code, before = sc.predict(base, imgs, deadline_ms=8000,
                                  timeout=15)
        rc, result = _post(base, "/admin/rollout",
                           {"artifact": artifact2})
        if rc != 200 or result.get("status") != "promoted":
            failures.append(f"rolling reload failed: {rc} {result}")
        code2, after = sc.predict(base, imgs, deadline_ms=8000,
                                  timeout=15)
        if code == code2 == 200:
            if before != after:
                failures.append(
                    "responses not bitwise identical across the "
                    "rolling reload"
                )
        else:
            failures.append(
                f"reload probes failed: {code}/{code2}"
            )

        # -- forced-bad-artifact rollout must roll back ------------------
        bad = os.path.join(work, "bad.msgpack")
        with open(bad, "wb") as f:
            f.write(os.urandom(512))
        rc, result = _post(base, "/admin/rollout", {"artifact": bad})
        if rc != 200 or result.get("status") != "rolled_back":
            failures.append(
                f"bad artifact did not roll back: {rc} {result}"
            )
        code, _ = sc.predict(base, imgs, deadline_ms=8000, timeout=15)
        if code != 200:
            failures.append(
                f"fleet not serving after rollback (got {code})"
            )

        stop_hammer.set()
        for t in threads:
            t.join(timeout=30)
        if any(t.is_alive() for t in threads):
            failures.append("hammer thread hung")

        by_code = {c: codes.count(c) for c in sorted(set(codes))}
        bad_final = {c: n for c, n in by_code.items() if c != 200}
        if bad_final:
            failures.append(
                "client requests failed beyond the retry window: "
                f"{bad_final} (of {len(codes)})"
            )
        if not by_code.get(200):
            failures.append(f"no request ever succeeded: {by_code}")

        # -- fleet /metrics reconciles with the replicas' /metrics -------
        # Traffic just quiesced; within a couple of scrape intervals the
        # fleet-merged serve_requests_total (obs/aggregate.py sums
        # scraped replica snapshots — the router's own counters live
        # under fleet_* names) must EXACTLY equal the sum of the
        # replicas' live counters, per label set.
        def reconciled() -> bool:
            rows_now = _healthz(base)["replicas"]
            fleet_snap = _get_json(base + "/metrics")
            expected: dict = {}
            for r in rows_now:
                rep_snap = _get_json(r["url"] + "/metrics")
                for key, v in _counter_series(
                    rep_snap, "serve_requests_total"
                ).items():
                    expected[key] = expected.get(key, 0.0) + v
            return bool(expected) and _counter_series(
                fleet_snap, "serve_requests_total"
            ) == expected

        if not _wait(reconciled, budget_s=20, interval_s=1.0):
            failures.append(
                "fleet /metrics serve_requests_total never reconciled "
                "with the sum of the replicas' own /metrics counters"
            )

        # -- SIGTERM: the whole fleet drains, exit 0 ---------------------
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            rc = proc.wait()
            failures.append("fleet did not drain within 120s of SIGTERM")
        if rc != 0:
            failures.append(f"fleet exited {rc} after SIGTERM (want 0)")
    finally:
        stop_hammer.set()
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # -- event-log assertions ------------------------------------------------
    fleet_events = load_events(os.path.join(tel_dir, "events.jsonl"))
    kinds = {e["kind"] for e in fleet_events}
    for kind in ("fleet_dispatch", "replica_health", "replica_spawn",
                 "replica_exit", "rollout", "drain"):
        if kind not in kinds:
            failures.append(f"fleet event log is missing {kind!r}")
    roll_phases = [e["phase"] for e in fleet_events
                   if e["kind"] == "rollout"]
    for phase in ("ship", "canary_ok", "complete", "trip",
                  "rolled_back"):
        if phase not in roll_phases:
            failures.append(f"rollout log is missing phase {phase!r}")
    exits = [e for e in fleet_events if e["kind"] == "replica_exit"
             and e.get("cause") == "died"]
    if not exits:
        failures.append("no replica_exit(died) event for the kill")

    # -- control-plane decision audit (ISSUE 16) -----------------------------
    # Every supervisor respawn and every router breaker transition must
    # have left a `decision` event carrying its inputs.
    decisions = [e for e in fleet_events if e["kind"] == "decision"]
    respawns = [e for e in decisions if e.get("action") == "respawn"]
    if len(respawns) < len(exits):
        failures.append(
            f"{len(exits)} replica death(s) but only {len(respawns)} "
            "supervisor respawn decision event(s)"
        )
    if respawns and "rc" not in (respawns[0].get("inputs") or {}):
        failures.append("respawn decision events carry no inputs.rc")
    breaker_transitions = [
        e for e in fleet_events
        if e["kind"] == "replica_health" and e.get("breaker")
    ]
    breaker_decisions = [
        e for e in decisions
        if str(e.get("action", "")).startswith("breaker_")
    ]
    if len(breaker_decisions) != len(breaker_transitions):
        failures.append(
            f"{len(breaker_transitions)} breaker transition(s) but "
            f"{len(breaker_decisions)} breaker decision event(s) — "
            "the audit trail must be 1:1"
        )

    # -- multi-dir trace join: `cli trace ROUTER_DIR REPLICA_DIR...` ---------
    replica_dirs = [
        os.path.join(tel_dir, name)
        for name in sorted(os.listdir(tel_dir))
        if name.startswith("replica-")
        and os.path.exists(os.path.join(tel_dir, name, "events.jsonl"))
    ]
    tr = subprocess.run(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
         "trace", tel_dir] + replica_dirs,
        env=env, cwd=repo, capture_output=True, text=True,
    )
    if tr.returncode != 0:
        failures.append(
            f"cli trace over router+replica dirs exited "
            f"{tr.returncode}:\n{tr.stderr[-1500:]}"
        )
    else:
        m = re.search(r"stitched (\d+)/(\d+)", tr.stderr)
        if not m or int(m.group(1)) < 1:
            failures.append(
                "cli trace stitched no replica request tree across "
                f"the fleet dirs (stderr: {tr.stderr[-500:]!r})"
            )

    # replica logs: chaos fired, sheds + breaker cycle happened SOMEWHERE
    # in the fleet (each replica runs the same scripted chaos)
    replica_events = []
    for name in sorted(os.listdir(tel_dir)):
        path = os.path.join(tel_dir, name, "events.jsonl")
        if name.startswith("replica-") and os.path.exists(path):
            replica_events.extend(load_events(path))
    rkinds = {e["kind"] for e in replica_events}
    for kind in ("fault_injected", "shed", "breaker_open",
                 "breaker_close"):
        if kind not in rkinds:
            failures.append(f"replica logs are missing {kind!r}")
    sheds = [e for e in replica_events if e["kind"] == "shed"]
    if not any(e.get("tier") for e in sheds):
        failures.append("replica sheds carry no tier label")

    # one trace id across router and replica: the probe's minted
    # context must appear in BOTH span logs
    fleet_spans = [e for e in fleet_events if e["kind"] == "span"]
    replica_spans = [e for e in replica_events if e["kind"] == "span"]
    if not any(s.get("trace") == probe_ctx.trace_id
               for s in fleet_spans):
        failures.append(
            "probe trace id missing from the ROUTER span log"
        )
    if not any(s.get("trace") == probe_ctx.trace_id
               for s in replica_spans):
        failures.append(
            "probe trace id missing from every REPLICA span log — "
            "the router must forward x-jg-trace unchanged"
        )

    # -- phase two: SLO burn-rate alerting on a min fleet (ISSUE 16) ---------
    # One replica, 1 s/3 s SLO windows: SIGKILL the sole replica so
    # failover has nowhere to go — every request 503s, the availability
    # burn saturates both windows and the alert OPENS; the supervisor's
    # respawn restores traffic and the fast window drains — CLOSE.
    tel2 = os.path.join(work, "telemetry_slo")
    port2 = _free_port()
    base2 = f"http://127.0.0.1:{port2}"
    proc2 = subprocess.Popen(
        [
            sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
            "fleet",
            "--artifact", artifact,
            "--port", str(port2),
            "--replicas", "1",
            "--min-replicas", "1", "--max-replicas", "1",
            "--no-autoscale",
            "--deadline-ms", "3000",
            "--probe-interval-s", "0.1",
            "--breaker-reset-s", "0.3",
            "--boot-timeout-s", "150",
            "--batch-size", "8",
            "--queue-depth", "8",
            "--stall-timeout-s", "0.15",
            "--slo-fast-window-s", "1.0",
            "--slo-slow-window-s", "3.0",
            "--scrape-interval-s", "0.5",
            "--interpret",
            "--aot", "--aot-dir", aot_dir,
            "--telemetry-dir", tel2,
            "--log-file", os.path.join(work, "fleet_slo.log"),
        ],
        env=env, cwd=repo,
    )
    stop2 = threading.Event()

    def hammer_slo() -> None:
        while not stop2.is_set():
            try:
                sc.predict_with_retries(
                    base2, imgs, deadline_ms=3000.0,
                    max_attempts=2, timeout=10.0,
                )
            except OSError:
                pass
            time.sleep(0.02)

    slo_alerts = []
    try:
        if not _wait(
            lambda: _healthz(base2).get("live") == 1, budget_s=180
        ):
            failures.append("SLO fleet never reached 1 live replica")
        else:
            threads2 = [
                threading.Thread(target=hammer_slo, daemon=True)
                for _ in range(4)
            ]
            for t in threads2:
                t.start()
            time.sleep(1.5)       # a good-traffic baseline first
            victim2 = _healthz(base2)["replicas"][0]
            os.kill(victim2["pid"], signal.SIGKILL)
            if not _wait(
                lambda: "availability" in _healthz(base2).get(
                    "slo_open_alerts", []
                ),
                budget_s=60, interval_s=0.2,
            ):
                failures.append(
                    "killing the sole replica never OPENED the "
                    "availability slo_alert"
                )
            elif not _wait(
                lambda: "availability" not in _healthz(base2).get(
                    "slo_open_alerts", []
                ),
                budget_s=90, interval_s=0.2,
            ):
                failures.append(
                    "the availability slo_alert never CLOSED after "
                    "the respawn restored traffic"
                )
            stop2.set()
            for t in threads2:
                t.join(timeout=30)
        proc2.send_signal(signal.SIGTERM)
        try:
            rc2 = proc2.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc2.kill()
            rc2 = proc2.wait()
            failures.append("SLO fleet did not drain after SIGTERM")
        if rc2 != 0:
            failures.append(f"SLO fleet exited {rc2} (want 0)")
    finally:
        stop2.set()
        if proc2.poll() is None:
            proc2.kill()
            proc2.wait()

    slo_events = load_events(os.path.join(tel2, "events.jsonl"))
    slo_alerts = [e for e in slo_events if e["kind"] == "slo_alert"
                  and e.get("slo") == "availability"]
    states = [e.get("state") for e in slo_alerts]
    if "open" not in states or "close" not in states:
        failures.append(
            "SLO fleet event log is missing the availability "
            f"slo_alert open/close pair (got states {states})"
        )
    if not any(e.get("action") == "respawn" for e in slo_events
               if e["kind"] == "decision"):
        failures.append(
            "SLO fleet event log has no supervisor respawn decision "
            "for the kill"
        )

    summary = {
        "responses_by_code": by_code,
        "fleet_events": {k: sum(1 for e in fleet_events
                                if e["kind"] == k)
                         for k in sorted(kinds)},
        "rollout_phases": roll_phases,
        "replica_event_kinds": sorted(rkinds),
        "decision_actions": sorted({
            str(e.get("action")) for e in decisions
        }),
        "slo_alert_states": states,
        "ok": not failures,
    }
    print(json.dumps(summary, indent=2, default=str))
    for failure in failures:
        print(f"FAIL: {failure}", file=sys.stderr)
    if not args.keep and args.dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
