"""CIFAR-shape XNOR-ResNet-18 stretch bench on the bf16 backend
(VERDICT r4 item 3, perf half).

Round 4 published `stretch_xnor_resnet18_cifar` on backend=pallas_xnor —
the backend PERF.md itself shows loses training shapes to bf16 by ~2x.
This measures the stretch on the measured-fastest backend (bf16 MXU,
the framework default) AND emits conv MFU via the same jaxpr-walk
analytic FLOPs as scripts/bench_resnet50.py, so the stretch row finally
compares against the north star. Also keeps a pallas_xnor row for the
backend-gap record.

Emits one JSON line. ``--smoke`` shrinks for CPU validation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
from distributed_mnist_bnns_tpu.utils.platform import (  # noqa: E402
    enable_persistent_compilation_cache,
)

enable_persistent_compilation_cache()
from bench import _conv_macs_per_image  # noqa: E402


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    bs = 32 if args.smoke else args.batch_size
    input_shape = (32, 32, 3)
    deadline = time.monotonic() + (240 if args.smoke else 900)
    key = jax.random.PRNGKey(0)
    images = jax.device_put(
        jax.random.normal(key, (bs, *input_shape), jnp.float32)
    )
    labels = jax.device_put(jax.random.randint(key, (bs,), 0, 10))

    out = {
        "metric": "stretch_xnor_resnet18_cifar_bf16",
        "ts": bench._utc_now(),
        "device": str(jax.devices()[0]),
        "batch_size": bs,
    }
    macs = None  # computed once from the bf16 trace: model MACs are
    # backend-invariant, and the im2col backends' jaxprs count the
    # patch-extraction conv as ~13x phantom MACs
    for backend in ("bf16",) if args.smoke else ("bf16", "pallas_xnor"):
        trainer = Trainer(
            TrainConfig(
                model="xnor-resnet18", batch_size=bs, optimizer="adam",
                learning_rate=0.01, backend=backend, seed=0,
            ),
            input_shape=input_shape,
        )
        if backend == "bf16":
            macs = _conv_macs_per_image(
                trainer.model,
                {"params": trainer.state.params,
                 "batch_stats": trainer.state.batch_stats},
                input_shape,
            )
        dt, loss = bench._bench_train_step(
            trainer, images, labels, steps=10 if args.smoke else 30,
            warmup=2, reps=args.reps, deadline=deadline,
        )
        if dt is None:
            out[backend] = "below measurement floor"
            continue
        peak, _ = bench._chip_peak(jax.devices()[0], "bf16")
        out[backend] = {
            "images_per_sec": round(bs / dt, 1),
            "step_time_ms": round(dt * 1e3, 3),
            "loss_finite": bool(loss == loss),
            "mfu": bench._mfu(3.0 * 2.0 * macs * bs, dt, peak),
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
