"""Partial-binarization ablation for the ViT family (VERDICT r4 item 5):
where does the transformer binarization gap live?

Three-point sweep under the identical recipe (Adam lr=0.003, batch 64,
30 epochs, t10k 9k/1k split, 3 seeds):
  - bnn-vit-tiny                      fully binarized (attention + MLP)
  - bnn-vit-tiny + fp32 attention     binarized_attention=False: q/k/v/out
                                      projections stay fp32, MLP binary
  - fp32-vit-tiny                     the fp32 twin (denominator)

The first and third already come from accuracy_transformer_twins
(RESULTS_VIT.md); this script measures the middle point and emits one
JSON line for RESULTS.md. Per-seed fits persist to the --out sidecar so
a killed run resumes (same contract as accuracy_report's cache).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_mnist_bnns_tpu.utils.platform import (
    enable_persistent_compilation_cache,
    pin_platform_from_env,
)

pin_platform_from_env()
# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
enable_persistent_compilation_cache()


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--seeds", type=int, nargs="+", default=[42, 43, 44])
    p.add_argument("--out", default="vit_ablation.json")
    args = p.parse_args()

    import jax

    from distributed_mnist_bnns_tpu.data import load_mnist
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    cache_path = args.out + ".cache.json"
    cache = {}
    if os.path.exists(cache_path):
        with open(cache_path) as f:
            cache = json.load(f)

    data = load_mnist()
    accs = []
    for seed in args.seeds:
        key = f"fp32attn|{seed}|{args.epochs}|{jax.default_backend()}"
        if key not in cache:
            trainer = Trainer(
                TrainConfig(
                    model="bnn-vit-tiny",
                    model_kwargs={"binarized_attention": False},
                    epochs=args.epochs, batch_size=64,
                    optimizer="adam", learning_rate=0.003,
                    seed=seed, log_interval=1000, scan_steps=4,
                )
            )
            history = trainer.fit(data)
            cache[key] = {
                "test_acc": history[-1]["test_acc"],
                "test_loss": history[-1]["test_loss"],
            }
            tmp = cache_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(cache, f)
            os.replace(tmp, cache_path)
        accs.append(cache[key]["test_acc"])

    rec = {
        "metric": "vit_partial_binarization_ablation",
        "model": "bnn-vit-tiny + binarized_attention=False",
        "epochs": args.epochs,
        "seeds": args.seeds,
        "test_acc_per_seed": [round(a, 2) for a in accs],
        "test_acc_mean": round(sum(accs) / len(accs), 2),
        "device": str(jax.devices()[0]),
    }
    with open(args.out, "w") as f:
        json.dump(rec, f)
        f.write("\n")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
