"""Endpoint window catcher: wait for the remote-TPU tunnel to answer,
then run the full on-chip certification — `pytest tests_tpu` and the
bench harness — and keep the better headline record in
BENCH_LOCAL_r04.json (bench.py's unreachable-endpoint path embeds that
file as `best_hardware_measurement`, so catching even one live window
preserves the round's hardware evidence). Keeps retrying until a
certification actually lands a record or the budget runs out.

Probing reuses bench._device_responsive with JAX_PLATFORMS pinned to the
remote-TPU platform (same guard as scripts/probe_endpoint.py) so a CPU
fallback can never read as a live window.

Run detached: ``nohup python scripts/run_on_window.py >/dev/null 2>&1 &``
Progress/log: scripts/window_run.log
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
LOG = os.path.join(HERE, "window_run.log")

sys.path.insert(0, REPO)

import bench  # noqa: E402  (the repo-root harness; shares its probe)


def log(msg: str) -> None:
    with open(LOG, "a") as f:
        f.write(f"{bench._utc_now()} {msg}\n")


def _run(cmd: list, timeout_s: float):
    """subprocess.run that logs instead of raising on timeout; returns
    the CompletedProcess or None on timeout. Children get the default
    platform resolution (the JAX_PLATFORMS pin is for the probe only —
    tests_tpu/bench do their own platform handling)."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        return subprocess.run(
            cmd, cwd=REPO, capture_output=True, text=True,
            timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        log(f"timed out after {timeout_s:.0f}s: {' '.join(cmd[:3])}...")
        return None


def run_certification() -> bool:
    """One certification attempt. True if a bench record landed."""
    log("window open: running tests_tpu")
    t = _run([sys.executable, "-m", "pytest", "tests_tpu", "-q"], 3600)
    if t is not None:
        log(f"tests_tpu rc={t.returncode} "
            f"tail={t.stdout.strip()[-300:]!r}")

    log("running bench")
    b = _run(
        [sys.executable, "bench.py", "--lm-bench", "--budget-s", "900",
         "--probe-budget-s", "120"],
        3000,
    )
    if b is None or b.returncode != 0 or not (b.stdout or "").strip():
        log(f"bench failed (rc={getattr(b, 'returncode', 'timeout')})")
        return False
    out = b.stdout.strip().splitlines()
    try:
        rec = json.loads(out[-1])
    except json.JSONDecodeError:
        log(f"bench emitted non-JSON tail {out[-1][:200]!r}")
        return False
    with open(os.path.join(HERE, "bench_window.json"), "w") as f:
        f.write(out[-1] + "\n")
    if rec.get("value") is None:
        log("bench record has null value (endpoint died mid-run)")
        return False
    target = os.path.join(REPO, "BENCH_LOCAL_r04.json")
    try:
        with open(target) as f:
            prev_val = json.load(f).get("value") or 0
    except Exception:
        prev_val = 0
    if rec["value"] > prev_val:
        with open(target, "w") as f:
            f.write(out[-1] + "\n")
        log(f"BENCH_LOCAL_r04.json updated: {rec['value']} img/s "
            f"(prev {prev_val})")
    else:
        log(f"kept existing record {prev_val} (window gave {rec['value']})")
    return True


def main() -> None:
    # pin the probe children to the remote-TPU platform (never CPU)
    os.environ["JAX_PLATFORMS"] = os.environ.get(
        "WINDOW_CATCHER_PLATFORM", "axon"
    )
    log("window catcher started")
    deadline = time.time() + float(
        os.environ.get("WINDOW_CATCHER_BUDGET_S", 6 * 3600)
    )
    while time.time() < deadline:
        if bench._device_responsive(70.0) and run_certification():
            log("certification landed; exiting")
            return
        time.sleep(480)
    log("budget exhausted without a completed certification")


if __name__ == "__main__":
    main()
