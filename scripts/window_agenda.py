"""Round-5 hardware agenda: the prioritized list of on-chip jobs the
window catcher (scripts/run_on_window_r5.py) executes when the TPU
tunnel answers.

Each step is (name, argv, timeout_s, required_file). Steps whose
required_file is missing are skipped with a log line (the catcher is
armed before every script exists; pieces land as the round builds
them). Completion is persisted in scripts/window_r05_status.json so a
short window resumes where the last one stopped instead of re-running
tests_tpu from scratch.

Priority order mirrors VERDICT.md round 4 "Next round" items:
  1. tests_tpu           — certify the round-4 serving layer on chip
  2. bench (w/ serving)  — headline + end-to-end serving numbers
  3. stretch bf16 + MFU  — conv stretch on the right backend
  4. int8 fused headline — binarize→int8 crossover rerun
  5. device-resident MFU — profile the one-dispatch epoch
  6. CIFAR accuracy      — xnor-resnet18 + fp32 control
  7. fp32 transformer twins — vit/LM binarization-gap denominators
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
LOG = os.path.join(HERE, "window_run.log")
STATUS = os.path.join(HERE, "window_r05_status.json")

sys.path.insert(0, REPO)

import bench  # noqa: E402


def log(msg: str) -> None:
    with open(LOG, "a") as f:
        f.write(f"{bench._utc_now()} {msg}\n")


def _load_status() -> dict:
    try:
        with open(STATUS) as f:
            return json.load(f)
    except Exception:
        return {}


def _save_status(st: dict) -> None:
    tmp = STATUS + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1)
    os.replace(tmp, STATUS)


def _steps():
    py = sys.executable
    return [
        ("tests_tpu",
         [py, "-m", "pytest", "tests_tpu", "-q"],
         3600, os.path.join(REPO, "tests_tpu")),
        # Bench is split into three tiers so a short window banks
        # something: the 2026-08-01 00:07-00:19 window fit tests_tpu but
        # the monolithic bench hung on a remote compile as the tunnel
        # died and banked nothing in 59 min. Tier timeouts are tight for
        # the same reason — a dead-tunnel hang must not eat the catcher.
        ("bench_headline",
         [py, "bench.py", "--verbose", "--no-crossover", "--no-stretch",
          "--no-epoch-bench", "--budget-s", "240",
          "--probe-budget-s", "90"],
         1200, os.path.join(REPO, "bench.py")),
        ("bench_serving",
         [py, "bench.py", "--verbose", "--serving-bench", "--no-crossover",
          "--no-stretch", "--no-epoch-bench", "--budget-s", "600",
          "--probe-budget-s", "90"],
         1500, os.path.join(REPO, "bench.py")),
        ("bench_full",
         [py, "bench.py", "--verbose", "--lm-bench", "--serving-bench",
          "--budget-s", "900", "--probe-budget-s", "90"],
         2700, os.path.join(REPO, "bench.py")),
        ("stretch_bf16",
         [py, "scripts/bench_stretch_bf16.py"],
         1800, os.path.join(HERE, "bench_stretch_bf16.py")),
        ("int8_headline",
         [py, "scripts/bench_int8.py"],
         1800, os.path.join(HERE, "bench_int8.py")),
        # VERDICT r4 item 4's decision half: measure the flagship
        # headline on the int8 MXU pipeline. _keep_best_bench merges
        # best-by-value, so the banked headline (and its precision-
        # matched MFU) switches to int8 exactly when int8 actually wins
        # end-to-end.
        ("bench_headline_int8",
         [py, "bench.py", "--verbose", "--backend", "int8",
          "--no-crossover", "--no-stretch", "--no-epoch-bench",
          "--budget-s", "240", "--probe-budget-s", "90"],
         1200, os.path.join(REPO, "bench.py")),
        ("device_resident_profile",
         [py, "scripts/profile_device_epoch.py"],
         1800, os.path.join(HERE, "profile_device_epoch.py")),
        ("resnet50_imagenet",
         [py, "scripts/bench_resnet50.py"],
         1800, os.path.join(HERE, "bench_resnet50.py")),
        ("cifar_accuracy",
         [py, "scripts/accuracy_cifar.py"],
         7200, os.path.join(HERE, "accuracy_cifar.py")),
        ("transformer_twins",
         [py, "scripts/accuracy_transformer_twins.py"],
         7200, os.path.join(HERE, "accuracy_transformer_twins.py")),
    ]


def _run_step(name: str, argv: list, timeout_s: float) -> tuple:
    """Run a step with a tunnel watchdog; returns (status_record,
    full_stdout).

    A dead remote-TPU tunnel hangs in-flight dispatches indefinitely
    (the 2026-08-01 00:19 window close ate 59 min of a 60 min timeout
    on one hung remote compile), so alongside the hard timeout the
    watchdog probes the tunnel every ~4 min and kills the step after
    3 consecutive dead probes (~12 min) — 3 because a single 70 s
    probe can starve spuriously while the step itself keeps the tunnel
    busy with large compiles."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # Share one persistent compilation cache across every agenda step and
    # window, so a step killed mid-compile (08:31 window: bench_headline
    # died to the tunnel with nothing banked) resumes from warm
    # executables next window instead of paying the cold remote compile
    # again. jax reads this env var as the cache-dir default.
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    t0 = time.time()
    out_path = os.path.join(HERE, f".step_{name}.out")
    err_path = os.path.join(HERE, f".step_{name}.err")
    dead_probes = 0
    killed_reason = None

    def _out_bytes():
        try:
            return os.path.getsize(out_path) + os.path.getsize(err_path)
        except OSError:
            return 0

    def _kill_group():
        # steps spawn their own subprocesses (e.g. the twins script runs
        # lm_corpus_eval.py) and the hang lives in whichever grandchild
        # holds the in-flight dispatch — reap the whole session, not
        # just the direct child
        import signal
        try:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            p.kill()
        p.wait()

    with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
        p = subprocess.Popen(argv, cwd=REPO, stdout=out_f, stderr=err_f,
                             text=True, env=env, start_new_session=True)
        next_probe = t0 + 240
        last_out = _out_bytes()
        while True:
            try:
                p.wait(timeout=10)
                break
            except subprocess.TimeoutExpired:
                pass
            now = time.time()
            if now - t0 > timeout_s:
                _kill_group()
                killed_reason = f"timed out after {timeout_s:.0f}s"
                break
            if now >= next_probe:
                # a probe can starve while the step saturates the
                # tunnel, so a failed probe only counts as dead when
                # the step's own output has ALSO stopped advancing —
                # otherwise a healthy >12-min busy step would be
                # livelocked by its own load
                cur_out = _out_bytes()
                progressing = cur_out > last_out
                last_out = cur_out
                alive = bench._device_responsive(70.0) or progressing
                dead_probes = 0 if alive else dead_probes + 1
                log(f"step {name}: watchdog probe "
                    f"{'alive' if alive else f'dead x{dead_probes}'}"
                    f"{' (output advancing)' if progressing else ''}")
                if dead_probes >= 3:
                    _kill_group()
                    killed_reason = (
                        "killed by watchdog: tunnel dead on 3 "
                        "consecutive probes with no step output")
                    break
                next_probe = now + 240
    stdout = open(out_path).read()
    stderr = open(err_path).read()
    if killed_reason is not None:
        rc, tail = -9, (killed_reason + ". " + (stdout + stderr)[-1500:])
    else:
        rc, tail = p.returncode, (stdout + stderr)[-2000:]
    return ({"rc": rc, "s": round(time.time() - t0, 1),
             "tail": tail, "ts": bench._utc_now()}, stdout)


# Sections a partial bench record can contribute independently of its
# headline number (the serving-only tier may post a lower headline than
# the headline tier but carry the only serving block). Every other key
# is headline block, replaced as a unit by a better headline. The tuple
# itself lives in bench.py (SECTION_MERGE_KEYS) so this merge and
# bench.py's dead-endpoint carry-over can never drift apart again.
_MERGE_KEYS = bench.SECTION_MERGE_KEYS


def _keep_best_bench(stdout: str):
    """Merge a bench record into BENCH_LOCAL_r05.json (bench.py's
    dead-endpoint path globs the latest BENCH_LOCAL_r*.json).

    The headline block is replaced only by a better headline; section
    blocks (serving, lm_flash, crossover, ...) are adopted whenever the
    new record has a non-failed value for them, so the three bench tiers
    accumulate into one complete record across short windows.

    Returns the parsed record (even when nothing merged) so the caller
    can decide whether the tier actually banked what it exists for —
    bench.py exits 0 for dead-endpoint/unmeasurable records too."""
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    if not lines:
        return None
    try:
        rec = json.loads(lines[-1])
    except json.JSONDecodeError:
        return None
    if rec.get("value") is None:
        return rec
    target = os.path.join(REPO, "BENCH_LOCAL_r05.json")
    try:
        with open(target) as f:
            prev = json.load(f)
    except Exception:
        prev = {}
    merged = dict(prev)
    if rec["value"] > (prev.get("value") or 0):
        # replace the whole headline block (= every non-section key)
        # as a unit so e.g. a stale mfu never outlives its headline
        for k in list(merged):
            if k not in _MERGE_KEYS:
                del merged[k]
        for k, v in rec.items():
            if k not in _MERGE_KEYS:
                merged[k] = v
    def _real(v):
        return v is not None and not (
            isinstance(v, str)
            and (v.startswith("failed") or v.startswith("skipped")))

    for k in _MERGE_KEYS:
        v = rec.get(k)
        if not _real(v):
            continue
        old = merged.get(k)
        if isinstance(v, dict) and isinstance(old, dict):
            # sub-key-aware: a later run whose sub-block was skipped or
            # failed (e.g. serving.lm_kv_decode) must not clobber an
            # earlier banked one; old markers survive until a real
            # value replaces them (same retention as the non-dict path)
            merged[k] = {
                **old,
                **{sk: sv for sk, sv in v.items()
                   if _real(sv) or sk not in old},
            }
        else:
            merged[k] = v
    with open(target, "w") as f:
        json.dump(merged, f)
        f.write("\n")
    log(f"BENCH_LOCAL_r05.json merged: headline={merged.get('value')} "
        f"sections={[k for k in _MERGE_KEYS if k in merged]}")
    return rec


def run_agenda() -> bool:
    """Run every incomplete step while the window lives.
    Returns True when all present steps have completed (rc==0)."""
    st = _load_status()
    all_done = True
    for name, argv, timeout_s, req in _steps():
        if st.get(name, {}).get("rc") == 0:
            continue
        if not os.path.exists(req):
            log(f"step {name}: skipped ({os.path.basename(req)} not built yet)")
            all_done = False
            continue
        if not bench._device_responsive(70.0):
            log(f"step {name}: window closed before start; stopping agenda")
            return False
        log(f"step {name}: running")
        res, stdout = _run_step(name, argv, timeout_s)
        # merge the bench record BEFORE persisting rc==0: a catcher
        # death in between must not mark the step done with its
        # measurement unbanked
        if name.startswith("bench_") and res["rc"] == 0:
            rec = _keep_best_bench(stdout)
            # bench.py exits 0 even for dead-endpoint (value: null)
            # records, for sections skipped on budget (key absent), and
            # for sections that raised (key = "failed: ..." string) —
            # in all of those the tier has not banked what it exists
            # for, so keep it retryable instead of retiring on rc alone.
            required = {"bench_serving": ("serving",),
                        "bench_full": ("serving", "lm_flash")}
            missing = [
                k for k in required.get(name, ())
                if not isinstance((rec or {}).get(k), dict)
            ]
            if rec is None or rec.get("value") is None:
                res["rc"] = -2
                res["tail"] = ("no hardware headline banked; kept "
                               "retryable. " + res["tail"])[:2000]
            elif missing:
                res["rc"] = -3
                res["tail"] = (f"headline ok but section(s) {missing} "
                               "not banked (budget or failure); kept "
                               "retryable. " + res["tail"])[:2000]
        st[name] = res
        _save_status(st)
        log(f"step {name}: rc={res['rc']} in {res['s']}s")
        if res["rc"] != 0:
            all_done = False
    return all_done


if __name__ == "__main__":
    run_agenda()
