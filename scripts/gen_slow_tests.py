"""Regenerate tests/slow_tests.txt (the fast-tier exclusion list).

Usage:
    python -m pytest tests/ -q --durations=0 > /tmp/durations.txt
    python scripts/gen_slow_tests.py /tmp/durations.txt

Tests whose summed setup+call+teardown time exceeds THRESH seconds are
marked slow, except that every test file keeps its fastest test in the
fast tier so ``pytest -m "not slow"`` still touches every subsystem.
"""

from __future__ import annotations

import collections
import os
import re
import sys

THRESH = 3.0
OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "slow_tests.txt",
)


def main() -> None:
    src = sys.argv[1]
    durs: dict = {}
    for line in open(src):
        m = re.match(r"([\d.]+)s (call|setup|teardown)\s+(tests/\S+)", line)
        if m:
            durs[m.group(3)] = durs.get(m.group(3), 0.0) + float(m.group(1))
    by_file = collections.defaultdict(list)
    for nid, t in durs.items():
        by_file[nid.split("::")[0]].append((t, nid))
    slow = set()
    for f, tests in by_file.items():
        tests.sort()
        fast = [x for x in tests if x[0] < THRESH]
        cands = [x for x in tests if x[0] >= THRESH]
        if not fast and cands:
            cands = cands[1:]  # keep the file's fastest for coverage
        slow.update(nid for _, nid in cands)
    with open(OUT, "w") as fh:
        fh.write(
            "# Tests marked slow by conftest (fast tier: pytest -m 'not "
            "slow').\n# Generated from a full-suite `--durations=0` run; "
            f"threshold {THRESH}s,\n# keeping at least one fast test per "
            "file so the fast tier still\n# touches every subsystem. "
            "Regenerate with scripts/gen_slow_tests.py.\n"
        )
        for nid in sorted(slow):
            fh.write(nid + "\n")
    print(f"{OUT}: {len(slow)} slow tests")


if __name__ == "__main__":
    main()
