#!/usr/bin/env python
"""Perf regression gate (ROADMAP item 5 — the CPU-measurable slice).

The repo has a rich perf record (BENCH_LOCAL_r*, PERF.md) but until
round 9 nothing FAILED when a PR regressed it. This gate runs the
deterministic, CPU-measurable comm sections of ``bench.py`` in a fresh
subprocess (the simulated 8-device mesh must be forced before jax
initializes) and compares per-metric results against the checked-in
baselines in ``PERF_BASELINES.json``:

* wire bytes/step for fp32-DP, sign_ef-DP, fp32-FSDP and sign_ef-FSDP
  — analytic byte models pinned to real buffer sizes, so the band is
  EXACT: any drift is a deliberate wire-model change and must be
  re-banked with ``--update`` (and explained in PERF.md);
* the compressed-FSDP wire ratio vs the fp32 reduce-scatter+all-gather
  pair — bounded by the ISSUE-9 acceptance ceiling (<= 1/8);
* post-warmup compile counts of the compressed-FSDP step and its fused
  scan_steps=4 composition — the zero-compile contract (a shape or
  sharding leak that retraces the hot path fails here even when it is
  too cheap for the recompile fence to notice in a short smoke).

The serving tier is gated here too (ROADMAP item 5 slice): classifier
request p99 under saturation through the REAL engine (admission queue +
micro-batcher; the importable ``serve/harness.py`` measurement, run via
``bench.py --serve-p99-bench``) gets the same wide-band ceiling
treatment as the step times below — a lock held across the predictor
dispatch or per-request host work multiplies p99, runner noise merely
wiggles it.

Step-time metrics for the comm-bench variants are gated too, with a
deliberately WIDE tolerance band (+300%): CPU step times swing 2-3x
run to run on shared/loaded runners, so the band is a CATASTROPHE
detector sized to catch only gross regressions — per-step host work leaking into the
steady-state hot path (the elastic loop's bookkeeping, a stray sync),
which multiplies step time rather than jittering it. Bytes and compile
counts remain the precise regression surface (PERF.md "Gradient
comms"); a measured step time below bench's measurement floor passes
vacuously (faster is never a regression).

Usage:
    python scripts/perf_gate.py               # compare, exit 1 on fail
    python scripts/perf_gate.py --update      # re-bank baselines
    python scripts/perf_gate.py --bench-json R  # compare a saved record
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINES = os.path.join(REPO, "PERF_BASELINES.json")

BENCH_ARGS = [
    "--model", "bnn-mlp-small", "--batch-size", "256",
    "--comm-bench", "--comm-batch-size", "256", "--comm-steps", "5",
    "--serve-p99-bench",
    # Fleet availability under chaos (ISSUE 15; ROADMAP item 1's named
    # follow-through): a saturated 3-replica in-process fleet through
    # the REAL router has one replica stalled then killed mid-window —
    # the success fraction is a floor, and a trip prints the section's
    # per-replica breaker/health transition log (explain_failures).
    "--fleet-avail-bench",
    # Per-program cost ledger (ISSUE 14; ROADMAP item 5's MFU slice):
    # cost-analysis flops are exact for a fixed model/batch/jax, the
    # measured-MFU floor is wide-band (OBSERVABILITY.md "Device
    # profiling", PERF.md "MFU floor").
    "--device-costs-bench",
    # LM serving slice (ROADMAP item 5 remnant, landed with ISSUE 13):
    # tiny geometry keeps the gate's wall clock sane while still
    # exercising the real engine, scheduler and all three compiled
    # programs (prefill/decode/verify).
    "--lm-serve-bench", "--serving-lm-ctx", "64",
    "--lm-embed-dim", "32", "--lm-depth", "1", "--lm-heads", "2",
    "--steps", "5", "--warmup", "3", "--reps", "1", "--scan-steps", "8",
    "--no-stretch", "--no-crossover",
    "--probe-timeout", "30", "--probe-budget-s", "30",
]


def _get(record: dict, path: str):
    """Dotted-path lookup ('comm.modes.none.wire_bytes_per_step');
    None when any hop is missing or a section failed (a string)."""
    cur = record
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


# metric name -> (dotted path into the bench record, comparison kind)
#   exact: measured == baseline (tolerance ignored)
#   max:   measured <= baseline * (1 + tolerance)
#   min:   measured >= baseline * (1 - tolerance)   (floors)
METRIC_PATHS = {
    "fp32_dp_wire_bytes_per_step": (
        "comm.modes.none.wire_bytes_per_step", "exact"),
    "sign_ef_dp_wire_bytes_per_step": (
        "comm.modes.sign_ef.wire_bytes_per_step", "exact"),
    "fp32_fsdp_wire_bytes_per_step": (
        "comm_fsdp.variants.fp32.wire_bytes_per_step", "exact"),
    "sign_ef_fsdp_wire_bytes_per_step": (
        "comm_fsdp.variants.sign_ef.wire_bytes_per_step", "exact"),
    "sign_ef_fsdp_wire_ratio_vs_fp32": (
        "comm_fsdp.variants.sign_ef.wire_ratio_vs_fp32", "max"),
    "sign_ef_fsdp_post_warmup_compiles": (
        "comm_fsdp.variants.sign_ef.compiles_post_warmup", "max"),
    "sign_ef_fsdp_scan4_post_warmup_compiles": (
        "comm_fsdp.variants.sign_ef_scan4.compiles_post_warmup", "max"),
    # Two-level hierarchical wire model (multi-host elastic runtime;
    # PERF.md "Hierarchical comms"): the DP world factored into
    # (hosts x local) — fp32 ring within a host, 1-bit sign_ef across
    # hosts only. Byte columns are pure functions of (model, hosts,
    # local, bucket layout), gated EXACTLY like the flat wire bytes.
    # The ratio band is the multi-host acceptance contract: inter-host
    # bytes <= 1/8 of the flat fp32 ring at the same total world.
    "hier_intra_wire_bytes_per_step": (
        "comm_hier.hier.intra_bytes_per_step", "exact"),
    "hier_inter_wire_bytes_per_step": (
        "comm_hier.hier.inter_bytes_per_step", "exact"),
    "hier_inter_wire_ratio_vs_flat_fp32": (
        "comm_hier.hier.inter_ratio_vs_flat_fp32", "max"),
    "hier_post_warmup_compiles": (
        "comm_hier.hier.compiles_post_warmup", "max"),
    # Serving-latency ceiling (ROADMAP item 5 slice): classifier
    # request p99 at saturation through the real engine — the
    # serve/harness measurement, banded WIDE like the step times (a
    # lock across the dispatch or per-request host-work leak
    # multiplies p99; runner jitter merely wiggles it).
    "classifier_p99_under_saturation_ms": (
        "serving_p99.p99_ms", "max"),
    # LM serving bands (ISSUE 13; ROADMAP items 2+5): a decode
    # tokens/sec FLOOR and an inter-token p99 ceiling through the real
    # continuous-batching engine, both wide-band (CPU throughput on
    # loaded runners swings; a host-work leak into the per-iteration
    # hot loop collapses it rather than wiggling it) — plus the
    # draft-acceptance-rate floor for self-speculative decoding (the
    # draft and verifier carry the SAME weights, so greedy acceptance
    # sits near 1.0; a numerics drift between the packed and dense-bf16
    # paths craters it long before output equality visibly breaks).
    "lm_tokens_per_sec_1stream": (
        "lm_serve.packed_1bit.streams_1.tokens_per_sec", "min"),
    "lm_p99_intertoken_ms_8streams": (
        "lm_serve.packed_1bit.streams_8.p99_intertoken_ms", "max"),
    "lm_spec_acceptance_rate": (
        "lm_serve.spec.acceptance_rate", "min"),
    # Packed-vs-dense decode throughput at every stream count (ISSUE 20
    # acceptance; ROADMAP item 2): with the Pallas serving path armed
    # (in-kernel page-table walk + packed-GEMM carries) the 1-bit
    # engine must beat the same artifact carried as dense fp32 at 1, 4
    # AND 8 streams. These are PINNED contract floors (baseline 1.0,
    # tolerance 0 — see PINNED_FLOORS) that HARD-ARM only on
    # compiled-kernel records: under the CPU interpreter both rows are
    # interpreter-overhead-bound and the ratio draws runner noise
    # around 1.0 (±20% observed across back-to-back runs), so
    # interpret-mode records report the draw informationally instead
    # of flaking CI on interpreter jitter (PERF.md round 16).
    "lm_packed_speedup_1_streams": (
        "lm_serve.packed_speedup_1_streams", "min"),
    "lm_packed_speedup_4_streams": (
        "lm_serve.packed_speedup_4_streams", "min"),
    "lm_packed_speedup_8_streams": (
        "lm_serve.packed_speedup_8_streams", "min"),
    # Fleet availability under chaos (ISSUE 15): success fraction of
    # saturating client requests against a 3-replica fleet while one
    # replica is chaos-stalled then killed mid-window — retry/failover
    # must keep this >= 0.99 (the acceptance floor). Banked at 1.0 with
    # a 0.01 tolerance rather than --update-measured: the floor IS the
    # contract, not a noise band.
    "fleet_availability_under_chaos": (
        "fleet_availability.availability", "min"),
    # Per-program cost ledger (ISSUE 14): XLA's cost-model flops for
    # the train step are a pure function of (model, batch, jax
    # version) — gated EXACTLY like the wire bytes; a drift means the
    # lowered program changed (a GEMM stopped being a dot, an
    # optimizer fusion broke) and must be re-banked deliberately. The
    # measured-MFU floor is the wide-band catastrophe detector ROADMAP
    # item 5 asked for: CPU throughput jitters, but a hot-path host
    # leak COLLAPSES achieved flops/s rather than wiggling it.
    "train_step_cost_flops": (
        "device_costs.cost_flops", "exact"),
    "train_step_mfu_measured": (
        "device_costs.mfu_measured", "min"),
    # Steady-state step-time ceilings (wide band, see module docstring).
    "fp32_dp_step_time_ms": (
        "comm.modes.none.step_time_ms", "max"),
    "sign_ef_dp_step_time_ms": (
        "comm.modes.sign_ef.step_time_ms", "max"),
    "fp32_fsdp_step_time_ms": (
        "comm_fsdp.variants.fp32.step_time_ms", "max"),
    "sign_ef_fsdp_step_time_ms": (
        "comm_fsdp.variants.sign_ef.step_time_ms", "max"),
}

# Wall-clock metrics sharing the wide band: step times plus the
# serving p99-under-saturation and LM inter-token ceilings (same
# runner-noise reasoning).
def _wide_band(name: str) -> bool:
    return (
        name.endswith("_step_time_ms")
        or name == "classifier_p99_under_saturation_ms"
        or name == "lm_p99_intertoken_ms_8streams"
    )


# Tolerance for the step-time ceilings when (re-)banking: wide enough
# for runner noise, tight enough that a per-step host-work leak (which
# multiplies, not jitters, CPU step time) still fails. NOTE: --update
# banks ONE draw; step-time baselines should be hand-raised to the
# worst case observed across a few runs (a lucky-fast draw plus 4x is
# still tighter than a loaded runner's honest jitter).
STEP_TIME_TOLERANCE = 3.0

# Banking tolerances for the floor (min) metrics: throughput may drop
# to a quarter of the banked draw before failing (the loaded-runner
# envelope); greedy draft acceptance may lose 10 points — exact-equal
# GEMM math keeps it pinned near 1.0, so even that is generous.
MIN_TOLERANCES = {
    "lm_tokens_per_sec_1stream": 0.75,
    "lm_spec_acceptance_rate": 0.1,
    "train_step_mfu_measured": 0.75,
    "fleet_availability_under_chaos": 0.01,
}

# Floors banked at a PINNED baseline instead of the measured draw: the
# floor IS the contract. A fast draw must not ratchet the band up and a
# slow runner must not relax it — packed decode beating dense fp32 at
# every stream count is ISSUE 20's acceptance line, full stop. On
# interpret-mode records compare() reports these informationally
# instead of hard-failing (see METRIC_PATHS comment).
PINNED_FLOORS = {
    "lm_packed_speedup_1_streams": 1.0,
    "lm_packed_speedup_4_streams": 1.0,
    "lm_packed_speedup_8_streams": 1.0,
}

# Serving-latency bands whose trips the gate EXPLAINS with `cli
# trace`-style tail attribution over the bench run's probe events
# (ROADMAP item 5: "EXPLAIN any band trip, not just detect it").
SERVING_BANDS = (
    "classifier_p99_under_saturation_ms",
    "lm_p99_intertoken_ms_8streams",
)
# MFU/cost bands whose trips print the per-program cost ledger.
MFU_BANDS = ("train_step_mfu_measured", "train_step_cost_flops")
# Fleet bands whose trips print the availability probe's per-replica
# health/breaker transition log (which replica flapped, when, why).
FLEET_BANDS = ("fleet_availability_under_chaos",)

# bench reports "below measurement floor" instead of a number when a
# variant ran faster than it can time honestly — never a regression.
_FLOOR = "below measurement floor"


def run_bench(events_dir: str | None = None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    cmd = [sys.executable, os.path.join(REPO, "bench.py"), *BENCH_ARGS]
    if events_dir:
        # Traced probe events land next to the mirror: a tripped
        # serving band explains itself from these (explain_failures).
        env["JG_TRACE"] = "1"
        cmd += ["--events", os.path.join(events_dir, "bench_events.jsonl")]
    print("perf_gate: running", " ".join(cmd), file=sys.stderr, flush=True)
    out = subprocess.run(
        cmd, env=env, cwd=REPO, check=True, capture_output=True, text=True
    )
    # bench's contract: stdout is exactly one JSON line (stderr carries
    # progress); take the last non-empty line defensively.
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    return json.loads(lines[-1])


def _measurement_note(record: dict, path: str) -> str:
    """Measurement-context suffix for a trip message: LM-serving bands
    measured with the Pallas kernels under the interpreter (CPU run —
    bench records ``lm_serve.interpret_mode``) say so in the failure
    itself, so a reader weighs the trip against interpreter overhead
    rather than assuming compiled-kernel numbers regressed."""
    if path.startswith("lm_serve.") and _get(
        record, "lm_serve.interpret_mode"
    ):
        return (
            " [measured with interpret_mode=true: Pallas kernels ran "
            "under the interpreter on CPU]"
        )
    return ""


def compare(baselines: dict, record: dict, notes: list | None = None) -> list:
    """Returns a list of failure strings (empty = gate passes).
    ``notes`` (optional) collects informational lines that are not
    failures — the interpret-mode draws of the pinned speedup floors."""
    failures = []
    for name, spec in baselines.get("metrics", {}).items():
        path, kind = METRIC_PATHS.get(name, (None, None))
        if path is None:
            failures.append(f"{name}: unknown metric (stale baseline file?)")
            continue
        measured = _get(record, path)
        if isinstance(measured, str) and measured == _FLOOR:
            continue  # faster than bench can time — vacuous pass
        if measured is None or isinstance(measured, str):
            failures.append(
                f"{name}: missing from the bench record at {path!r} "
                f"(section failed or skipped: {measured!r})"
            )
            continue
        base = spec["baseline"]
        tol = float(spec.get("tolerance", 0.0))
        note = _measurement_note(record, path)
        if name in PINNED_FLOORS and _get(
            record, "lm_serve.interpret_mode"
        ):
            # The speedup floors certify a weight-bandwidth contract
            # (1/32 byte/param) that only exists where the kernels
            # compile; under the interpreter the ratio is runner noise
            # around 1.0. Record the draw, arm the floor on
            # compiled-kernel records (see METRIC_PATHS comment).
            if notes is not None:
                notes.append(
                    f"{name}: measured {measured} — pinned floor "
                    f"{base} is informational under "
                    "interpret_mode=true, hard-armed on "
                    "compiled-kernel records" + note
                )
            continue
        if kind == "exact":
            if measured != base:
                failures.append(
                    f"{name}: measured {measured} != banked {base} "
                    "(analytic byte model drifted — if deliberate, "
                    "re-bank with scripts/perf_gate.py --update)" + note
                )
        elif kind == "min":
            floor = base * (1.0 - tol)
            if measured < floor:
                failures.append(
                    f"{name}: measured {measured} < floor {floor} "
                    f"(baseline {base}, tolerance {tol})" + note
                )
        else:  # max
            limit = base * (1.0 + tol)
            if measured > limit:
                failures.append(
                    f"{name}: measured {measured} > allowed {limit} "
                    f"(baseline {base}, tolerance {tol})" + note
                )
    return failures


def explain_failures(
    failures: list, record: dict, events_dir: str | None,
) -> str:
    """Turn a band trip into a diagnosis, not just a detection
    (ROADMAP item 5's "EXPLAIN any band trip"):

    * a serving-latency trip runs the `cli trace` tail attribution over
      the bench probe's traced events (bench wrote them under
      ``<events_dir>/serving_p99/`` when the gate armed tracing) and
      appends the per-kind critical-path breakdown — "p99 is
      queue-dominated" vs "slow dispatch" in the failure output itself;
    * an MFU/cost trip appends the per-program cost ledger section
      (flops, HBM, measured-vs-analytic reconciliation) so the reader
      sees WHICH program drifted and by how much.

    Best-effort: a missing/untraced events dir degrades to a note, the
    gate's verdict never depends on the explanation succeeding."""
    failed_names = {f.split(":", 1)[0] for f in failures}
    parts: list = []
    if failed_names & set(SERVING_BANDS):
        probe_events = os.path.join(
            events_dir or "", "serving_p99", "events.jsonl"
        )
        try:
            sys.path.insert(0, REPO)
            from distributed_mnist_bnns_tpu.obs.trace import (
                load_spans,
                render_attribution,
                tail_attribution,
            )

            spans = load_spans(probe_events)
            if spans:
                report = tail_attribution(spans, pct=99.0)
                parts.append(
                    "serving band tripped — tail attribution over the "
                    f"probe's traced events ({probe_events}):\n"
                    + render_attribution(report)
                )
            else:
                parts.append(
                    f"serving band tripped but {probe_events} holds no "
                    "spans (probe untraced?)"
                )
        except (OSError, ImportError) as e:
            parts.append(
                f"serving band tripped; tail attribution unavailable "
                f"({type(e).__name__}: {e})"
            )
    if failed_names & set(MFU_BANDS):
        section = record.get("device_costs")
        parts.append(
            "MFU/cost band tripped — per-program cost ledger:\n"
            + json.dumps(section, indent=1, sort_keys=True)
        )
    if failed_names & set(FLEET_BANDS):
        section = record.get("fleet_availability")
        if isinstance(section, dict):
            parts.append(
                "fleet availability band tripped — per-replica "
                "health/breaker transitions over the probe window "
                f"(killed {section.get('killed_replica')} at "
                f"{section.get('killed_at_s')}s, outcomes "
                f"{section.get('outcomes')}):\n"
                + json.dumps(
                    section.get("replica_transitions"),
                    indent=1, sort_keys=True,
                )
            )
            # The control-plane audit trail: every router/SLO decision
            # the probe captured, rendered as the same timeline
            # `cli fleet explain` prints — the trip explains itself.
            try:
                sys.path.insert(0, REPO)
                from distributed_mnist_bnns_tpu.obs import (
                    decision_timeline,
                    render_decision_timeline,
                )

                events = list(section.get("decisions") or [])
                events += list(section.get("slo_alerts") or [])
                rows = decision_timeline(events)
                if rows:
                    parts.append(render_decision_timeline(
                        rows,
                        title="probe decision timeline "
                              "(router ejections, breaker trips, "
                              "SLO alerts)",
                    ))
                slo = section.get("slo")
                if slo:
                    parts.append(
                        "probe SLO summary:\n"
                        + json.dumps(slo, indent=1, sort_keys=True)
                    )
            except ImportError as e:
                parts.append(
                    "decision timeline unavailable "
                    f"({type(e).__name__}: {e})"
                )
        else:
            parts.append(
                "fleet availability band tripped and the probe section "
                f"is missing/failed: {section!r}"
            )
    return "\n\n".join(parts)


def bank(record: dict, prev: dict | None = None) -> dict:
    metrics = {}
    prev_metrics = (prev or {}).get("metrics", {})
    for name, (path, kind) in METRIC_PATHS.items():
        measured = _get(record, path)
        if isinstance(measured, str) and measured == _FLOOR:
            # This run was faster than bench can time. Keep any prior
            # baseline instead of silently shrinking the regression
            # surface — a later slow run must still be gated.
            if name in prev_metrics:
                metrics[name] = prev_metrics[name]
                print(
                    f"perf_gate: {name}: below measurement floor this "
                    "run; carrying the prior baseline forward",
                    file=sys.stderr,
                )
            else:
                print(
                    f"perf_gate: {name}: below measurement floor and no "
                    "prior baseline — not banked (gate passes it "
                    "vacuously)",
                    file=sys.stderr,
                )
            continue
        if measured is None or isinstance(measured, str):
            raise SystemExit(
                f"cannot bank {name}: missing from the record at {path!r} "
                f"({measured!r})"
            )
        if name in PINNED_FLOORS:
            metrics[name] = {"baseline": PINNED_FLOORS[name],
                             "kind": kind, "tolerance": 0.0}
            continue
        if kind == "min":
            tol = MIN_TOLERANCES.get(name, 0.0)
        else:
            tol = STEP_TIME_TOLERANCE if _wide_band(name) else 0.0
        metrics[name] = {"baseline": measured, "kind": kind,
                         "tolerance": tol}
    return {
        "note": (
            "Perf-regression baselines for the CPU-measurable comm "
            "slice (scripts/perf_gate.py; ROADMAP item 5). Byte counts "
            "and the train-step cost-analysis flops (device_costs "
            "section, ISSUE 14) are deterministic and gated EXACTLY; "
            "compile counts and the wire ratio are ceilings; step "
            "times, the classifier p99-under-saturation "
            "(serve/harness.py) and the LM inter-token p99 are WIDE-"
            "band ceilings (noise-tolerant, catch per-step/per-request "
            "host-work leaks into the hot path); LM tokens/sec, the "
            "spec-decode draft-acceptance rate, the measured "
            "train-step MFU and the fleet availability-under-chaos "
            "(serve/fleet/harness.py: 3 replicas, one chaos-stalled "
            "then killed mid-saturation, success fraction through the "
            "real router) are FLOORS (kind=min: measured >= "
            "baseline*(1-tolerance)). The LM packed-vs-dense speedups "
            "at 1/4/8 streams are PINNED contract floors (baseline "
            "1.0, tolerance 0, never ratcheted by --update): with the "
            "Pallas serving path armed, packed decode must beat dense "
            "fp32 at every stream count — hard-armed on compiled-"
            "kernel records, reported informationally on interpret-"
            "mode records (PERF.md round 16). Serving-band, MFU-band and "
            "fleet-band trips print their own explanation (tail "
            "attribution / cost ledger / per-replica transition log — "
            "explain_failures). Re-bank deliberate changes "
            "with scripts/perf_gate.py --update."
        ),
        "bench_args": BENCH_ARGS,
        "metrics": metrics,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update", action="store_true",
                    help="re-bank PERF_BASELINES.json from a fresh run")
    ap.add_argument("--bench-json", default=None,
                    help="compare a saved bench record instead of "
                         "running bench.py")
    args = ap.parse_args()

    events_dir = None
    if args.bench_json:
        with open(args.bench_json) as f:
            record = json.load(f)
        # A saved record may carry its probe's events dir (bench banks
        # it in the serving_p99 section when tracing was armed).
        p99 = record.get("serving_p99")
        if isinstance(p99, dict) and p99.get("events_dir"):
            events_dir = os.path.dirname(p99["events_dir"])
    else:
        import tempfile

        events_dir = tempfile.mkdtemp(prefix="perf_gate_events_")
        record = run_bench(events_dir)

    if args.update:
        prev = None
        if os.path.exists(BASELINES):
            with open(BASELINES) as f:
                prev = json.load(f)
        with open(BASELINES, "w") as f:
            json.dump(bank(record, prev=prev), f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"perf_gate: banked baselines to {BASELINES}")
        return 0

    with open(BASELINES) as f:
        baselines = json.load(f)
    notes: list = []
    failures = compare(baselines, record, notes=notes)
    for name, spec in sorted(baselines.get("metrics", {}).items()):
        path, _ = METRIC_PATHS.get(name, (None, None))
        measured = _get(record, path) if path else None
        print(f"perf_gate: {name}: measured={measured} "
              f"baseline={spec['baseline']} ({spec['kind']})")
    for n_ in notes:
        print(f"perf_gate: note: {n_}")
    if failures:
        print("\nPERF GATE FAILED:", file=sys.stderr)
        for f_ in failures:
            print(f"  - {f_}", file=sys.stderr)
        explanation = explain_failures(failures, record, events_dir)
        if explanation:
            print("\n" + explanation, file=sys.stderr)
        return 1
    print("perf_gate: all metrics within bands")
    return 0


if __name__ == "__main__":
    sys.exit(main())
