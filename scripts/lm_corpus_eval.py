"""Binarized-LM bits/byte on the external corpus, with honest baselines
(VERDICT r4 item 7).

Protocol: contiguous 90/10 train/valid split of
data_files/licenses_corpus.txt (build_licenses_corpus.py). The LM trains
on random train-side windows; bits/byte is measured on the UNSEEN valid
side with full-window context (positions past the warmup prefix score
their next byte; the first ``context`` positions of each window are
excluded so every scored byte has at least that much context).

Anchors computed on the same split (train-fit, valid-scored):
  - order-0 (unigram) entropy: add-1-smoothed byte unigram model
  - bigram conditional: add-1-smoothed P(b_t | b_{t-1})
  - trigram conditional: add-1-smoothed P(b_t | b_{t-2}, b_{t-1})
A byte LM only earns its keep below the n-gram line it can afford to
beat; enwik8-class transformer results sit near ~1.0-1.3 bits/byte for
context, but that corpus is 400x larger — the honest comparison here is
the n-grams on THIS corpus.

Emits one JSON line (paste into RESULTS.md). Defaults are sized to run
on CPU in ~15 min; pass --embed-dim 256 --depth 4 --steps 4000 on a live
TPU window for the full-size family evidence.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor JAX_PLATFORMS over the image's sitecustomize (remote-TPU
# plugin); raises if a backend already initialized on the wrong platform.
from distributed_mnist_bnns_tpu.utils.platform import (
    enable_persistent_compilation_cache,
    pin_platform_from_env,
)

pin_platform_from_env()
# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
enable_persistent_compilation_cache()

CORPUS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "data_files", "licenses_corpus.txt",
)


def ngram_bits_per_byte(train, valid, order: int) -> float:
    """Add-1-smoothed order-``order`` conditional model, fit on train,
    scored on valid (contexts drawn from valid itself, first order
    bytes skipped)."""
    import numpy as np

    if order == 1:
        counts = np.bincount(train, minlength=256).astype(np.float64)
        probs = (counts + 1.0) / (counts.sum() + 256.0)
        return float(-np.log2(probs[valid]).mean())
    # context hash: previous (order-1) bytes as an integer
    def ctx(arr, i):
        c = 0
        for j in range(order - 1):
            c = c * 256 + int(arr[i - order + 1 + j])
        return c

    from collections import defaultdict

    counts: dict = defaultdict(lambda: defaultdict(int))
    totals: dict = defaultdict(int)
    for i in range(order - 1, len(train)):
        c = ctx(train, i)
        counts[c][int(train[i])] += 1
        totals[c] += 1
    bits = 0.0
    n = 0
    for i in range(order - 1, len(valid)):
        c = ctx(valid, i)
        num = counts[c][int(valid[i])] + 1.0
        den = totals[c] + 256.0
        bits += -math.log2(num / den)
        n += 1
    return bits / n


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=1500)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--embed-dim", type=int, default=128)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--context", type=int, default=32,
                   help="min context per scored byte in eval windows "
                        "(>= 1; position i scores byte i+1, so context 1 "
                        "scores every window position)")
    p.add_argument("--partial", action="store_true",
                   help="also train the partial-binarization point "
                        "(fp32 attention + binary MLP — the RESULTS.md "
                        "ablation recipe, binarized_attention=False)")
    p.add_argument("--fp32-twin", action="store_true",
                   help="also train an fp32 twin (binarization-gap "
                        "denominator)")
    p.add_argument("--cache", default=CORPUS + ".eval_cache.json",
                   help="per-variant result cache: each finished "
                        "training banks immediately, so a run killed "
                        "mid-study (window close, watchdog) resumes "
                        "from the completed variants instead of "
                        "retraining them. Keyed on config + a sha256 of "
                        "the corpus content + a fingerprint of the "
                        "model/eval code, so corpus edits and code "
                        "changes miss instead of replaying stale "
                        "results; pass --cache '' to disable")
    args = p.parse_args()
    if args.context < 1 or args.context >= args.seq_len:
        p.error(
            f"--context must be in [1, seq_len); got {args.context} "
            f"(context-1 slicing would silently score only window-final "
            "bytes at 0)"
        )

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_mnist_bnns_tpu.models import (
        latent_clamp_mask,
        lm_loss,
    )
    from distributed_mnist_bnns_tpu.models.transformer import BinarizedLM
    from distributed_mnist_bnns_tpu.train import clamp_latent

    raw = open(CORPUS, "rb").read()
    data = np.frombuffer(raw, np.uint8)
    split = int(len(data) * 0.9)
    train, valid = data[:split], data[split:]
    t = args.seq_len

    import hashlib

    import distributed_mnist_bnns_tpu.models.transformer as _tf_mod

    # Cache-key integrity: a byte-length-only corpus identity silently
    # replays stale results after an equal-length corpus edit, and no
    # code identity replays them after a model change. Hash the corpus
    # CONTENT and fingerprint the model + eval code (the two files whose
    # edits change the numbers); the git rev alone would miss dirty-tree
    # runs.
    corpus_sha = hashlib.sha256(raw).hexdigest()[:16]
    code_fp = hashlib.sha256()
    for src in (_tf_mod.__file__, os.path.abspath(__file__)):
        with open(src, "rb") as f:
            code_fp.update(f.read())
    cfg_key = json.dumps(
        {"embed_dim": args.embed_dim, "depth": args.depth, "seq_len": t,
         "steps": args.steps, "batch": args.batch, "lr": args.lr,
         "heads": args.num_heads, "seed": args.seed,
         "context": args.context, "corpus_bytes": int(len(data)),
         "corpus_sha256": corpus_sha,
         "code_fingerprint": code_fp.hexdigest()[:16]},
        sort_keys=True,
    )
    cache = {}
    if args.cache:
        try:
            with open(args.cache) as f:
                cache = json.load(f)
        except Exception:
            pass

    def train_lm(variant: str, binarized: bool, binarized_attention=None):
        key = f"{variant}|{cfg_key}"
        if key in cache:
            # marked so a log reader can tell a replayed result (stale
            # train_seconds) from a training that actually ran now
            return {**cache[key], "cached": True}
        # Per-variant rng stream so a resumed run that skips cached
        # variants trains the rest identically. bnn keeps the original
        # scalar-seed stream: its numbers are the published RESULTS.md
        # recipe and must stay bit-reproducible.
        rng = (
            np.random.RandomState(args.seed)
            if variant == "bnn"
            else np.random.RandomState(
                (args.seed, {"partial": 1, "fp32": 2}[variant])
            )
        )
        model = BinarizedLM(
            vocab=256, max_len=t, embed_dim=args.embed_dim,
            depth=args.depth, num_heads=args.num_heads, attention="xla",
            binarized=binarized, binarized_attention=binarized_attention,
        )
        variables = model.init(
            {"params": jax.random.PRNGKey(args.seed)},
            jnp.zeros((2, t), jnp.int32), train=False,
        )
        params = variables["params"]
        mask = latent_clamp_mask(params)
        tx = optax.adam(args.lr)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt, tokens):
            def loss_fn(p):
                return lm_loss(
                    model.apply({"params": p}, tokens, train=False),
                    tokens,
                )

            loss, g = jax.value_and_grad(loss_fn)(params)
            up, opt = tx.update(g, opt, params)
            return (
                clamp_latent(optax.apply_updates(params, up), mask),
                opt, loss,
            )

        t0 = time.time()
        loss = None
        for i in range(args.steps):
            starts = rng.randint(0, len(train) - t, size=args.batch)
            tokens = jnp.asarray(
                np.stack([train[s:s + t] for s in starts]), jnp.int32
            )
            params, opt, loss = step(params, opt, tokens)
        train_s = time.time() - t0

        # held-out bits/byte: tile valid into overlapping windows with
        # stride (t - context); score positions [context, t) of each
        @jax.jit
        def window_bits(params, tokens):
            lp = model.apply({"params": params}, tokens, train=False)
            tgt = tokens[:, 1:]
            per = jnp.take_along_axis(
                lp[:, :-1], tgt[..., None], axis=-1
            )[..., 0]
            return per[:, args.context - 1:]

        stride = t - args.context
        starts = list(range(0, len(valid) - t, stride))
        bits, count = 0.0, 0
        for i in range(0, len(starts), args.batch):
            chunk = starts[i:i + args.batch]
            toks = jnp.asarray(
                np.stack([valid[s:s + t] for s in chunk]), jnp.int32
            )
            per = np.asarray(window_bits(params, toks))
            bits += float(-per.sum() / math.log(2.0))
            count += per.size
        res = {
            "train_final_loss_bits": round(
                float(loss) / math.log(2.0), 4
            ),
            "valid_bits_per_byte": round(bits / count, 4),
            "train_seconds": round(train_s, 1),
            "scored_bytes": count,
        }
        cache[key] = res
        if args.cache:
            tmp = args.cache + ".tmp"
            with open(tmp, "w") as f:
                json.dump(cache, f, indent=1)
            os.replace(tmp, args.cache)
        return res

    result = {
        "metric": "lm_licenses_corpus",
        "corpus_bytes": int(len(data)),
        "train_bytes": int(split),
        "valid_bytes": int(len(data) - split),
        "config": {
            "embed_dim": args.embed_dim, "depth": args.depth,
            "seq_len": t, "steps": args.steps, "batch": args.batch,
        },
        "baselines_bits_per_byte": {
            "unigram": round(ngram_bits_per_byte(train, valid, 1), 4),
            "bigram": round(ngram_bits_per_byte(train, valid, 2), 4),
            "trigram": round(ngram_bits_per_byte(train, valid, 3), 4),
        },
        "bnn_lm": train_lm("bnn", True),
    }
    if args.partial:
        result["partial_lm_fp32_attn"] = train_lm(
            "partial", True, binarized_attention=False
        )
    if args.fp32_twin:
        result["fp32_lm"] = train_lm("fp32", False)
        result["binarization_gap_bits_per_byte"] = round(
            result["bnn_lm"]["valid_bits_per_byte"]
            - result["fp32_lm"]["valid_bits_per_byte"], 4,
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
