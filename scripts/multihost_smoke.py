"""Multi-host elastic smoke (CI): a real two-process world must survive
a SIGKILLed host rank WITHOUT a full-job restart.

Drives :func:`resilience.multihost.run_elastic_multihost` over the
actual CLI — two OS processes on localhost, each a single-process jax
runtime exchanging 1-bit sign_ef gradients over the parallel/hostcomm
TCP collective — with a scripted ``host_lost@step=20,hosts=1`` chaos
rule that makes rank 1 SIGKILL itself mid-epoch-1. Asserts that:

  * the supervisor returns 0: rank 0 noticed the dead socket, vacated
    exit-75 WITHOUT saving the tainted step, and the relaunch at ONE
    host resumed from the newest digest-verified generation (the
    (2, ...) per-host EF rows remesh-folded to world 1);
  * ``membership.json`` records exactly one 2->1 ``lost`` transition
    and the supervisor event log exactly one ``host_membership``
    ``lost`` with ``budget_used=0`` — host loss is membership churn,
    never a retry (RESILIENCE.md "Multi-host elastic membership");
  * zero ``failed``/``preempted``/``timeout`` supervisor events — the
    retry and preemption budgets are untouched;
  * the run LEARNED across the shrink (final test accuracy beats the
    bar — a relaunch that scrambled the folded EF rows would still
    exit 0).

Usage: python scripts/multihost_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHAOS_SPEC = "host_lost@step=20,hosts=1"
HOSTS = 2
MIN_ACC = 50.0


def _read_jsonl(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None,
                        help="work dir (default: a fresh temp dir)")
    parser.add_argument("--keep", action="store_true",
                        help="keep the work dir for inspection")
    args = parser.parse_args(argv)

    from distributed_mnist_bnns_tpu.obs.events import EventLog
    from distributed_mnist_bnns_tpu.resilience import (
        RetryPolicy,
        run_elastic_multihost,
    )
    from distributed_mnist_bnns_tpu.resilience.multihost import (
        read_membership,
    )

    work = args.dir or tempfile.mkdtemp(prefix="multihost_smoke_")
    ckpt_dir = os.path.join(work, "ckpts")
    tel_dir = os.path.join(work, "telemetry")
    results = os.path.join(work, "results.csv")

    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JG_MH_TIMEOUT": "60",
    }
    cmd = [
        sys.executable, "-m", "distributed_mnist_bnns_tpu.cli", "train",
        "--model", "bnn-mlp-small", "--epochs", "3", "--batch-size", "64",
        "--grad-compress", "sign_ef", "--elastic", "--resume",
        "--synthetic-sizes", "1024", "128", "--seed", "0",
        "--chaos", CHAOS_SPEC,
        "--checkpoint-dir", ckpt_dir, "--telemetry-dir", tel_dir,
        "--results", results,
    ]
    print("multihost_smoke: supervising", " ".join(cmd), file=sys.stderr,
          flush=True)

    sup_events_path = os.path.join(work, "supervisor_events.jsonl")
    events = EventLog(sup_events_path)
    failures = []
    try:
        rc = run_elastic_multihost(
            cmd, hosts=HOSTS, store=work, env=env, events=events,
            policy=RetryPolicy(max_restarts=0, max_preemptions=0),
            generation_timeout_s=420.0,
        )
    except Exception as e:  # budget exhausted / world extinct
        rc = -1
        failures.append(f"supervisor raised: {type(e).__name__}: {e}")
    finally:
        events.close()
    if rc != 0:
        failures.append(f"run_elastic_multihost returned {rc} (want 0)")

    view = read_membership(work) or {}
    lost = [h for h in view.get("history", []) if h.get("event") == "lost"]
    if [(h.get("hosts_from"), h.get("hosts_to")) for h in lost] != [(2, 1)]:
        failures.append(
            f"want exactly one 2->1 lost transition in membership.json, "
            f"got {lost}"
        )
    if view.get("hosts") != 1:
        failures.append(
            f"membership.json final world is {view.get('hosts')} (want 1)"
        )

    sup_events = []
    try:
        sup_events = _read_jsonl(sup_events_path)
    except OSError as e:
        failures.append(f"no supervisor event log: {e}")
    sup_lost = [e for e in sup_events if e.get("event") == "lost"]
    if len(sup_lost) != 1:
        failures.append(
            f"want exactly one host_membership lost event, got {sup_lost}"
        )
    elif sup_lost[0].get("budget_used") != 0:
        failures.append(
            "host loss consumed retry budget: "
            f"budget_used={sup_lost[0].get('budget_used')} (want 0)"
        )
    budgeted = [e for e in sup_events
                if e.get("event") in ("failed", "preempted", "timeout")]
    if budgeted:
        failures.append(
            f"supervisor burned budget on membership churn: {budgeted}"
        )
    if [e.get("event") for e in sup_events if e.get("event") == "complete"] \
            != ["complete"]:
        failures.append("want exactly one complete event")

    acc = None
    try:
        with open(results) as f:
            rows = list(csv.DictReader(f))
        acc = float(rows[-1]["test_acc"])
        if acc <= MIN_ACC:
            failures.append(
                f"run did not learn across the host loss: test_acc={acc} "
                f"(want > {MIN_ACC})"
            )
    except (OSError, IndexError, KeyError, ValueError) as e:
        failures.append(f"could not read final accuracy from {results}: {e}")

    # Rank 0's own event log: it must have SEEN the loss (emitted before
    # vacating) and resumed remeshed at world 1 in the next generation.
    trainer_events = []
    try:
        trainer_events = _read_jsonl(os.path.join(tel_dir, "events.jsonl"))
    except OSError as e:
        failures.append(f"no trainer event log: {e}")
    tr_lost = [e for e in trainer_events
               if e.get("kind") == "host_membership"
               and e.get("event") == "lost"]
    if len(tr_lost) != 1:
        failures.append(
            "rank 0 should emit exactly one host_membership lost before "
            f"vacating, got {len(tr_lost)}"
        )

    summary = {
        "exit_code": rc,
        "test_acc": acc,
        "membership": [(h.get("event"), h.get("hosts_from"),
                        h.get("hosts_to")) for h in view.get("history", [])
                       if h.get("event")],
        "supervisor_events": [e.get("event") for e in sup_events],
        "ok": not failures,
    }
    print(json.dumps(summary, indent=2))
    for f_ in failures:
        print(f"FAIL: {f_}", file=sys.stderr)
    if not args.keep and args.dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
