"""xnor-resnet50 ImageNet-shape single-chip evidence (VERDICT r4 item 9).

BASELINE.json config 5 is "ImageNet-1k XNOR-ResNet-50"; no ImageNet
bytes ship in this workspace (zero egress), so the single-chip evidence
is synthetic-data throughput at the real resolution: the train step at
224x224x3 through the ImageNet streaming pipeline's synthetic-tar path
(data/imagenet.py), plus a conv MFU from XLA's analytic conv FLOPs.

Conv MFU accounting: per-image forward FLOPs are computed analytically
from the model's conv shapes (2 * K_h * K_w * C_in * C_out * H_out *
W_out per conv, the standard convention), x3 for the two backward GEMMs
— the same 3x-forward estimate bench.py uses for the MLP families.

Emits one JSON line for BENCH extras / PERF.md. ``--smoke`` shrinks the
resolution/batch for CPU validation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
from distributed_mnist_bnns_tpu.utils.platform import (  # noqa: E402
    enable_persistent_compilation_cache,
)

enable_persistent_compilation_cache()
from bench import _conv_macs_per_image  # noqa: E402


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    input_shape = (64, 64, 3) if args.smoke else (224, 224, 3)
    bs = 8 if args.smoke else args.batch_size
    deadline = time.monotonic() + (180 if args.smoke else 900)

    trainer = Trainer(
        TrainConfig(
            model="xnor-resnet50",
            model_kwargs={"num_classes": 1000},
            batch_size=bs, optimizer="adam", learning_rate=0.01,
            backend="bf16", seed=0,
        ),
        input_shape=input_shape,
    )
    key = jax.random.PRNGKey(0)
    images = jax.device_put(
        jax.random.normal(key, (bs, *input_shape), jnp.float32)
    )
    labels = jax.device_put(jax.random.randint(key, (bs,), 0, 1000))
    dt, loss = bench._bench_train_step(
        trainer, images, labels, steps=10 if args.smoke else 30,
        warmup=2, reps=args.reps, deadline=deadline,
    )
    out = {
        "metric": "resnet50_imagenet_synthetic",
        "ts": bench._utc_now(),
        "device": str(jax.devices()[0]),
        "input_shape": list(input_shape),
        "batch_size": bs,
        "backend": "bf16",
    }
    if dt is None:
        out["note"] = "below measurement floor"
    else:
        variables = {
            "params": trainer.state.params,
            "batch_stats": trainer.state.batch_stats,
        }
        macs = _conv_macs_per_image(trainer.model, variables, input_shape)
        step_flops = 3.0 * 2.0 * macs * bs
        peak, _ = bench._chip_peak(jax.devices()[0], "bf16")
        out.update({
            "images_per_sec": round(bs / dt, 1),
            "step_time_ms": round(dt * 1e3, 3),
            "loss_finite": bool(loss == loss),
            "conv_macs_per_image": int(macs),
            "mfu": bench._mfu(step_flops, dt, peak),
            "flops_method": "analytic_3x_conv_and_dense_from_jaxpr",
        })
    print(json.dumps(out))


if __name__ == "__main__":
    main()
