"""int8 training-headline study (VERDICT r4 item 4).

Round-4 measured the int8 MXU pipeline at ~2x bf16 binary-TOPS on GEMMs
with pre-cast operands, but the flagship headline stayed bf16 because the
standalone fp32->int8 cast pass appeared to eat the win (PERF.md §short
version 2). This script settles it on-chip:

1. GEMM level, flagship training shape (2048x3072x1536), operands
   produced from fp32 *latents* inside the jitted program (the real
   per-step situation, where XLA can fuse sign+convert into the
   producing pass):
     - bf16_from_latent:  dot(sign(x).bf16, sign(w).bf16)
     - int8_from_latent:  dot(sign_int8(x), sign_int8(w)) — sign emits
       int8 directly (select on int8 constants, no fp32 intermediate)
     - int8_cast_pm1:     the round-4 formulation (±1 fp32 args, cast
       in-graph) for continuity with PERF.md's numbers
2. Full train step A/B: Trainer step on backend bf16 vs int8, scan
   dispatch, steady state — the number that decides the headline. The
   backward GEMMs are bf16 in both (gradients are not ±1), so int8 can
   accelerate at most the forward third of step FLOPs.

Emits one JSON line; paste into PERF.md and, if int8 wins end-to-end,
flip bench.py's default --backend.

CPU smoke: ``--smoke`` shrinks shapes/steps so the harness logic runs
anywhere (numbers meaningless off-chip).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (repo-root harness: _measure, _mfu helpers)

# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
from distributed_mnist_bnns_tpu.utils.platform import (  # noqa: E402
    enable_persistent_compilation_cache,
)

enable_persistent_compilation_cache()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    m, k, n = (256, 512, 256) if args.smoke else (2048, 3072, 1536)
    n_short, n_long = (5, 20) if args.smoke else (20, 100)
    deadline = time.monotonic() + (120 if args.smoke else 900)

    key = jax.random.PRNGKey(0)
    latent_x = jax.random.normal(key, (m, k), jnp.float32)
    latent_w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
    pm1_x = jnp.where(latent_x >= 0, 1.0, -1.0)
    pm1_w = jnp.where(latent_w >= 0, 1.0, -1.0)

    def sign_i8(v):
        return jnp.where(v >= 0, jnp.int8(1), jnp.int8(-1))

    bf16_from_latent = jax.jit(lambda x, w: jnp.dot(
        jnp.where(x >= 0, 1.0, -1.0).astype(jnp.bfloat16),
        jnp.where(w >= 0, 1.0, -1.0).astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ))
    int8_from_latent = jax.jit(lambda x, w: jnp.dot(
        sign_i8(x), sign_i8(w), preferred_element_type=jnp.int32,
    ).astype(jnp.float32))
    int8_cast_pm1 = jax.jit(lambda x, w: jnp.dot(
        x.astype(jnp.int8), w.astype(jnp.int8),
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32))

    tops = 2.0 * m * k * n
    gemm = {}
    for name, fn, a, b in (
        ("bf16_from_latent", bf16_from_latent, latent_x, latent_w),
        ("int8_from_latent", int8_from_latent, latent_x, latent_w),
        ("int8_cast_pm1", int8_cast_pm1, pm1_x, pm1_w),
    ):
        dt, _ = bench._measure(
            lambda fn=fn, a=a, b=b: fn(a, b),
            lambda r: float(jnp.sum(r)),
            n_short, n_long, args.reps, deadline,
        )
        gemm[name] = (
            "below measurement floor" if dt is None else {
                "ms": round(dt * 1e3, 4),
                "binary_tops": round(tops / dt / 1e12, 2),
            }
        )

    # -- full train step A/B ------------------------------------------
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    bs = 256 if args.smoke else 4096
    steps = 4 if args.smoke else 64
    step_ab = {}
    for backend in ("bf16", "int8"):
        trainer = Trainer(
            TrainConfig(
                model="bnn-mlp-large", batch_size=bs, optimizer="adam",
                learning_rate=0.01, backend=backend, seed=0,
            ),
            input_shape=(28, 28, 1),
        )
        dt, loss = bench._bench_train_scan(
            trainer, steps, bs, (28, 28, 1), 2, 2, args.reps, deadline,
        )
        if dt is None:
            step_ab[backend] = "below measurement floor"
            continue
        flops_info = bench._step_flops(trainer, bs)
        peak, prec = bench._chip_peak(jax.devices()[0], backend)
        step_ab[backend] = {
            "images_per_sec": round(bs / dt, 1),
            "step_time_ms": round(dt * 1e3, 3),
            "mfu_vs_matched_peak": bench._mfu(
                flops_info[0] if flops_info else None, dt, peak
            ),
            "peak_precision": prec,
        }

    verdict = None
    if (
        isinstance(step_ab.get("bf16"), dict)
        and isinstance(step_ab.get("int8"), dict)
    ):
        r = (step_ab["int8"]["images_per_sec"]
             / step_ab["bf16"]["images_per_sec"])
        verdict = {
            "int8_over_bf16_step_ratio": round(r, 4),
            "headline_backend": "int8" if r > 1.02 else "bf16",
        }
    print(json.dumps({
        "metric": "int8_headline_study",
        "ts": bench._utc_now(),
        "device": str(jax.devices()[0]),
        "shape": [m, k, n],
        "gemm_from_latents": gemm,
        "train_step_ab": step_ab,
        "verdict": verdict,
    }))


if __name__ == "__main__":
    main()
