"""Chaos smoke (tier-1 / CI): a scripted fault sequence must finish.

Runs a short ``fit()`` under ``run_with_policy`` with three injected
faults — a checkpoint corruption, a transient step fault and a SIGTERM-
style preemption — and asserts training completes via generation
rollback + retry + step-granular resume, with every resilience event
kind present in the obs log. Exit 0 = the recovery machinery works end
to end; anything else fails the build (RESILIENCE.md).

Timeline (4 tiny epochs, 4 steps each):
  attempt 1  epoch-0 ckpt lands clean (gen 0); epoch-1 ckpt is
             corrupted in place (chaos); step_fault crashes epoch 2 at
             step 10
  attempt 2  resume rolls back past the corrupt generation to gen 0
             (epoch 0), retrains epochs 1-2, then the preempt fault
             forces a graceful stop mid-epoch-3 before step 13 runs
             (mid-epoch checkpoint: epoch_in_progress=3,
             batch_in_epoch=1)
  attempt 3  resumes epoch 3 at step granularity and finishes

Usage: python scripts/chaos_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXPECTED_KINDS = (
    "fault_injected", "rollback", "graceful_stop", "resume", "restart",
)

# 128 synthetic examples / batch 32 = 4 optimizer steps per epoch.
EPOCHS = 4
STEPS_PER_EPOCH = 4
CHAOS_SPEC = (
    "ckpt_corrupt@epoch=1"          # epoch-1 save: latest+gen_1 corrupt
    ";step_fault@step=10"           # epoch 2, transient crash
    ";preempt@step=13"              # after rollback replay: mid-epoch 3
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None,
                        help="work dir (default: a fresh temp dir)")
    parser.add_argument("--keep", action="store_true",
                        help="keep the work dir for inspection")
    args = parser.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="chaos_smoke_")
    ckpt_dir = os.path.join(work, "ckpts")
    tel_dir = os.path.join(work, "telemetry")

    from distributed_mnist_bnns_tpu.data import load_mnist
    from distributed_mnist_bnns_tpu.obs import Telemetry, load_events
    from distributed_mnist_bnns_tpu.resilience import (
        RetryPolicy,
        reset_fire_counts,
        run_with_policy,
    )
    from distributed_mnist_bnns_tpu.train import TrainConfig, Trainer

    reset_fire_counts()
    data = load_mnist("/nonexistent", synthetic_sizes=(128, 32))

    def make_trainer() -> Trainer:
        return Trainer(TrainConfig(
            model="bnn-mlp-small",
            epochs=EPOCHS,
            batch_size=32,
            backend="xla",
            seed=7,
            checkpoint_dir=ckpt_dir,
            telemetry_dir=tel_dir,
            resume=True,
            chaos=CHAOS_SPEC,
        ))

    # The policy's restart events append to the same events.jsonl the
    # trainers write (each seals its log before the loop emits).
    with Telemetry(tel_dir, heartbeat=False) as policy_tel:
        history = run_with_policy(
            make_trainer,
            lambda t: t.fit(data),
            policy=RetryPolicy(
                max_restarts=3, base_backoff_s=0.05, max_backoff_s=0.2,
                seed=0,
            ),
            telemetry=policy_tel,
        )

    total_steps = EPOCHS * STEPS_PER_EPOCH
    failures = []
    if not history or history[-1]["epoch"] != EPOCHS - 1:
        failures.append(
            f"training did not reach epoch {EPOCHS - 1}: "
            f"{[h['epoch'] for h in history]}"
        )
    events = load_events(os.path.join(tel_dir, "events.jsonl"))
    kinds = {e["kind"] for e in events}
    for kind in EXPECTED_KINDS:
        if kind not in kinds:
            failures.append(f"event log is missing a {kind!r} event")
    resumes = [e for e in events if e["kind"] == "resume"]
    if not any(e.get("batch_in_epoch") for e in resumes):
        failures.append("no step-granular (mid-epoch) resume recorded")
    if not any(e.get("rolled_back") for e in resumes):
        failures.append("no resume went through a generation rollback")
    meta = json.load(open(os.path.join(ckpt_dir, "checkpoint_meta.json")))
    if meta.get("epoch") != EPOCHS - 1 or meta.get("step") != total_steps:
        failures.append(
            f"final checkpoint meta off: epoch={meta.get('epoch')} "
            f"step={meta.get('step')} (want {EPOCHS - 1}/{total_steps})"
        )

    summary = {
        "epochs_completed": [h["epoch"] for h in history],
        "final_step": meta.get("step"),
        "events": {
            k: sum(1 for e in events if e["kind"] == k)
            for k in EXPECTED_KINDS
        },
        "ok": not failures,
    }
    print(json.dumps(summary, indent=2))
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not args.keep and args.dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
