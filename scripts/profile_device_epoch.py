"""Device-resident epoch MFU decomposition (VERDICT r4 item 6).

BENCH_LOCAL_r04 measured the one-dispatch 60k-image epoch at MFU 0.26
vs 0.675 for the steady-state scan — ~60% of the chip idle somewhere in
the epoch program. This script attributes the gap on-chip by timing the
pieces separately:

  A. epoch_fn            — the full one-dispatch epoch (gather + scan)
  B. scan_pregathered    — make_train_scan over the SAME (n_batches, B)
                           data, pre-gathered outside the timed region:
                           isolates the whole-epoch gather cost
  C. gather_only         — images_all[idx] materialized alone
  D. tail                — the epoch's non-full trailing batches and
                           small n_batches amortization are visible by
                           comparing B at n_batches vs the long-scan
                           steady state from bench.py

Identity check: A ≈ B + C within noise, else something else (e.g.
donation/copy) is eating time. Emits one JSON line for PERF.md; pass
``--profile-dir DIR`` to also dump a jax profiler trace of one epoch
dispatch.

CPU smoke: ``--smoke`` shrinks everything (numbers meaningless).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
from distributed_mnist_bnns_tpu.utils.platform import (  # noqa: E402
    enable_persistent_compilation_cache,
)

enable_persistent_compilation_cache()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=4096)
    p.add_argument("--images", type=int, default=60000)
    p.add_argument("--profile-dir", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_mnist_bnns_tpu.data.mnist import shard_indices
    from distributed_mnist_bnns_tpu.train import (
        TrainConfig,
        Trainer,
        make_train_scan,
    )

    bs = 256 if args.smoke else args.batch_size
    n = 4096 if args.smoke else args.images
    deadline = time.monotonic() + (120 if args.smoke else 600)

    trainer = Trainer(
        TrainConfig(
            model="bnn-mlp-large", batch_size=bs, optimizer="adam",
            learning_rate=0.01, backend="bf16", seed=0, device_data=True,
        ),
        input_shape=(28, 28, 1),
    )
    key = jax.random.PRNGKey(0)
    images_all = jax.random.normal(key, (n, 28, 28, 1), jnp.float32)
    labels_all = jax.random.randint(key, (n,), 0, 10)
    idx = shard_indices(n, epoch=0, seed=0, host_id=0, num_hosts=1)
    nb = len(idx) // bs
    idx = jnp.asarray(
        np.asarray(idx[: nb * bs], np.int32).reshape(nb, bs)
    )
    epoch_fn = trainer._get_epoch_fn()
    rng = trainer.rng

    def timed(run, fetch, n_short=1, n_long=3):
        dt, _ = bench._measure(run, fetch, n_short, n_long,
                               args.reps, deadline)
        return dt

    holder = {}

    # A. full epoch dispatch
    def run_epoch():
        trainer.state, holder["m"] = epoch_fn(
            trainer.state, images_all, labels_all, idx, rng
        )
        return holder["m"]

    run_epoch()
    t_epoch = timed(run_epoch, lambda m: float(m["loss"]))

    # C. the whole-epoch gather alone
    gather = jax.jit(lambda im, lb, idx: (im[idx], lb[idx]))

    def run_gather():
        return gather(images_all, labels_all, idx)

    run_gather()
    t_gather = timed(
        run_gather, lambda r: float(jnp.sum(r[0][0, 0])),
    )

    # B. scan over pre-gathered batches (no gather in the timed program)
    im_seq, lb_seq = jax.block_until_ready(run_gather())
    scan = make_train_scan(
        trainer.clamp_mask, loss_fn=trainer._loss_fn, donate=False,
    )

    def run_scan():
        trainer.state, holder["m"] = scan(
            trainer.state, im_seq, lb_seq, rng
        )
        return holder["m"]

    run_scan()
    t_scan = timed(run_scan, lambda m: float(m["loss"]))

    flops_info = bench._step_flops(trainer, nb * bs)
    peak, _ = bench._chip_peak(jax.devices()[0], "bf16")

    def mfu(t):
        return bench._mfu(flops_info[0] if flops_info else None, t, peak)

    if args.profile_dir:
        from distributed_mnist_bnns_tpu.utils.profiling import trace

        with trace(args.profile_dir):
            jax.block_until_ready(run_epoch()["loss"])

    out = {
        "metric": "device_resident_epoch_breakdown",
        "ts": bench._utc_now(),
        "device": str(jax.devices()[0]),
        "batch_size": bs,
        "n_batches": nb,
        "epoch_s": None if t_epoch is None else round(t_epoch, 4),
        "scan_pregathered_s": None if t_scan is None else round(t_scan, 4),
        "gather_only_s": None if t_gather is None else round(t_gather, 4),
        "mfu_epoch": mfu(t_epoch),
        "mfu_scan_pregathered": mfu(t_scan),
        "identity_residual_s": (
            None
            if None in (t_epoch, t_scan, t_gather)
            else round(t_epoch - t_scan - t_gather, 4)
        ),
        "note": "epoch ~= scan + gather => the gather is the gap; "
                "large residual => look elsewhere (donation copies, "
                "metric reductions)",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
