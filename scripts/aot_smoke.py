"""AOT smoke (CI): zero-compile cold starts must actually be zero.

The end-to-end proof of PERF.md "Cold start": export tiny classifier
and LM artifacts, `cli aot build` the store in one subprocess, then
boot the REAL `cli serve` and `cli serve --lm` servers from it (fresh
processes, fresh jax persistent cache) and assert from /healthz that

  * the boot was an AOT hit,
  * ``recompiles_post_boot`` / ``recompiles_post_warmup`` == 0 — from
    BOOT, not merely post-warmup (the fence baseline is pinned at the
    pre-load mark on a hit),
  * real traffic round-trips (predict + a streamed generation),
  * a hot reload served FROM the store keeps the count at zero,
  * SIGTERM drains to exit 0 (the budget-0 fence stayed green for the
    whole run).

Usage: python scripts/aot_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(base: str, path: str, timeout: float = 5.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _post(base: str, path: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read().decode()


def _wait_healthy(base: str, proc, failures, what: str) -> bool:
    for _ in range(240):
        try:
            code, _h = _get(base, "/healthz", timeout=2)
            if code == 200:
                return True
        except OSError:
            pass
        if proc.poll() is not None:
            failures.append(
                f"{what}: server died at startup (rc {proc.returncode})"
            )
            return False
        time.sleep(0.5)
    failures.append(f"{what}: never became healthy")
    return False


def _drain(proc, failures, what: str) -> None:
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        failures.append(f"{what}: no drain within 60s of SIGTERM")
        return
    if rc != 0:
        failures.append(f"{what}: exited {rc} after SIGTERM (want 0)")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None)
    parser.add_argument("--keep", action="store_true")
    args = parser.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="aot_smoke_")
    store = os.path.join(work, "aot_store")
    failures: list = []

    # artifacts (in-process; backend-independent numpy msgpack) — the
    # shared constructor with bench.py --cold-start-bench
    from distributed_mnist_bnns_tpu.aot.coldstart import (
        make_tiny_artifacts,
    )

    cls_artifact, lm_artifact = make_tiny_artifacts(work)

    def env_fresh_cache():
        return {
            **os.environ, "JAX_PLATFORMS": "cpu",
            "JAX_COMPILATION_CACHE_DIR": tempfile.mkdtemp(dir=work),
        }

    # -- build the store (a subprocess, as an operator would)
    build = subprocess.run(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli", "aot",
         "build", "--store", store,
         "--artifact", cls_artifact, "--batch-size", "8",
         "--lm-artifact", lm_artifact, "--slots", "2",
         "--page-size", "8", "--interpret"],
        env=env_fresh_cache(), cwd=REPO,
        capture_output=True, text=True, timeout=600,
    )
    if build.returncode != 0:
        print(f"FAIL: aot build rc {build.returncode}: "
              f"{build.stderr[-800:]}", file=sys.stderr)
        return 1
    print("aot build:", build.stdout.strip())

    ls = subprocess.run(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli", "aot",
         "ls", "--store", store, "--json"],
        env=env_fresh_cache(), cwd=REPO,
        capture_output=True, text=True, timeout=120,
    )
    entries = json.loads(ls.stdout) if ls.returncode == 0 else []
    names = {e.get("name") for e in entries}
    for want in ("classifier_predict", "lm_prefill", "lm_decode"):
        if want not in names:
            failures.append(f"aot ls: store is missing {want!r}")

    # -- classifier server from the warm store
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
         "serve", "--artifact", cls_artifact, "--port", str(port),
         "--batch-size", "8", "--interpret",
         "--aot", "--aot-dir", store,
         "--log-file", os.path.join(work, "serve.log")],
        env=env_fresh_cache(), cwd=REPO,
    )
    try:
        if _wait_healthy(base, proc, failures, "serve"):
            _, h = _get(base, "/healthz")
            if h.get("aot") != "hit":
                failures.append(f"serve: aot={h.get('aot')!r}, want hit")
            if h.get("recompiles_post_boot") != 0:
                failures.append(
                    "serve: recompiles_post_boot="
                    f"{h.get('recompiles_post_boot')}, want 0"
                )
            img = [[[0.1 * ((i + j) % 7)] for j in range(28)]
                   for i in range(28)]
            code, body = _post(base, "/predict", {"images": [img]})
            if code != 200:
                failures.append(f"serve: predict returned {code}")
            # hot reload served FROM the store: zero compiles must hold
            code, _b = _post(base, "/admin/reload", {}, timeout=120)
            if code != 200:
                failures.append(f"serve: reload returned {code}")
            _, h = _get(base, "/healthz")
            if h.get("recompiles_post_boot") != 0:
                failures.append(
                    "serve: post-reload recompiles_post_boot="
                    f"{h.get('recompiles_post_boot')}, want 0 (reload "
                    "must be served from the store)"
                )
            if h.get("status") != "ok":
                failures.append(f"serve: status {h.get('status')!r} "
                                "(fence must stay green)")
        _drain(proc, failures, "serve")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # -- LM server from the warm store
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
         "serve", "--lm", "--artifact", lm_artifact,
         "--port", str(port), "--slots", "2", "--page-size", "8",
         "--interpret", "--aot", "--aot-dir", store,
         "--log-file", os.path.join(work, "lm_serve.log")],
        env=env_fresh_cache(), cwd=REPO,
    )
    try:
        if _wait_healthy(base, proc, failures, "lm"):
            _, h = _get(base, "/healthz")
            if h.get("aot") != "hit":
                failures.append(f"lm: aot={h.get('aot')!r}, want hit")
            if h.get("recompiles_post_warmup") != 0:
                failures.append(
                    "lm: recompiles_post_warmup="
                    f"{h.get('recompiles_post_warmup')}, want 0 from "
                    "boot"
                )
            code, body = _post(
                base, "/generate",
                {"prompt": [1, 2, 3], "max_new_tokens": 6}, timeout=120,
            )
            lines = [json.loads(ln) for ln in body.strip().splitlines()]
            if code != 200 or not lines or \
                    lines[-1].get("status") != "ok":
                failures.append(f"lm: generate {code}: {body[:200]}")
            _, h = _get(base, "/healthz")
            if h.get("recompiles_post_warmup") != 0:
                failures.append(
                    "lm: post-traffic recompiles="
                    f"{h.get('recompiles_post_warmup')}, want 0"
                )
            if h.get("status") != "ok":
                failures.append(f"lm: status {h.get('status')!r}")
        _drain(proc, failures, "lm")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    print(json.dumps({"store_entries": sorted(names),
                      "ok": not failures}))
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not args.keep and args.dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
