"""Profile smoke (CI): on-demand device introspection on a live,
fence-armed server (ISSUE 14, OBSERVABILITY.md "Device profiling").

Boots a real ``cli serve --lm`` subprocess (its budget-0 recompile
fence is armed by default) with the cost ledger on (``JG_COSTS=1``) and
tracing armed, drives generation traffic through it, then — mid-traffic
— hits ``POST /admin/profile`` and asserts the whole device-side story:

  * the capture succeeds off-path (traffic keeps streaming through the
    window) and reports a non-empty artifact dir;
  * the artifact is LOADABLE: the Chrome-trace half parses, and its
    step markers carry a ``jg_trace`` id that matches a trace id in the
    host span events — the host-trace <-> device-profile join;
  * a ``profile_capture`` event landed in the events log;
  * ``/healthz`` carries the per-program cost ledger (flops + measured
    MFU for the compiled programs) and the paged-pool HBM attribution;
  * ``recompiles_post_warmup == 0`` AFTER the capture — arming
    profiling + costs kept the one-compiled-signature contract;
  * SIGTERM drains to exit 0 with the telemetry sealed.

Usage: python scripts/profile_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None,
                        help="work dir (default: a fresh temp dir)")
    parser.add_argument("--keep", action="store_true",
                        help="keep the work dir for inspection")
    args = parser.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="profile_smoke_")
    tel_dir = os.path.join(work, "telemetry")
    artifact = os.path.join(work, "lm_packed.msgpack")

    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.infer import export_packed
    from distributed_mnist_bnns_tpu.models.transformer import BinarizedLM
    from distributed_mnist_bnns_tpu.obs import load_events
    from distributed_mnist_bnns_tpu.obs.profile import summarize_capture
    from distributed_mnist_bnns_tpu.serve.lm import client as lc

    model = BinarizedLM(
        vocab=64, max_len=64, embed_dim=32, depth=1, num_heads=2,
        attention="xla", backend="xla",
    )
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, tokens)
    export_packed(model, variables, artifact)

    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "JG_COSTS": "1"}
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
            "serve", "--lm",
            "--artifact", artifact,
            "--port", str(port),
            "--slots", "2",
            "--page-size", "8",
            "--prefill-chunk", "8",
            "--queue-depth", "4",
            "--telemetry-dir", tel_dir,
            "--trace",
            "--interpret",
            "--log-file", os.path.join(work, "profile_smoke.log"),
        ],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    )

    failures = []
    try:
        for _ in range(240):   # jax import + warmup compiles are slow
            try:
                if lc.healthz(base, timeout=2)[0] == 200:
                    break
            except OSError:
                pass
            if proc.poll() is not None:
                print(f"FAIL: server died at startup (rc {proc.returncode})",
                      file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print("FAIL: server never became healthy", file=sys.stderr)
            return 1

        # Continuous traffic through the capture window: repeated short
        # generations so decode iterations keep dispatching.
        stop = [False]
        stream_fail = []

        def traffic() -> None:
            i = 0
            while not stop[0]:
                i += 1
                try:
                    code, _ = lc.generate(
                        base, [1 + (i % 8), 2, 3], max_new_tokens=16,
                        deadline_ms=60000, timeout=90,
                    )
                    if code != 200:
                        stream_fail.append(f"generate rc {code}")
                except OSError as e:
                    if not stop[0]:
                        stream_fail.append(f"transport: {e}")

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(1.0)

        # -- the on-demand capture, mid-traffic ---------------------------
        code, cap = _post(base, "/admin/profile", {"duration_ms": 1500})
        if code != 200:
            failures.append(f"/admin/profile -> {code}: {cap}")
            cap = {}
        if cap and not (cap.get("files", 0) > 0
                        and cap.get("total_bytes", 0) > 0):
            failures.append(f"capture artifact empty: {cap}")

        stop[0] = True
        t.join(timeout=90)
        if stream_fail:
            failures.append(
                f"traffic failed during capture: {stream_fail[:3]}"
            )

        # -- healthz: fence + cost ledger + pool census -------------------
        _, health_raw = lc.healthz(base, timeout=10)
        health = json.loads(health_raw)
        if health.get("recompiles_post_warmup") != 0:
            failures.append(
                "recompiles_post_warmup != 0 after the capture: "
                f"{health.get('recompiles_post_warmup')} "
                f"(fence_error={health.get('fence_error')})"
            )
        programs = health.get("programs") or {}
        for prog in ("lm_prefill", "lm_decode"):
            row = programs.get(prog) or {}
            if not row.get("flops"):
                failures.append(f"/healthz programs missing {prog}: {row}")
        if not (programs.get("lm_decode") or {}).get("dispatches"):
            failures.append("lm_decode has no measured dispatches")
        pool = health.get("kv_pool") or {}
        if not pool.get("reserved_bytes"):
            failures.append(f"kv_pool census missing: {pool}")

        # -- graceful drain ----------------------------------------------
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        if rc != 0:
            failures.append(f"SIGTERM drain exit {rc} (want 0)")

        # -- events + the host<->device join ------------------------------
        events = load_events(os.path.join(tel_dir, "events.jsonl"))
        kinds = {e.get("kind") for e in events}
        for kind in ("profile_capture", "program_cost", "drain"):
            if kind not in kinds:
                failures.append(f"missing {kind} event")
        if cap.get("dir"):
            try:
                summary = summarize_capture(cap["dir"])
                if summary["annotated_steps"] < 1:
                    failures.append(
                        "capture has no jg_step markers "
                        f"({summary['events']} events)"
                    )
                span_traces = {
                    e.get("trace") for e in events
                    if e.get("kind") == "span"
                }
                if not any(tid in span_traces
                           for tid in summary["trace_ids"]):
                    failures.append(
                        "no capture trace id joins the host span "
                        f"events ({summary['trace_ids'][:3]})"
                    )
            except (OSError, ValueError, KeyError) as e:
                failures.append(f"capture not loadable: {e}")

    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        if args.keep:
            print(f"work dir kept: {work}", file=sys.stderr)
        elif args.dir is None:
            shutil.rmtree(work, ignore_errors=True)

    if failures:
        print("PROFILE SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(json.dumps({
        "ok": True,
        "capture_bytes": cap.get("total_bytes"),
        "programs": sorted((health.get("programs") or {})),
    }))
    return 0


def _post(base: str, path: str, body: dict, timeout: float = 60.0):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        {"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


if __name__ == "__main__":
    sys.exit(main())
