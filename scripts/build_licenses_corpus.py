"""Assemble the repo's external byte corpus (VERDICT r4 item 7).

Round 4's LM evidence used the framework's own source as the corpus —
self-referential. This image has no downloadable datasets (zero egress)
and no bundled NLP corpora (nltk data absent), so the best available
non-self-referential English prose is /usr/share/common-licenses: the
GNU/Apache/MPL/CC0 license texts, ~300 KB of real legal-register
English whose verbatim redistribution is explicitly permitted by every
one of them.

Deterministic assembly: files sorted by name, symlink duplicates
(e.g. GPL -> GPL-3) dropped by realpath, concatenated with a one-line
header each. The output is committed at data_files/licenses_corpus.txt
so training is reproducible off this image too.
"""

from __future__ import annotations

import os

SRC = "/usr/share/common-licenses"
DST = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "data_files", "licenses_corpus.txt",
)


def main() -> None:
    seen = set()
    parts = []
    for name in sorted(os.listdir(SRC)):
        path = os.path.join(SRC, name)
        real = os.path.realpath(path)
        if real in seen or not os.path.isfile(real):
            continue
        seen.add(real)
        with open(real, "rb") as f:
            body = f.read()
        parts.append(f"===== {name} =====\n".encode() + body + b"\n")
    os.makedirs(os.path.dirname(DST), exist_ok=True)
    with open(DST, "wb") as f:
        f.write(b"".join(parts))
    print(f"{DST}: {os.path.getsize(DST)} bytes from {len(seen)} licenses")


if __name__ == "__main__":
    main()
