"""Conv-family binarization-gap study (VERDICT r4 item 3).

BASELINE.json config 4 names "CIFAR-10 XNOR-ResNet-18", but CIFAR-10 is
not shippable in this workspace: zero network egress and no CIFAR bytes
anywhere in the image (the keras loader present under site-packages
downloads on first use, which cannot happen here). What IS real data is
the vendored MNIST t10k split (9k train / 1k test — RESULTS.md's
established methodology), and the XnorResNet CIFAR stem consumes any
HWC resolution, so the conv-family control the item actually needs —
xnor-resnet18 vs an architecture-identical fp32-resnet18, multi-seed,
real data — runs on that split.

Writes RESULTS_CONV.md via examples/accuracy_report (which computes the
twin gap) and prints the per-model accuracies. Sized for a live TPU
window; on CPU expect ~2 h.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor JAX_PLATFORMS over the image's sitecustomize (remote-TPU
# plugin); raises if a backend already initialized on the wrong platform.
from distributed_mnist_bnns_tpu.utils.platform import (
    enable_persistent_compilation_cache,
    pin_platform_from_env,
)

pin_platform_from_env()
# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
enable_persistent_compilation_cache()

from distributed_mnist_bnns_tpu.examples.accuracy_report import run  # noqa: E402


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=15)
    p.add_argument("--seeds", type=int, nargs="+", default=[42, 43, 44])
    p.add_argument("--out", default="RESULTS_CONV.md")
    args = p.parse_args()
    run(
        ["xnor-resnet18", "fp32-resnet18"],
        epochs=args.epochs, batch_size=64, lr=0.01,
        seeds=args.seeds, out_path=args.out, scan_steps=4,
        cache_path=args.out + ".cache.json",
    )


if __name__ == "__main__":
    main()
