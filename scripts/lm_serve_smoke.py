"""LM-serve smoke (CI): continuous batching must survive chaos.

The generation mirror of scripts/serve_smoke.py: exports a tiny packed
LM artifact, starts ``cli serve --lm`` as a real subprocess with chaos
injecting decode stalls and transient backend errors, then drives
staggered-length concurrent streaming requests through it and asserts:

  * every stream finishes ``ok`` with exactly its requested token count
    despite the injected faults (transient decode errors are retried —
    the decode step is pure, a failed attempt mutates nothing);
  * tokens arrive INCREMENTALLY (the chaos stalls spread the stream in
    time — a burst-at-close would mean buffering, not streaming);
  * a late request JOINS MID-STREAM: its ``lm_admit`` iteration falls
    strictly inside another stream's decode window (event log);
  * a queued request whose deadline expires before admission gets a
    prompt **504** and frees nothing (``lm_evict`` with status
    ``deadline`` and ``pages_freed == 0``);
  * ZERO post-warmup recompiles (/healthz ``recompiles_post_warmup``) —
    the one-compiled-signature contract held while sequences joined and
    left;
  * every page is back in the pool when traffic ends, and SIGTERM
    drains to **exit 0** with a ``drain`` event.

Both ISSUE-13 features ride the whole scenario (``--prefix-cache
--spec-decode 4``): the streams share a system-prompt prefix, so a
late admission must land a **prefix hit** that skipped prefill work
(``lm_prefix_hit`` + the ``lm_admit`` prefill-tokens delta), the
faults above fire while speculative rounds run (draft acceptance
visible in ``lm_spec_tokens_total``), an idle engine's held pages are
exactly the cache's (shared-page accounting in /healthz), the cache is
fully evictable at drain (``drain`` event: ``pages_in_use == 0``), and
the budget-0 recompile fence stays green with all THREE compiled
programs in flight.

Tracing rides the whole scenario (``--trace``, OBSERVABILITY.md
"Tracing"): every completed stream must leave a CLOSED span tree
(root ``lm.request`` + queue/prefill/decode children, parents
resolving) joined to its ``lm_evict`` event by id, the export must be
Perfetto-loadable Chrome-trace JSON, and the zero-post-warmup-
recompile check above now runs WITH tracing on — the budget-0 fence
must stay green while spans flow.

A second, chaos-free phase arms the Pallas serving path (``--kernels``:
in-kernel page-table-walk attention + fused bitplane-unpack GEMM, in
interpret mode on CPU) and replays the same prompts through a kernel
server and a gather server: the outputs must be TOKEN-IDENTICAL, both
boots must hold ``recompiles_post_warmup == 0`` with the budget-0 fence
green (the kernel path compiles the same three-program set — see
SERVING.md "Zero-recompile serving"), and /healthz must report which
path is armed.

Usage: python scripts/lm_serve_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAOS_SPEC = (
    "infer_slow@step=4,times=3,delay_s=0.25"   # stalls: streams spread,
                                               # the queued probe 504s
    ";infer_error@step=8,times=2"              # transient: retried —
                                               # early enough that spec
                                               # rounds (≈K tokens per
                                               # iteration) still reach
                                               # it before streams end
)
EXPECTED_KINDS = ("lm_admit", "lm_evict", "fault_injected", "drain",
                  "lm_prefix_hit")
STREAMS = ((0.0, 24), (0.15, 8), (0.3, 12))    # (start delay s, max_new)
# Shared system prompt: two full 8-token pages, so the stream admitted
# after another's eviction must fork them as a prefix hit.
SYSTEM_PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def kernel_path_phase(artifact: str, work: str, failures: list) -> dict:
    """Gather vs Pallas-kernel serving path, token-identity acceptance.

    Boots the server twice against the same artifact — once on the
    gather (oracle) path, once with ``--kernels`` — runs the same
    greedy prompts through each, and asserts identical token streams,
    zero post-warmup recompiles on BOTH boots (same three compiled
    programs either way), a green budget-0 fence, and a clean SIGTERM
    drain. Returns a summary dict for the smoke's JSON output."""
    from distributed_mnist_bnns_tpu.serve.lm import client as lc

    prompts = [SYSTEM_PROMPT + [7, 2, 3], [5, 4, 3, 2, 1]]
    tokens_by = {}
    health_by = {}
    for variant, extra in (("gather", []), ("kernels", ["--kernels"])):
        port = _free_port()
        base = f"http://127.0.0.1:{port}"
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
                "serve", "--lm",
                "--artifact", artifact,
                "--port", str(port),
                "--slots", "2",
                "--page-size", "8",
                "--prefill-chunk", "8",
                "--queue-depth", "4",
                "--spec-decode", "4",
                "--interpret",
                "--log-file",
                os.path.join(work, f"lm_serve_{variant}.log"),
                *extra,
            ],
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        )
        toks_all = []
        try:
            for _ in range(240):
                try:
                    if lc.healthz(base, timeout=2)[0] == 200:
                        break
                except OSError:
                    pass
                if proc.poll() is not None:
                    failures.append(
                        f"kernel phase: {variant} server died at startup "
                        f"(rc {proc.returncode})"
                    )
                    return {}
                time.sleep(0.5)
            else:
                failures.append(
                    f"kernel phase: {variant} server never became healthy"
                )
                return {}
            for p in prompts:
                code, events = lc.generate(
                    base, p, max_new_tokens=12,
                    deadline_ms=120000, timeout=120,
                )
                if code != 200:
                    failures.append(
                        f"kernel phase: {variant} generate got HTTP {code}"
                    )
                    toks_all.append(None)
                    continue
                done = events[-1] if events else {}
                if done.get("status") != "ok":
                    failures.append(
                        f"kernel phase: {variant} stream did not finish "
                        f"ok: {done}"
                    )
                toks_all.append(
                    [e["token"] for e in events if "token" in e]
                )
            code, body = lc.healthz(base)
            health = json.loads(body) if code == 200 else {}
            health_by[variant] = health
            if health.get("recompiles_post_warmup") != 0:
                failures.append(
                    f"kernel phase: {variant} path recompiled post-"
                    f"warmup ({health.get('recompiles_post_warmup')}, "
                    "want 0) — the Pallas/gather flip must not leak "
                    "extra compiled signatures"
                )
            if health.get("fence_error"):
                failures.append(
                    f"kernel phase: {variant} fence error: "
                    f"{health['fence_error']}"
                )
            want_kernels = variant == "kernels"
            if bool(health.get("kernels")) != want_kernels:
                failures.append(
                    f"kernel phase: /healthz reports kernels="
                    f"{health.get('kernels')!r} on the {variant} boot"
                )
            proc.send_signal(signal.SIGTERM)
            try:
                rc = proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
                failures.append(
                    f"kernel phase: {variant} server did not drain "
                    "within 60s of SIGTERM"
                )
            if rc != 0:
                failures.append(
                    f"kernel phase: {variant} server exited {rc} after "
                    "SIGTERM (want 0)"
                )
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        tokens_by[variant] = toks_all
    if tokens_by.get("gather") != tokens_by.get("kernels"):
        failures.append(
            "kernel phase: Pallas path tokens differ from the gather "
            f"oracle — gather={tokens_by.get('gather')} "
            f"kernels={tokens_by.get('kernels')}"
        )
    return {
        "token_identical": tokens_by.get("gather")
        == tokens_by.get("kernels"),
        "recompiles_post_warmup": {
            v: h.get("recompiles_post_warmup")
            for v, h in health_by.items()
        },
        "kernels_flag": {
            v: h.get("kernels") for v, h in health_by.items()
        },
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None,
                        help="work dir (default: a fresh temp dir)")
    parser.add_argument("--keep", action="store_true",
                        help="keep the work dir for inspection")
    args = parser.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="lm_serve_smoke_")
    tel_dir = os.path.join(work, "telemetry")
    artifact = os.path.join(work, "lm_packed.msgpack")

    import jax
    import jax.numpy as jnp

    from distributed_mnist_bnns_tpu.infer import export_packed
    from distributed_mnist_bnns_tpu.models.transformer import BinarizedLM
    from distributed_mnist_bnns_tpu.obs import load_events
    from distributed_mnist_bnns_tpu.serve.lm import client as lc

    model = BinarizedLM(
        vocab=64, max_len=64, embed_dim=32, depth=1, num_heads=2,
        attention="xla", backend="xla",
    )
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, tokens)
    export_packed(model, variables, artifact)

    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
            "serve", "--lm",
            "--artifact", artifact,
            "--port", str(port),
            "--slots", "2",
            "--page-size", "8",
            "--prefill-chunk", "8",
            "--queue-depth", "4",
            "--prefix-cache",
            "--spec-decode", "4",
            "--telemetry-dir", tel_dir,
            "--trace",
            "--chaos", CHAOS_SPEC,
            "--interpret",
            "--log-file", os.path.join(work, "lm_serve.log"),
        ],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    )

    failures = []
    results = {}
    lock = threading.Lock()
    try:
        for _ in range(240):   # jax import + warmup compiles are slow
            try:
                if lc.healthz(base, timeout=2)[0] == 200:
                    break
            except OSError:
                pass
            if proc.poll() is not None:
                print(f"FAIL: server died at startup (rc {proc.returncode})",
                      file=sys.stderr)
                return 1
            time.sleep(0.5)
        else:
            print("FAIL: server never became healthy", file=sys.stderr)
            return 1

        def stream(tid: int, delay: float, max_new: int) -> None:
            time.sleep(delay)
            stamps = []
            toks = []
            done = None
            try:
                code, resp = lc.open_stream(
                    base, SYSTEM_PROMPT + [1 + tid, 2, 3],
                    max_new_tokens=max_new,
                    deadline_ms=120000, timeout=120,
                )
                if code == 200:
                    for ev in lc.iter_lines(resp):
                        stamps.append(time.monotonic())
                        if "token" in ev:
                            toks.append(ev["token"])
                        else:
                            done = ev
            except OSError as e:
                code = -1
                print(f"stream[{tid}]: transport error {e}",
                      file=sys.stderr)
            with lock:
                results[tid] = {
                    "code": code, "tokens": toks, "done": done,
                    "span_s": (stamps[-1] - stamps[0]) if len(stamps) > 1
                    else 0.0,
                }

        threads = [
            threading.Thread(target=stream, args=(i, d, n))
            for i, (d, n) in enumerate(STREAMS)
        ]
        for t in threads:
            t.start()

        # With 2 slots and 3 live streams, this probe queues behind them;
        # the chaos stalls guarantee its 50 ms deadline expires first ->
        # a prompt 504 whose pages were never allocated.
        time.sleep(0.5)
        t0 = time.monotonic()
        code_504, _body = lc.generate(
            base, [9, 9], max_new_tokens=4, deadline_ms=50, timeout=30
        )
        took_504 = time.monotonic() - t0
        if code_504 != 504:
            failures.append(f"queued-deadline probe got {code_504}, "
                            "want 504")
        elif took_504 > 5.0:
            failures.append(f"504 took {took_504:.2f}s — not prompt")

        for t in threads:
            t.join(timeout=180)
        if any(t.is_alive() for t in threads):
            failures.append("stream thread hung")
        for tid, (_d, max_new) in enumerate(STREAMS):
            r = results.get(tid)
            if r is None:
                failures.append(f"stream {tid} produced no result")
                continue
            if r["code"] != 200:
                failures.append(f"stream {tid} got HTTP {r['code']}")
                continue
            if r["done"] is None or r["done"].get("status") != "ok":
                failures.append(
                    f"stream {tid} did not finish ok: {r['done']}"
                )
            if len(r["tokens"]) != max_new:
                failures.append(
                    f"stream {tid} emitted {len(r['tokens'])}/{max_new} "
                    "tokens"
                )
        # incremental streaming: the longest stream must span the chaos
        # stalls, not arrive as one burst at close
        if results.get(0, {}).get("span_s", 0.0) < 0.2:
            failures.append(
                f"stream 0 arrived as a burst "
                f"(span {results.get(0, {}).get('span_s')}s) — tokens "
                "must stream incrementally"
            )

        code, body = lc.healthz(base)
        health = json.loads(body) if code == 200 else {}
        if health.get("recompiles_post_warmup") != 0:
            failures.append(
                "post-warmup recompiles: "
                f"{health.get('recompiles_post_warmup')} (want 0) — the "
                "one-compiled-signature contract broke with prefix "
                "caching AND spec decode armed (three programs)"
            )
        # With the prefix cache on, an idle engine's held pages must be
        # EXACTLY the cache's published entries — anything else is a
        # stream leaking pages.
        if health.get("pages_in_use") != health.get(
            "prefix_cache_entries"
        ):
            failures.append(
                f"{health.get('pages_in_use')} pages held at idle but "
                f"the prefix cache owns {health.get('prefix_cache_entries')}"
                " — a stream leaked pages"
            )
        if not health.get("prefix_cache_entries"):
            failures.append(
                "no prefix-cache entries after eviction — publication "
                "back to the index never happened"
            )
        rate = health.get("spec_acceptance_rate")
        if rate is None or rate < 0.5:
            failures.append(
                f"spec acceptance rate {rate!r} (want >= 0.5): the "
                "packed draft and bf16 verifier carry the same weights"
            )
        if health.get("fence_error"):
            failures.append(f"fence error: {health['fence_error']}")
        code, body = lc.metrics(base)
        snap = json.loads(body) if code == 200 else {}
        accepted = sum(
            s["value"]
            for s in snap.get("lm_spec_tokens_total", {}).get(
                "series", []
            )
            if s["labels"].get("outcome") == "accepted"
        )
        if not accepted:
            failures.append(
                "lm_spec_tokens_total{outcome=accepted} is zero — "
                "speculative rounds never ran (or never accepted)"
            )
        prefix_hits = sum(
            s["value"]
            for s in snap.get("lm_prefix_cache_hits_total", {}).get(
                "series", []
            )
            if s["labels"].get("result") == "hit"
        )
        if not prefix_hits:
            failures.append(
                "lm_prefix_cache_hits_total{result=hit} is zero — no "
                "admission found the shared system prompt"
            )

        # graceful drain: SIGTERM -> flush -> exit 0
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            rc = proc.wait()
            failures.append("server did not drain within 60s of SIGTERM")
        if rc != 0:
            failures.append(f"server exited {rc} after SIGTERM (want 0)")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    events = load_events(os.path.join(tel_dir, "events.jsonl"))
    kinds = {e["kind"] for e in events}
    for kind in EXPECTED_KINDS:
        if kind not in kinds:
            failures.append(f"event log is missing a {kind!r} event")
    admits = [e for e in events if e["kind"] == "lm_admit"]
    evicts = [e for e in events if e["kind"] == "lm_evict"]
    # mid-stream join: some admission iteration falls strictly inside
    # another stream's (admit, evict) decode window
    joined_mid_stream = any(
        a["iteration"] > 0
        and any(
            b["id"] != a["id"]
            and b["iteration"] < a["iteration"] < e["iteration"]
            for b in admits
            for e in evicts
            if b["id"] == e["id"]
        )
        for a in admits
    )
    if not joined_mid_stream:
        failures.append(
            "no request joined while another was mid-decode "
            f"(admit iters {[a['iteration'] for a in admits]}, evict "
            f"iters {[e['iteration'] for e in evicts]})"
        )
    deadline_evicts = [e for e in evicts if e["status"] == "deadline"]
    if not deadline_evicts:
        failures.append("no lm_evict with status=deadline (504 path)")
    elif any(e["pages_freed"] != 0 for e in deadline_evicts):
        failures.append(
            "queued-deadline eviction reported pages_freed != 0 — it "
            "must never have allocated"
        )
    drains = [e for e in events if e["kind"] == "drain"]
    if drains and not drains[-1].get("flushed"):
        failures.append("drain did not flush streaming work")
    # prefix-cache acceptance: a later admission skipped prefill work —
    # its lm_admit carries cached_tokens > 0 and a prefill-tokens delta
    # strictly below its prompt length (the counter only grew by the
    # suffix), corroborated by an lm_prefix_hit event.
    prefix_hits_ev = [e for e in events if e["kind"] == "lm_prefix_hit"]
    hit_admits = [a for a in admits if a.get("cached_tokens", 0) > 0]
    if not hit_admits:
        failures.append(
            "no lm_admit with cached_tokens > 0 — the shared system "
            "prompt never hit the prefix index"
        )
    elif not all(
        a["prefill_tokens"] == a["prompt_tokens"] - a["cached_tokens"]
        for a in hit_admits
    ):
        failures.append(
            "a prefix-hit admission's prefill_tokens delta does not "
            "equal prompt - cached (prefill work was not skipped): "
            f"{hit_admits}"
        )
    if len(prefix_hits_ev) != len(hit_admits):
        failures.append(
            f"{len(prefix_hits_ev)} lm_prefix_hit events vs "
            f"{len(hit_admits)} cache-hit admissions"
        )
    # drain accounting: the cache must be fully evictable at drain —
    # after the final flush every page is back in the pool and the
    # index is empty.
    if drains:
        if drains[-1].get("pages_in_use") != 0:
            failures.append(
                f"drain left {drains[-1].get('pages_in_use')} pages in "
                "use — the prefix cache was not fully evictable"
            )
        if drains[-1].get("prefix_cache_entries") != 0:
            failures.append(
                "drain left prefix-cache entries behind: "
                f"{drains[-1].get('prefix_cache_entries')}"
            )
    # spec decode under chaos: the injected infer_error transients must
    # have fired DURING spec rounds and been retried (streams above all
    # finished ok with exact token counts).
    if not any(
        e.get("fault") == "infer_error"
        for e in events if e["kind"] == "fault_injected"
    ):
        failures.append(
            "chaos infer_error never fired — the spec-round retry path "
            "went unexercised"
        )

    # -- tracing acceptance (OBSERVABILITY.md "Tracing") ----------------
    from distributed_mnist_bnns_tpu.obs.trace import unresolved_parents

    spans = [e for e in events if e["kind"] == "span"]
    if not spans:
        failures.append("tracing was enabled but no span events landed")
    roots = {
        (s.get("attrs") or {}).get("id"): s
        for s in spans if s.get("span_kind") == "request"
    }
    kinds_by_root = {}
    for s in spans:
        key = (s.get("trace"), s.get("parent"))
        for rid, r in roots.items():
            if key == (r.get("trace"), r.get("span")):
                kinds_by_root.setdefault(rid, set()).add(s.get("span_kind"))
    for e in evicts:
        if e["status"] != "ok":
            continue
        rid = e["id"]
        if rid not in roots:
            failures.append(
                f"completed stream {rid} has no root span — every "
                "request must leave a closed span tree"
            )
            continue
        have = kinds_by_root.get(rid, set())
        if not {"queue", "prefill", "decode"} <= have:
            failures.append(
                f"stream {rid}'s span tree is missing phases: have "
                f"{sorted(have)}, want queue+prefill+decode"
            )
    if not any(s.get("span_kind") == "decode_iter" for s in spans):
        failures.append(
            "no decode-iteration spans — the scheduler's per-iteration "
            "lane must be trace-visible"
        )
    iter_ids = {
        (s.get("trace"), s.get("span"))
        for s in spans if s.get("span_kind") == "decode_iter"
    }
    for kind in ("draft", "verify"):
        if not any(
            s.get("span_kind") == kind
            and (s.get("trace"), s.get("parent")) in iter_ids
            for s in spans
        ):
            failures.append(
                f"no lm.{kind} span parented under lm.decode_iter — "
                "the speculative round's phases must be trace-visible"
            )
    if not any(s.get("span_kind") == "stall" for s in spans):
        failures.append(
            "chaos stalls fired but no stall span landed — fault->"
            "latency causality must be trace-visible"
        )
    broken = unresolved_parents(spans)
    if broken:
        failures.append(
            f"{len(broken)} span(s) reference a parent missing from "
            "the log — span trees must close"
        )
    export_path = os.path.join(work, "chrome_trace.json")
    cli = subprocess.run(
        [sys.executable, "-m", "distributed_mnist_bnns_tpu.cli",
         "trace", tel_dir, "--export", export_path],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if cli.returncode != 0:
        failures.append(f"cli trace failed: {cli.stderr[-300:]}")
    else:
        try:
            with open(export_path) as f:
                chrome = json.load(f)
            assert chrome["traceEvents"], "empty traceEvents"
            for ev in chrome["traceEvents"]:
                assert ev["ph"] in ("X", "M"), ev
                assert {"name", "pid", "tid"} <= set(ev), ev
        except (OSError, ValueError, KeyError, AssertionError) as e:
            failures.append(f"Chrome-trace export invalid: {e!r}")

    # -- Pallas kernel-path acceptance (chaos-free, deterministic) ------
    kernel_summary = kernel_path_phase(artifact, work, failures)

    summary = {
        "kernel_path": kernel_summary,
        "streams": {
            tid: {"code": r["code"], "n_tokens": len(r["tokens"]),
                  "status": (r["done"] or {}).get("status"),
                  "span_s": round(r["span_s"], 3)}
            for tid, r in sorted(results.items())
        },
        "queued_deadline_probe": code_504,
        "events": {k: sum(1 for e in events if e["kind"] == k)
                   for k in EXPECTED_KINDS},
        "spans": len(spans),
        "recompiles_post_warmup": health.get("recompiles_post_warmup"),
        "prefix_hits": len(prefix_hits_ev),
        "spec_acceptance_rate": health.get("spec_acceptance_rate"),
        "drain": drains[-1] if drains else None,
        "ok": not failures,
    }
    print(json.dumps(summary, indent=2, default=str))
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not args.keep and args.dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
