"""Pipeline bubble + activation-memory study (VERDICT r4 item 8).

Two measurements over the op-level GPipe schedule
(parallel/pipeline.make_pipeline_fn), runnable without TPU hardware:

1. **Bubble fraction.** On the virtual-device CPU mesh every device's
   tick executes serially on one core, so step wall-clock should track
   the schedule's total cell count S * (M + S - 1). Sweeping M at fixed
   per-microbatch work and linearly fitting t = overhead + cell_cost *
   cells validates the tick count empirically (R^2 ~ 1); given that
   schedule, the per-chip idle fraction on real parallel devices is the
   analytic (S - 1) / (M + S - 1) reported per row.

2. **1F1B-class memory.** XLA's compiled memory analysis
   (``.compile().memory_analysis().temp_size_in_bytes``) for the grad
   step with and without ``stage_remat``: checkpointing each stage
   bounds the backward tape to the stage *inputs* (O(M x microbatch))
   instead of every stage-internal intermediate — the in-flight-memory
   property tick-interleaved 1F1B buys, recovered under XLA's static
   schedule without a manual vjp scheduler.

Writes JSON to stdout; paste the table into PERF.md §pipeline.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
from distributed_mnist_bnns_tpu.utils.platform import (  # noqa: E402
    enable_persistent_compilation_cache,
)

enable_persistent_compilation_cache()

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from distributed_mnist_bnns_tpu.parallel import (  # noqa: E402
    make_pipeline_fn,
    pipeline_bubble_fraction,
)

MB_ROWS = 32         # per-microbatch rows (fixed work per cell)
WIDTH = 256
INNER = 1024


def _stage_fn(p, x):
    h = jnp.tanh(x @ p["w1"])
    return x + jnp.tanh(h @ p["w2"])


def _time(fn, *args, reps=5, inner=3):
    fn(*args)  # compile + settle
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            r = fn(*args)
        jax.block_until_ready(r)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def _devices(n_stages: int):
    devices = jax.devices()[:n_stages]
    assert len(devices) == n_stages, (
        f"need {n_stages} devices, have {len(devices)} — is XLA_FLAGS "
        "already set without --xla_force_host_platform_device_count?"
    )
    return devices


def bubble_sweep(n_stages: int):
    devices = _devices(n_stages)
    mesh = Mesh(np.array(devices), axis_names=("pipe",))
    key = jax.random.PRNGKey(0)
    params = {
        "w1": jax.random.normal(key, (n_stages, WIDTH, INNER)) * 0.05,
        "w2": jax.random.normal(key, (n_stages, INNER, WIDTH)) * 0.05,
    }
    rows = []
    ms = [n_stages, 2 * n_stages, 4 * n_stages, 8 * n_stages, 16 * n_stages]
    for m in ms:
        pipe = make_pipeline_fn(mesh, _stage_fn, n_micro=m)
        x = jax.random.normal(key, (m * MB_ROWS, WIDTH))

        def step(p, x, pipe=pipe):
            return pipe(p, x)

        t = _time(step, params, x)
        cells_total = n_stages * (m + n_stages - 1)
        cells_useful = n_stages * m
        rows.append({
            "n_micro": m,
            "step_s": round(t, 5),
            "s_per_useful_cell": t / cells_useful,
            "cells_total": cells_total,
            "analytic_bubble": round(
                pipeline_bubble_fraction(n_stages, m), 4
            ),
        })
    # The schedule claim is t = overhead + cell_cost * S * (M + S - 1):
    # fit it linearly over the sweep and report the fit quality — an R^2
    # near 1 validates the tick count empirically. The bubble fraction
    # then follows from the fitted cell cost (overhead excluded).
    xs = np.array([r["cells_total"] for r in rows], float)
    ys = np.array([r["step_s"] for r in rows], float)
    cell_cost, overhead = np.polyfit(xs, ys, 1)
    pred = overhead + cell_cost * xs
    ss_res = float(((ys - pred) ** 2).sum())
    ss_tot = float(((ys - ys.mean()) ** 2).sum())
    for r in rows:
        del r["s_per_useful_cell"]
    return {
        "rows": rows,
        "fit": {
            "cell_cost_us": round(cell_cost * 1e6, 2),
            "overhead_us": round(overhead * 1e6, 2),
            "r2": round(1.0 - ss_res / ss_tot, 4),
        },
    }


def memory_study(n_stages: int):
    devices = _devices(n_stages)
    mesh = Mesh(np.array(devices), axis_names=("pipe",))
    key = jax.random.PRNGKey(0)
    params = {
        "w1": jax.random.normal(key, (n_stages, WIDTH, INNER)) * 0.05,
        "w2": jax.random.normal(key, (n_stages, INNER, WIDTH)) * 0.05,
    }
    out = []
    for m in (n_stages, 4 * n_stages, 16 * n_stages):
        x = jax.random.normal(key, (m * MB_ROWS, WIDTH))
        row = {"n_micro": m}
        for name, remat in (("plain", False), ("stage_remat", True)):
            pipe = make_pipeline_fn(
                mesh, _stage_fn, n_micro=m, stage_remat=remat
            )

            def loss(p, x=x, pipe=pipe):
                return jnp.sum(pipe(p, x) ** 2)

            g = jax.jit(jax.grad(loss))
            ma = g.lower(params).compile().memory_analysis()
            row[f"temp_mb_{name}"] = (
                None if ma is None
                else round(ma.temp_size_in_bytes / 2**20, 2)
            )
        if row["temp_mb_plain"] and row["temp_mb_stage_remat"]:
            row["ratio"] = round(
                row["temp_mb_stage_remat"] / row["temp_mb_plain"], 3
            )
        out.append(row)
    return out


def main():
    result = {"per_microbatch_rows": MB_ROWS, "width": WIDTH,
              "stage_inner": INNER}
    for s in (2, 4):
        result[f"bubble_pp{s}"] = bubble_sweep(s)
        result[f"memory_pp{s}"] = memory_study(s)
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()
