#!/usr/bin/env python
"""Event-schema doc-drift check: obs/events.py's EVENT_KINDS registry
must mirror OBSERVABILITY.md's event table row for row.

Both sides are parsed without importing the package (AST literal on the
Python side, the markdown table on the doc side), so the check runs in
any environment — it is a step of the CI lint job, and
tests/test_analysis.py runs it in-process as a tier-1 test. Exit 0 when
the sets match, 1 with a both-directions diff otherwise.

The registry itself is enforced at emit() call sites by the linter's
JG017 (unknown kind) and JG018 (envelope collision) — see ANALYSIS.md
"SPMD pack & event-schema contracts".
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EVENTS_PY = os.path.join(
    REPO, "distributed_mnist_bnns_tpu", "obs", "events.py"
)
OBS_MD = os.path.join(REPO, "OBSERVABILITY.md")

# A table row whose first cell is a single backticked kind name.
_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")


def registry_kinds(path: str = EVENTS_PY) -> Set[str]:
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if any(
            isinstance(t, ast.Name) and t.id == "EVENT_KINDS"
            for t in targets
        ):
            return set(ast.literal_eval(node.value))
    raise SystemExit(f"no EVENT_KINDS literal found in {path}")


def documented_kinds(path: str = OBS_MD) -> Set[str]:
    """Rows of the event table specifically — the table whose header's
    first column is `kind` (OBSERVABILITY.md also carries a metrics
    table, which is out of contract)."""
    kinds = set()
    in_event_table = False
    with open(path, encoding="utf-8") as f:
        for line in f:
            stripped = line.strip()
            if re.match(r"^\|\s*kind\s*\|", stripped):
                in_event_table = True
                continue
            if not in_event_table:
                continue
            if not stripped.startswith("|"):
                in_event_table = False
                continue
            m = _ROW_RE.match(stripped)
            if m:
                kinds.add(m.group(1))
    return kinds


def diff() -> Tuple[Set[str], Set[str]]:
    """(registered but undocumented, documented but unregistered)."""
    reg = registry_kinds()
    doc = documented_kinds()
    return reg - doc, doc - reg


def main() -> int:
    undocumented, unregistered = diff()
    if not undocumented and not unregistered:
        n = len(registry_kinds())
        print(f"event docs in sync: {n} kinds")
        return 0
    if undocumented:
        print(
            "kinds in obs/events.py EVENT_KINDS with no OBSERVABILITY.md "
            f"event-table row: {sorted(undocumented)}",
            file=sys.stderr,
        )
    if unregistered:
        print(
            "OBSERVABILITY.md event-table rows with no EVENT_KINDS "
            f"entry: {sorted(unregistered)}",
            file=sys.stderr,
        )
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
