"""Round-5 endpoint window catcher: wait for the remote-TPU tunnel to
answer, then run the round-5 hardware agenda (scripts/window_agenda.py)
— tests_tpu certification, bench + serving numbers, stretch/int8/MFU
benches, accuracy runs — resuming partial progress across windows via
scripts/window_r05_status.json.

Probing reuses bench._device_responsive with JAX_PLATFORMS pinned to the
remote-TPU platform so a CPU fallback can never read as a live window.

Run detached: ``nohup python scripts/run_on_window_r5.py >/dev/null 2>&1 &``
Progress/log: scripts/window_run.log
"""

from __future__ import annotations

import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

import bench  # noqa: E402
from window_agenda import log, run_agenda  # noqa: E402


def main() -> None:
    os.environ["JAX_PLATFORMS"] = os.environ.get(
        "WINDOW_CATCHER_PLATFORM", "axon"
    )
    log("round-5 window catcher started")
    deadline = time.time() + float(
        os.environ.get("WINDOW_CATCHER_BUDGET_S", 11 * 3600)
    )
    while time.time() < deadline:
        if bench._device_responsive(70.0):
            log("window open: running round-5 agenda")
            if run_agenda():
                log("full agenda complete; exiting")
                return
        # Window #1 (2026-08-01) lasted ~12 min; a 480 s probe gap can
        # eat most of such a window, and a dead-endpoint probe already
        # burns its 70 s timeout, so the idle duty cycle stays low.
        time.sleep(150)
    log("budget exhausted; agenda incomplete (see window_r05_status.json)")


if __name__ == "__main__":
    main()
