"""Elastic-membership smoke (CI): a real ``cli train --elastic`` run
must survive a scripted worker loss WITHOUT a full-job restart.

Spawns the actual CLI as a subprocess on the simulated 8-device CPU
mesh with the 1-bit sign_ef gradient exchange and a scripted membership
sequence — ``worker_lost@step=6,world=4`` (mesh shrinks 8→4, state
re-placed from the newest digest-verified checkpoint generation) then
``worker_restore@step=12`` (regrow to 8) — and asserts from the exit
code, results CSV and obs event log that:

  * the process finished exit 0 (one invocation, no exit-75 relaunch);
  * it LEARNED (final test accuracy beats the bar — a remesh that
    silently scrambled the re-placed EF/moment rows would still exit 0);
  * exactly ONE shrink and ONE regrow ``remesh`` event, world 8→4→8;
  * both post-remesh ``resume`` events restored a digest-verified
    generation and re-placed state (``remeshed`` flag);
  * ZERO ``restart`` events — membership churn is routine, not failure
    (RESILIENCE.md "Elastic membership").

Usage: python scripts/elastic_smoke.py [--dir DIR] [--keep]
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHAOS_SPEC = "worker_lost@step=6,world=4;worker_restore@step=12"
MIN_ACC = 50.0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dir", default=None,
                        help="work dir (default: a fresh temp dir)")
    parser.add_argument("--keep", action="store_true",
                        help="keep the work dir for inspection")
    args = parser.parse_args(argv)

    work = args.dir or tempfile.mkdtemp(prefix="elastic_smoke_")
    ckpt_dir = os.path.join(work, "ckpts")
    tel_dir = os.path.join(work, "telemetry")
    results = os.path.join(work, "results.csv")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    cmd = [
        sys.executable, "-m", "distributed_mnist_bnns_tpu.cli", "train",
        "--model", "bnn-mlp-small", "--epochs", "2", "--batch-size", "64",
        "--dp", "auto", "--grad-compress", "sign_ef", "--elastic",
        "--synthetic-sizes", "1024", "128", "--seed", "0",
        "--chaos", CHAOS_SPEC,
        "--checkpoint-dir", ckpt_dir, "--telemetry-dir", tel_dir,
        "--results", results,
        "--log-file", os.path.join(work, "train.log"),
    ]
    print("elastic_smoke: running", " ".join(cmd), file=sys.stderr,
          flush=True)
    proc = subprocess.run(cmd, env=env, cwd=REPO)

    failures = []
    if proc.returncode != 0:
        failures.append(
            f"cli train --elastic exited {proc.returncode} (want 0: one "
            "invocation, no relaunch)"
        )

    acc = None
    try:
        with open(results) as f:
            rows = list(csv.DictReader(f))
        acc = float(rows[-1]["test_acc"])
        if acc <= MIN_ACC:
            failures.append(
                f"run did not learn across the remeshes: test_acc={acc} "
                f"(want > {MIN_ACC})"
            )
    except (OSError, IndexError, KeyError, ValueError) as e:
        failures.append(f"could not read final accuracy from {results}: {e}")

    events = []
    events_path = os.path.join(tel_dir, "events.jsonl")
    try:
        with open(events_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    except OSError as e:
        failures.append(f"no event log at {events_path}: {e}")

    kinds = [e["kind"] for e in events]
    remesh = [e for e in events if e["kind"] == "remesh"]
    transitions = [
        (e["direction"], e["world_from"], e["world_to"]) for e in remesh
    ]
    if transitions != [("shrink", 8, 4), ("grow", 4, 8)]:
        failures.append(
            "want exactly one 8->4 shrink then one 4->8 regrow, got "
            f"{transitions}"
        )
    member = [e["event"] for e in events
              if e["kind"] == "membership_change"]
    if member != ["lost", "restored"]:
        failures.append(f"membership_change sequence off: {member}")
    restarts = kinds.count("restart")
    if restarts:
        failures.append(
            f"{restarts} restart event(s) — the elastic loop must "
            "remesh, never full-job-restart, on membership churn"
        )
    resumes = [e for e in events if e["kind"] == "resume"]
    if [bool(e.get("remeshed")) for e in resumes] != [True, True]:
        failures.append(
            "want two remeshed resumes (one per remesh), got "
            f"{[(e.get('remeshed'), e.get('world_size')) for e in resumes]}"
        )
    if not all(e.get("digest_verified") for e in resumes):
        failures.append(
            "a resume restored an unverified generation: "
            f"{[e.get('digest_verified') for e in resumes]}"
        )

    summary = {
        "exit_code": proc.returncode,
        "test_acc": acc,
        "remesh": transitions,
        "events": {k: kinds.count(k) for k in (
            "membership_change", "remesh", "resume", "restart",
            "fault_injected",
        )},
        "ok": not failures,
    }
    print(json.dumps(summary, indent=2))
    for f_ in failures:
        print(f"FAIL: {f_}", file=sys.stderr)
    if not args.keep and args.dir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
