"""Transformer binarization-gap study (VERDICT r4 item 5).

Round 4 published bnn-vit-tiny at 46.3% tuned with no fp32 denominator.
This runs the twin pair (bnn-vit-tiny vs fp32-vit-tiny — identical
topology, binarization removed) multi-seed on the real t10k split via
examples/accuracy_report, then the byte-LM twin pair on the external
licenses corpus (scripts/lm_corpus_eval --fp32-twin) at the full 256-dim
configuration.

Writes RESULTS_VIT.md + prints the lm_corpus_eval JSON line. Sized for a
live TPU window; the ViT half is CPU-feasible (~15 min), the 256-dim LM
half is slow off-chip (use --lm-steps 0 to skip it).
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Honor JAX_PLATFORMS over the image's sitecustomize (remote-TPU
# plugin); raises if a backend already initialized on the wrong platform.
from distributed_mnist_bnns_tpu.utils.platform import (
    enable_persistent_compilation_cache,
    pin_platform_from_env,
)

pin_platform_from_env()
# Persist compiled executables across processes/windows (shared
# repo-root cache; a cold remote compile can eat a short TPU window).
enable_persistent_compilation_cache()

from distributed_mnist_bnns_tpu.examples.accuracy_report import run  # noqa: E402


def main() -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--seeds", type=int, nargs="+", default=[42, 43, 44])
    p.add_argument("--out", default="RESULTS_VIT.md")
    p.add_argument("--lm-steps", type=int, default=4000,
                   help="0 skips the LM corpus half")
    args = p.parse_args()
    run(
        ["bnn-vit-tiny", "fp32-vit-tiny"],
        epochs=args.epochs, batch_size=64, lr=0.003,
        seeds=args.seeds, out_path=args.out, scan_steps=4,
        cache_path=args.out + ".cache.json",
    )
    if args.lm_steps > 0:
        subprocess.run(
            [sys.executable, "scripts/lm_corpus_eval.py",
             "--embed-dim", "256", "--depth", "4", "--seq-len", "256",
             "--steps", str(args.lm_steps), "--fp32-twin", "--partial"],
            cwd=REPO, check=True,
        )


if __name__ == "__main__":
    main()
