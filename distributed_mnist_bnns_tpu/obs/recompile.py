"""Recompilation tracking — make silent retrace storms a visible counter.

A jitted function recompiles whenever it sees a new input
shape/dtype/static-arg combination; on a remote-compile backend one
silent retrace can cost minutes. JAX announces every backend compile
through ``jax.monitoring`` (the ``/jax/core/compile/backend_compile_duration``
duration event, fired exactly once per XLA compilation — i.e. per jit
cache miss); the tracker registers a listener and counts them into the
metrics registry, so the step-level telemetry (and the ``telemetry``
CLI) can report "this run compiled N programs, M of them after warmup".

Fallback: when ``jax.monitoring`` is unavailable (stubbed jax, very old
versions), ``observe_step`` applies a dispatch-time-spike heuristic — a
step that takes > ``spike_factor`` x the running median is counted as a
suspected recompile. The heuristic is only consulted when the listener
could not be installed, so real counts are never mixed with guesses.
"""

from __future__ import annotations

import collections
import statistics
import threading
from typing import Optional

from .registry import MetricsRegistry, default_registry

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

COMPILES_TOTAL = "jax_backend_compiles_total"
COMPILE_SECONDS = "jax_backend_compile_seconds"


class RecompileTracker:
    """Counts backend compiles (see module docstring). One instance per
    process is enough — use ``get_tracker()``."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        spike_factor: float = 20.0,
        window: int = 64,
    ):
        self.registry = registry or default_registry()
        self._lock = threading.Lock()
        self._count = 0
        self._compile_seconds = 0.0
        self._installed = False
        self.listener_available = False
        self.spike_factor = spike_factor
        self._recent = collections.deque(maxlen=window)
        self._counter = self.registry.counter(
            COMPILES_TOTAL,
            "XLA backend compilations observed (jit cache misses; "
            "suspected-from-latency-spike when kind=suspected)",
        )
        self._seconds = self.registry.counter(
            COMPILE_SECONDS, "cumulative XLA backend compile time",
        )

    # -- jax.monitoring listener (primary) ----------------------------------

    def _on_duration(self, event: str, duration: float, **kwargs) -> None:
        if event != BACKEND_COMPILE_EVENT:
            return
        with self._lock:
            self._count += 1
            self._compile_seconds += float(duration)
        self._counter.inc(kind="measured")
        self._seconds.inc(float(duration))

    def install(self) -> "RecompileTracker":
        """Register the monitoring listener (idempotent). jax.monitoring
        offers no per-listener unregister on all supported versions, so
        installation is once-per-process by design."""
        if self._installed:
            return self
        self._installed = True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(
                self._on_duration
            )
            self.listener_available = True
        except (ImportError, AttributeError):
            self.listener_available = False
        return self

    # -- dispatch-time-spike fallback ---------------------------------------

    def observe_step(self, step_seconds: float) -> bool:
        """Feed a measured step time. Only when the monitoring listener
        is NOT available, a spike above ``spike_factor`` x the running
        median counts as a suspected recompile. Returns True when a
        suspected recompile was recorded."""
        if self.listener_available:
            return False
        with self._lock:
            suspected = (
                len(self._recent) >= 8
                and step_seconds
                > self.spike_factor * statistics.median(self._recent)
            )
            self._recent.append(step_seconds)
            if suspected:
                self._count += 1
        if suspected:
            self._counter.inc(kind="suspected")
        return suspected

    # -- reads --------------------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def compile_seconds(self) -> float:
        with self._lock:
            return self._compile_seconds

    def mark(self) -> int:
        """Snapshot the current count; subtract from a later ``count``
        to attribute compiles to a region (warmup vs steady-state)."""
        return self.count


_tracker: Optional[RecompileTracker] = None
_tracker_lock = threading.Lock()


def get_tracker() -> RecompileTracker:
    """Process-wide tracker, installed on first use."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = RecompileTracker().install()
        return _tracker
