"""Analytic FLOPs accounting and MFU — the single source for chip peaks
and model-FLOPs estimates (bench.py delegates here, the trainer's
step-level telemetry records from here; previously this logic lived only
inside bench.py).

Convention: training FLOPs per step = 3 x forward-GEMM FLOPs
(fwd = 2*MACs; backward costs ~2x fwd for the dL/dW and dL/dx GEMMs per
layer) — the standard MFU numerator, which deliberately excludes
optimizer/elementwise noise. XLA's cost_analysis is NOT this number's
source: it is unavailable through remote-compile tunnel backends and
counts the noise the convention excludes. It is banked separately by
the per-program cost ledger (obs/costs, OBSERVABILITY.md "Device
profiling"), and the two agreeing within a small factor is a tested
reconciliation invariant.
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

log = logging.getLogger(__name__)

# Per-chip bf16 peak (dense MXU FLOPs/s) by device_kind substring, most
# specific first. Sources: public TPU spec sheets (v5e 197 TF, v5p 459 TF,
# v4 275 TF, v6e 918 TF, v3 123 TF, v2 45 TF bf16 per chip).
PEAKS_BF16: Tuple[Tuple[str, float], ...] = (
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),
    ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# int8 MXU peak relative to bf16: 2x on v5e/v5p/v6 (the generations with
# a doubled int8 pipeline), 1x on v4 and earlier.
INT8_MULT: Tuple[Tuple[str, float], ...] = (
    ("v5", 2.0), ("v6", 2.0), ("trillium", 2.0),
    ("v4", 1.0), ("v3", 1.0), ("v2", 1.0),
)

# Nominal dense peak for hosts with no spec-sheet entry (CPU smoke runs,
# unknown accelerators): ~100 GFLOP/s, a round order-of-magnitude for a
# few vectorized cores. MFU against it is a *relative* utilization signal
# only — telemetry marks it peak_precision="nominal" so a reader never
# mistakes a CPU number for a TPU one.
NOMINAL_HOST_PEAK = 1e11


def _device_kind(device: Any) -> str:
    return (getattr(device, "device_kind", "") or str(device)).lower()


def chip_peak_bf16(device: Any) -> Optional[float]:
    kind = _device_kind(device)
    for sub, peak in PEAKS_BF16:
        if sub in kind:
            return peak
    return None


def chip_peak(device: Any, backend: str = "bf16") -> Tuple[Optional[float], str]:
    """Precision-matched MXU peak for MFU accounting: the int8 pipeline's
    peak for the int8 backend, the dense bf16 peak for everything else
    (the xnor/pallas_xnor backends run on the VPU but are still scored
    against the bf16 MXU peak — that IS the machine's dense capability
    the kernel is competing with). Returns (peak or None, precision)."""
    peak = chip_peak_bf16(device)
    if peak is None:
        return None, "unknown"
    if backend == "int8":
        kind = _device_kind(device)
        mult = next((m for sub, m in INT8_MULT if sub in kind), 1.0)
        return peak * mult, "int8"
    return peak, "bf16"


def device_peak_flops(
    device: Any, backend: str = "bf16",
) -> Tuple[float, str]:
    """``chip_peak`` with a nominal-host fallback so step telemetry can
    always report an MFU estimate (marked "nominal" off the spec table —
    see NOMINAL_HOST_PEAK)."""
    peak, precision = chip_peak(device, backend)
    if peak is None:
        return NOMINAL_HOST_PEAK, "nominal"
    return peak, precision


def dense_macs_per_example(params: Any) -> int:
    """Analytic per-example MAC count of every Dense kernel in the model
    (rank-2 (in, out) kernels contribute in*out MACs per example). Exact
    for the MLP/QNN families where all FLOPs are in Dense layers."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(params):
        if getattr(leaf, "ndim", 0) == 2:
            total += int(leaf.shape[0]) * int(leaf.shape[1])
    return total


def jaxpr_macs_per_example(apply_fn, variables: Any, input_shape) -> int:
    """Analytic conv+dense MAC count of one forward pass, by walking the
    shaped jaxpr for conv_general_dilated / dot_general primitives — the
    conv-family counterpart of ``dense_macs_per_example`` (convs put most
    FLOPs outside rank-2 kernels, so the dense count undercounts)."""
    import jax
    import jax.numpy as jnp

    macs = [0]

    def fwd(v, x):
        return apply_fn(v, x, train=False)

    jaxpr = jax.make_jaxpr(fwd)(
        variables, jnp.zeros((1, *input_shape), jnp.float32)
    )

    def count(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "conv_general_dilated":
                out = eqn.outvars[0].aval.shape      # (N, H, W, O)
                rhs = eqn.invars[1].aval.shape       # (Kh, Kw, I, O)
                macs[0] += (
                    out[1] * out[2] * out[3]
                    * rhs[0] * rhs[1] * rhs[2]
                )
            elif eqn.primitive.name == "dot_general":
                shapes = [v.aval.shape for v in eqn.invars]
                if len(shapes) == 2 and len(shapes[1]) == 2:
                    m = 1
                    for d in eqn.outvars[0].aval.shape[:-1]:
                        m *= d
                    macs[0] += m * shapes[1][0] * shapes[1][1]
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    count(sub.jaxpr)
                elif isinstance(sub, (list, tuple)):
                    for s in sub:
                        if hasattr(s, "jaxpr"):
                            count(s.jaxpr)

    count(jaxpr.jaxpr)
    return macs[0]


def train_step_flops(
    model_name: str,
    params: Any,
    batch_size: int,
    *,
    apply_fn=None,
    variables: Any = None,
    input_shape=None,
) -> Tuple[Optional[float], str]:
    """FLOPs of one optimizer step over ``batch_size`` examples, with the
    estimation method used: "analytic_3x_dense_gemms" for the MLP/QNN
    families (all FLOPs in rank-2 kernels), else
    "analytic_3x_conv_and_dense_from_jaxpr" when the forward can be
    traced, else (None, "unavailable")."""
    if "mlp" in model_name or "qnn" in model_name:
        macs = dense_macs_per_example(params)
        if macs > 0:
            return 3.0 * 2.0 * macs * batch_size, "analytic_3x_dense_gemms"
    if apply_fn is not None and variables is not None and input_shape:
        try:
            macs = jaxpr_macs_per_example(apply_fn, variables, input_shape)
            if macs > 0:
                return (
                    3.0 * 2.0 * macs * batch_size,
                    "analytic_3x_conv_and_dense_from_jaxpr",
                )
        except Exception as e:
            # A model whose forward cannot be abstractly traced (custom
            # calls, data-dependent shapes) simply gets no MFU figure.
            log.debug("jaxpr MAC walk failed (%s); flops unavailable", e)
    return None, "unavailable"


def mfu(
    step_flops: Optional[float],
    step_time_s: Optional[float],
    peak: Optional[float],
    n_devices: int = 1,
) -> Optional[float]:
    """Model FLOPs Utilization: achieved model FLOPs/s over the peak of
    the ``n_devices`` chips the step ran on (BASELINE.md names
    images/sec/chip and MFU-style utilization as the headline metrics)."""
    if not step_flops or not step_time_s or not peak or step_time_s <= 0:
        return None
    return round(step_flops / step_time_s / (peak * max(n_devices, 1)), 6)


def device_memory_stats(
    *, live_fallback: bool = False,
) -> Optional[dict]:
    """Per-device HBM usage via ``device.memory_stats()`` where the
    backend exposes it (TPU/GPU runtimes do, CPU returns None). Returns
    {device_index: {bytes_in_use, peak_bytes_in_use, bytes_limit}} for
    local devices, or None when unavailable.

    ``live_fallback=True`` adds the live-buffer-walk fallback: when no
    device reports allocator stats (CPU), every ``jax.live_arrays()``
    buffer's nbytes is attributed to the devices its sharding spans, so
    the HBM census (/healthz ``device_memory``, OBSERVABILITY.md
    "Device profiling") still returns a number — marked
    ``source="live_arrays"``, and an *approximation*: it sees arrays
    the Python side keeps alive, not allocator internals. The walk is
    O(live arrays); reserve it for poll-rate paths (healthz), never the
    dispatch hot loop."""
    try:
        import jax

        out = {}
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            out[str(d.id)] = {
                k: int(v) for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                         "largest_alloc_size")
            }
        if out or not live_fallback:
            return out or None
        walked: dict = {}
        for arr in jax.live_arrays():
            try:
                devs = list(arr.devices())
                nbytes = int(arr.nbytes)
            except (AttributeError, RuntimeError, TypeError, ValueError):
                continue  # a deleted/exotic buffer: skip, don't poison
            if not devs:
                continue
            share = nbytes // len(devs)
            for d in devs:
                row = walked.setdefault(
                    str(d.id),
                    {"bytes_in_use": 0, "live_buffers": 0,
                     "source": "live_arrays"},
                )
                row["bytes_in_use"] += share
                row["live_buffers"] += 1
        return walked or None
    except (ImportError, RuntimeError, TypeError, ValueError):
        return None
