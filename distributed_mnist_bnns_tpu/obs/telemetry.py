"""Telemetry facade — one object bundling the metrics registry, the
JSONL event sink, the recompile tracker and the multi-host heartbeat,
with the derived step metrics (examples/sec, latency percentiles, MFU)
computed in one place.

The Trainer, the infer paths and bench.py all talk to this class; the
legacy consumers (AverageMeter wall-time logging, ResultsLog CSV rows)
keep their outputs unchanged and simply read alongside.

Disabled mode: ``Telemetry()`` with no run directory still maintains the
in-process metrics registry (cheap) but emits no files — call sites need
no ``if telemetry:`` guards.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from .events import EventLog
from .flops import device_memory_stats, device_peak_flops, mfu
from .heartbeat import Heartbeat
from .recompile import RecompileTracker, get_tracker
from .registry import MetricsRegistry
from .trace import Tracer

EVENTS_FILE = "events.jsonl"
ENV_TRACE = "JG_TRACE"
ENV_EVENTS_MAX_BYTES = "JG_EVENTS_MAX_BYTES"
EVENTS_ROTATED_TOTAL = "events_rotated_total"

STEP_SECONDS = "train_step_seconds"
EXAMPLES_TOTAL = "train_examples_total"
STEPS_TOTAL = "train_steps_total"


class Telemetry:
    """Per-run telemetry. ``run_dir=None`` disables all file outputs.

    The recompile tracker is a process-wide singleton by default
    (compiles are a process property, not a run property). The registry
    holding the run's OWN instruments (step histogram, step/example
    counters) is per-instance by default — a second Trainer in the same
    process must not report the first run's steps in its epoch events;
    the process-wide ``default_registry()`` keeps serving the layers
    whose metrics genuinely span runs (placement timing, decode
    counters, compiles)."""

    def __init__(
        self,
        run_dir: Optional[str] = None,
        *,
        registry: Optional[MetricsRegistry] = None,
        tracker: Optional[RecompileTracker] = None,
        heartbeat_interval_s: float = 30.0,
        heartbeat: bool = True,
        trace: Optional[bool] = None,
        events_max_bytes: Optional[int] = None,
    ):
        self.run_dir = run_dir
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.tracker = tracker if tracker is not None else get_tracker()
        self.events: Optional[EventLog] = None
        self.heartbeat: Optional[Heartbeat] = None
        self._t0 = time.time()
        self._last_step_payload: Dict[str, Any] = {}
        self.step_hist = self.registry.histogram(
            STEP_SECONDS, "per-optimizer-step wall latency"
        )
        self.examples = self.registry.counter(
            EXAMPLES_TOTAL, "training examples processed"
        )
        self.steps = self.registry.counter(
            STEPS_TOTAL, "optimizer steps run"
        )
        if run_dir is not None:
            os.makedirs(run_dir, exist_ok=True)
            # Size-bound the log for long-lived servers (events.py
            # "Rotation"): explicit ``events_max_bytes`` wins, else the
            # JG_EVENTS_MAX_BYTES env var, else unbounded (training
            # runs are epoch-bounded). Rotations are visible as the
            # events_rotated_total counter.
            if events_max_bytes is None:
                env_cap = os.environ.get(ENV_EVENTS_MAX_BYTES, "")
                events_max_bytes = int(env_cap) if env_cap.isdigit() \
                    else None
            self.events = EventLog(
                os.path.join(run_dir, EVENTS_FILE),
                max_bytes=events_max_bytes,
            )
            rotated_ctr = self.registry.counter(
                EVENTS_ROTATED_TOTAL,
                "event-log segment rotations (size-bounded servers)",
            )
            self.events.on_rotate = rotated_ctr.inc
            if heartbeat:
                self.heartbeat = Heartbeat(
                    run_dir,
                    interval_s=heartbeat_interval_s,
                    payload_fn=lambda: dict(self._last_step_payload),
                ).start()
        # Tracing (obs/trace, OBSERVABILITY.md "Tracing"): explicit
        # ``trace=`` wins; None defers to the JG_TRACE env var (how CI
        # arms tracing without touching call sites). A run without an
        # event sink has nowhere durable to put spans, so the tracer
        # stays disabled — near-zero cost at every instrumented site.
        if trace is None:
            trace = os.environ.get(ENV_TRACE, "") not in ("", "0")
        self.tracer = Tracer(
            sink=self.events,
            enabled=bool(trace) and self.events is not None,
            registry=self.registry,
        )

    @property
    def enabled(self) -> bool:
        return self.run_dir is not None

    # -- lifecycle events ---------------------------------------------------

    def manifest(
        self, config: Optional[Dict[str, Any]] = None, mesh: Any = None,
        **extra: Any,
    ) -> None:
        if self.events is not None:
            self.events.manifest(config=config, mesh=mesh, **extra)

    def emit(self, kind: str, **fields: Any) -> None:
        if self.events is not None:
            self.events.emit(kind, **fields)

    def error(self, exc: BaseException, **fields: Any) -> None:
        self.registry.counter(
            "run_errors_total", "exceptions recorded by telemetry"
        ).inc(kind=type(exc).__name__)
        if self.events is not None:
            self.events.error(exc, **fields)

    def close(self, **final_fields: Any) -> None:
        if self.heartbeat is not None:
            self.heartbeat.stop()
            self.heartbeat = None
        # Staged spans land before the log seals (and before the final
        # metrics snapshot, which includes the trace drop counter).
        self.tracer.flush()
        if self.events is not None:
            # Cost-ledger final rows (obs/costs; armed runs only): the
            # ledger is process-wide — its dispatch times may live in
            # the process registry, not this run's — so the closing
            # snapshot re-emits each program's row WITH dispatches/
            # mean/measured-MFU, making the `cli telemetry` programs
            # section complete from the events dir alone.
            from .costs import get_ledger

            ledger = get_ledger()
            if ledger.enabled:
                for row in ledger.snapshot().values():
                    self.events.emit("program_cost", final=True, **row)
            # Final registry snapshot as ONE event: counters the run
            # accumulated (comm_bytes_total phases, shed/fault counts,
            # …) become post-mortem-readable from the event log alone,
            # without a live /metrics endpoint to scrape.
            self.events.emit("metrics", registry=self.registry.snapshot())
            self.events.emit(
                "run_end",
                wall_seconds=round(time.time() - self._t0, 3),
                recompiles_total=self.tracker.count,
                compile_seconds=round(self.tracker.compile_seconds, 3),
                **final_fields,
            )
            self.events.close()
            self.events = None

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.error(exc)
        self.close()

    # -- step-level derived metrics -----------------------------------------

    def record_step(
        self,
        latency_s: float,
        *,
        batch_size: int,
        n_steps: int = 1,
        step: Optional[int] = None,
        step_flops: Optional[float] = None,
        peak_flops: Optional[float] = None,
        n_devices: int = 1,
        metrics: Optional[Dict[str, float]] = None,
        emit_event: bool = True,
        **extra: Any,
    ) -> Dict[str, Any]:
        """Record one dispatch covering ``n_steps`` optimizer steps of
        ``batch_size`` examples each, ``latency_s`` being the amortized
        PER-STEP latency. Updates the histogram/counters, feeds the
        recompile fallback heuristic, and (when enabled) emits a ``step``
        event with the derived examples/sec and MFU."""
        self.step_hist.observe(latency_s)
        self.steps.inc(n_steps)
        self.examples.inc(n_steps * batch_size)
        self.tracker.observe_step(latency_s)
        examples_per_sec = (
            batch_size / latency_s if latency_s > 0 else None
        )
        payload: Dict[str, Any] = {
            "latency_s": round(latency_s, 6),
            "examples_per_sec": (
                round(examples_per_sec, 2) if examples_per_sec else None
            ),
            "n_steps": n_steps,
            "batch_size": batch_size,
        }
        if step is not None:
            payload["step"] = int(step)
        step_mfu = mfu(step_flops, latency_s, peak_flops, n_devices)
        if step_mfu is not None:
            payload["mfu"] = step_mfu
        if metrics:
            payload.update({
                k: round(float(v), 6) for k, v in metrics.items()
            })
        payload.update(extra)
        self._last_step_payload = {
            k: payload[k]
            for k in ("step", "latency_s", "examples_per_sec")
            if k in payload
        }
        if emit_event:
            self.emit("step", **payload)
        return payload

    # -- aggregates ---------------------------------------------------------

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        return {
            "p50": self.step_hist.percentile(50),
            "p95": self.step_hist.percentile(95),
            "p99": self.step_hist.percentile(99),
        }

    def epoch(
        self, epoch: int, metrics: Optional[Dict[str, float]] = None,
        **extra: Any,
    ) -> None:
        """Per-epoch aggregate event: latency percentiles so far, device
        memory stats where the backend exposes them, and the recompile
        count (cumulative — a growing number across same-shape epochs is
        the retrace-storm signal)."""
        fields: Dict[str, Any] = {
            "epoch": int(epoch),
            "latency": {
                k: round(v, 6) if v is not None else None
                for k, v in self.latency_percentiles().items()
            },
            "steps_total": int(self.steps.total()),
            "examples_total": int(self.examples.total()),
            "recompiles_total": self.tracker.count,
        }
        mem = device_memory_stats()
        if mem is not None:
            fields["device_memory"] = mem
            for dev, stats in mem.items():
                if "bytes_in_use" in stats:
                    self.registry.gauge(
                        "device_hbm_bytes_in_use", "live HBM per device"
                    ).set(stats["bytes_in_use"], device=dev)
        if metrics:
            fields.update({
                k: round(float(v), 6) for k, v in metrics.items()
            })
        fields.update(extra)
        self.emit("epoch", **fields)

    def checkpoint(self, epoch: int, path: str, *, best: bool) -> None:
        self.registry.counter(
            "checkpoints_total", "checkpoint saves"
        ).inc()
        self.emit("checkpoint", epoch=int(epoch), path=path, best=best)


def peak_for_default_device(backend: str = "bf16"):
    """(peak FLOP/s, precision-label) of local device 0 — the MFU
    denominator per chip (``mfu`` multiplies by n_devices)."""
    try:
        import jax

        return device_peak_flops(jax.devices()[0], backend)
    except (ImportError, RuntimeError, IndexError):
        return None, "unknown"
