"""Declarative SLOs with multiwindow multi-burn-rate alerting.

The Google SRE Workbook's production alerting shape over the repo's
own metrics plane: an :class:`SLOSpec` states an objective ("99% of
routed requests succeed", "99% of requests finish under 1500 ms") and
the monitor tracks the **burn rate** — the rate the error budget is
being consumed, as a multiple of the sustainable rate:

    burn = (bad fraction over window) / (1 - objective)

Burn 1.0 spends exactly the budget over the budget window; burn 14.4
exhausts a 30-day budget in 2 days — the classic page threshold. One
window can't alert well alone: a short window pages on blips, a long
window pages an hour late and stays red long after recovery. So each
spec evaluates TWO windows and an alert **opens** only when the fast
AND slow burn both exceed their thresholds (sustained, current), and
**closes** when the fast window drains below its threshold (recovery
is visible quickly, because the short window forgets quickly).

Everything is clock-injectable and pure-host: ``observe_*`` feeds
(timestamp, good?) pairs into per-second-ish ring buckets, and
``evaluate(now)`` — called from the router's probe loop, the fleet
harness, or a test driving a fake clock — computes burn rates, sets
the ``slo_burn_rate``/``slo_budget_remaining`` gauges, and emits
``slo_alert`` open/close events through whatever ``emit`` callable it
was given (a ``Telemetry.emit``, or a plain list appender in the
chaos harness). Nothing here imports jax or does I/O.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "SLOSpec",
    "SLOMonitor",
    "default_fleet_slos",
]

SLO_BURN_RATE = "slo_burn_rate"
SLO_BUDGET_REMAINING = "slo_budget_remaining"
SLO_ALERTS_TOTAL = "slo_alerts_total"


@dataclass(frozen=True)
class SLOSpec:
    """One objective plus its alerting windows.

    ``signal`` selects what an observation means:

      * ``availability`` — good = the request completed ok;
      * ``latency`` — good = the request completed ok AND under
        ``threshold_ms`` (a failed request burns latency budget too:
        users experience it as slow, not as fast-and-broken).

    ``stream`` routes observations: ``request`` specs consume
    :meth:`SLOMonitor.observe_request`, ``lm_token`` specs consume
    :meth:`SLOMonitor.observe_token` (LM inter-token latency).
    """

    name: str
    objective: float                      # good fraction, e.g. 0.999
    signal: str = "availability"          # availability | latency
    threshold_ms: Optional[float] = None  # latency signal only
    stream: str = "request"               # request | lm_token
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    fast_burn: float = 14.4               # page thresholds (SRE WB)
    slow_burn: float = 6.0
    budget_window_s: float = 3600.0       # budget-remaining horizon
    min_events: int = 10                  # below this: no alerting

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if self.signal not in ("availability", "latency"):
            raise ValueError(f"unknown signal {self.signal!r}")
        if self.signal == "latency" and self.threshold_ms is None:
            raise ValueError("latency signal requires threshold_ms")
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError("fast window must be shorter than slow")


def default_fleet_slos(
    *,
    availability_objective: float = 0.99,
    request_p99_ms: float = 1500.0,
    lm_inter_token_p99_ms: float = 250.0,
    fast_window_s: float = 60.0,
    slow_window_s: float = 300.0,
) -> Tuple[SLOSpec, ...]:
    """The three SLOs the fleet router tracks out of the box: routed
    availability, request latency p99 (as a threshold objective: 99%
    under the deadline-ish bound), and LM inter-token p99."""
    return (
        SLOSpec("availability", availability_objective,
                signal="availability",
                fast_window_s=fast_window_s, slow_window_s=slow_window_s),
        SLOSpec("request_p99", 0.99, signal="latency",
                threshold_ms=request_p99_ms,
                fast_window_s=fast_window_s, slow_window_s=slow_window_s),
        SLOSpec("lm_inter_token_p99", 0.99, signal="latency",
                threshold_ms=lm_inter_token_p99_ms, stream="lm_token",
                fast_window_s=fast_window_s, slow_window_s=slow_window_s),
    )


class _Track:
    """Ring of (bucket_start, good, total) for one spec. Bucket width
    adapts to the fast window so a 0.5 s chaos-probe window still gets
    ~30 evaluation points."""

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        self.bucket_s = max(spec.fast_window_s / 30.0, 0.02)
        horizon = max(spec.slow_window_s, spec.budget_window_s)
        self.buckets: deque = deque(
            maxlen=int(horizon / self.bucket_s) + 2
        )
        self.state = "ok"                 # ok | open
        self.opens = 0
        self.closes = 0
        self.good_total = 0
        self.total = 0

    def observe(self, good: bool, now: float) -> None:
        start = now - (now % self.bucket_s)
        if not self.buckets or self.buckets[-1][0] != start:
            self.buckets.append([start, 0, 0])
        row = self.buckets[-1]
        row[1] += 1 if good else 0
        row[2] += 1
        self.good_total += 1 if good else 0
        self.total += 1

    def window(self, now: float, window_s: float) -> Tuple[int, int]:
        """(good, total) over [now - window_s, now]."""
        cutoff = now - window_s
        good = total = 0
        for start, g, t in reversed(self.buckets):
            if start + self.bucket_s < cutoff:
                break
            good += g
            total += t
        return good, total

    def burn(self, now: float, window_s: float) -> Tuple[float, int]:
        good, total = self.window(now, window_s)
        if total == 0:
            return 0.0, 0
        bad_frac = 1.0 - good / total
        return bad_frac / (1.0 - self.spec.objective), total


class SLOMonitor:
    """Evaluates a set of :class:`SLOSpec` over observed outcomes.

    Thread-safe (the router's dispatch threads observe while the probe
    loop evaluates). ``registry`` (optional) receives the burn-rate /
    budget gauges; ``emit(kind, **fields)`` (optional) receives
    ``slo_alert`` events; ``clock`` is injectable — unit tests drive
    open→close transitions deterministically with a fake clock.
    """

    def __init__(
        self,
        specs: Sequence[SLOSpec] = (),
        *,
        registry: Any = None,
        emit: Optional[Callable[..., Any]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not specs:
            specs = default_fleet_slos()
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._clock = clock
        self._emit = emit
        self._lock = threading.Lock()
        self._tracks = {s.name: _Track(s) for s in specs}
        self._burn_gauge = self._budget_gauge = self._alerts_ctr = None
        if registry is not None:
            self._burn_gauge = registry.gauge(
                SLO_BURN_RATE,
                "SLO error-budget burn rate (1.0 = sustainable)",
            )
            self._budget_gauge = registry.gauge(
                SLO_BUDGET_REMAINING,
                "fraction of SLO error budget left over the budget window",
            )
            self._alerts_ctr = registry.counter(
                SLO_ALERTS_TOTAL, "SLO alert transitions"
            )

    @property
    def specs(self) -> Tuple[SLOSpec, ...]:
        return tuple(t.spec for t in self._tracks.values())

    # -- feeding ---------------------------------------------------------

    def observe_request(self, ok: bool, latency_ms: Optional[float] = None,
                        now: Optional[float] = None) -> None:
        """One routed request at its final status."""
        now = self._clock() if now is None else now
        with self._lock:
            for track in self._tracks.values():
                spec = track.spec
                if spec.stream != "request":
                    continue
                if spec.signal == "availability":
                    track.observe(bool(ok), now)
                else:
                    good = bool(ok) and latency_ms is not None \
                        and latency_ms <= spec.threshold_ms
                    track.observe(good, now)

    def observe_token(self, inter_token_ms: float,
                      now: Optional[float] = None) -> None:
        """One LM decode inter-token gap."""
        now = self._clock() if now is None else now
        with self._lock:
            for track in self._tracks.values():
                spec = track.spec
                if spec.stream != "lm_token":
                    continue
                track.observe(inter_token_ms <= spec.threshold_ms, now)

    # -- evaluating ------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Recompute burn rates, update gauges, emit open/close
        transitions. Returns the transitions (possibly empty)."""
        now = self._clock() if now is None else now
        transitions: List[dict] = []
        with self._lock:
            for name, track in self._tracks.items():
                spec = track.spec
                burn_fast, n_fast = track.burn(now, spec.fast_window_s)
                burn_slow, n_slow = track.burn(now, spec.slow_window_s)
                _, n_budget = track.window(now, spec.budget_window_s)
                budget_burn, _ = track.burn(now, spec.budget_window_s)
                budget_remaining = 1.0 - budget_burn
                if self._burn_gauge is not None:
                    self._burn_gauge.set(round(burn_fast, 4),
                                         slo=name, window="fast")
                    self._burn_gauge.set(round(burn_slow, 4),
                                         slo=name, window="slow")
                    self._budget_gauge.set(round(budget_remaining, 4),
                                           slo=name)
                enough = n_fast >= spec.min_events
                fields = {
                    "slo": name,
                    "signal": spec.signal,
                    "objective": spec.objective,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "fast_window_s": spec.fast_window_s,
                    "slow_window_s": spec.slow_window_s,
                    "events_fast": n_fast,
                    "events_slow": n_slow,
                    "budget_remaining": round(budget_remaining, 4),
                    "severity": "page",
                }
                if (track.state == "ok" and enough
                        and burn_fast >= spec.fast_burn
                        and burn_slow >= spec.slow_burn):
                    track.state = "open"
                    track.opens += 1
                    transitions.append({**fields, "state": "open"})
                elif track.state == "open" and burn_fast < spec.fast_burn:
                    track.state = "ok"
                    track.closes += 1
                    transitions.append({**fields, "state": "close"})
        for tr in transitions:
            if self._alerts_ctr is not None:
                self._alerts_ctr.inc(slo=tr["slo"], state=tr["state"])
            if self._emit is not None:
                self._emit("slo_alert", **tr)
        return transitions

    # -- reading ---------------------------------------------------------

    def state(self, name: str) -> str:
        with self._lock:
            return self._tracks[name].state

    def open_alerts(self) -> List[str]:
        with self._lock:
            return [n for n, t in self._tracks.items()
                    if t.state == "open"]

    def summary(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-SLO compliance report — the fleet harness embeds this in
        its bench section so the perf gate can score SLO compliance,
        not just raw availability."""
        now = self._clock() if now is None else now
        out: Dict[str, dict] = {}
        with self._lock:
            for name, track in self._tracks.items():
                spec = track.spec
                burn_fast, n_fast = track.burn(now, spec.fast_window_s)
                budget_burn, _ = track.burn(now, spec.budget_window_s)
                good_frac = (track.good_total / track.total
                             if track.total else None)
                out[name] = {
                    "signal": spec.signal,
                    "objective": spec.objective,
                    "events_total": track.total,
                    "good_fraction": (round(good_frac, 5)
                                      if good_frac is not None else None),
                    "burn_fast": round(burn_fast, 4),
                    "budget_remaining": round(1.0 - budget_burn, 4),
                    "state": track.state,
                    "alerts_opened": track.opens,
                    "alerts_closed": track.closes,
                    "compliant": (track.state == "ok"
                                  and (good_frac is None
                                       or good_frac >= spec.objective)),
                }
        return out
