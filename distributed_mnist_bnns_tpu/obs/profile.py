"""On-demand XLA profiling — arm ``jax.profiler`` captures at runtime.

The trainer has always been able to trace its first-epoch steps
(``--profile-dir``); this module makes device profiling an *operational*
tool instead of a launch-time decision:

  * ``POST /admin/profile {"duration_ms": N}`` on both serving front
    ends captures a live window off-path (the handler thread sleeps
    through the capture; serving traffic never blocks on it) and
    returns the artifact directory + byte sizes;
  * ``cli train --profile-steps A:B`` captures a step window mid-run;
  * while a capture is armed, the serving/training dispatch sites wrap
    their device calls in ``jax.profiler.StepTraceAnnotation`` markers
    carrying the run's ``x-jg-trace`` trace id, so the device profile
    and the host span trees (obs/trace) of the same window join on id —
    a Perfetto view of host spans next to the xplane of the chips;
  * ``cli profile DIR`` summarizes a capture (top ops by total time,
    compile-vs-execute split) from the Chrome-trace half of the
    artifact, stdlib-only — no TensorBoard required to answer "what was
    the device doing".

One capture at a time per process (a ``jax.profiler`` limit — the
global profiler state cannot nest); a second concurrent request gets
:class:`ProfileBusyError` (HTTP 409). Disabled cost: instrumented
dispatch sites check one attribute (``profiler.active``); nothing else
runs and ``jax.profiler`` is never imported until a capture starts.
"""

from __future__ import annotations

import gzip
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

PROFILE_CAPTURES_TOTAL = "profile_captures_total"

MAX_CAPTURE_MS = 60_000.0

# The profiler marker name carried into the xplane by every annotated
# dispatch — one grep key for the join between host spans and device
# profiles (the annotation's ``jg_trace`` arg holds the trace id).
STEP_MARKER = "jg_step"


class ProfileBusyError(RuntimeError):
    """A capture is already in progress (one per process)."""


class ProfileManager:
    """Owns the process's single capture slot.

    ``capture`` is the blocking duration-window form (the /admin
    endpoint); ``start``/``stop`` are the step-window form (the
    trainer drives them at step boundaries). Both emit a
    ``profile_capture`` event (artifact dir, file count, bytes) and
    increment ``profile_captures_total`` when telemetry is attached at
    the call."""

    def __init__(self, registry: Any = None):
        self._lock = threading.Lock()
        self.active = False           # the hot paths' one-attribute check
        self._dir: Optional[str] = None
        self._t0 = 0.0
        if registry is None:
            from .registry import default_registry

            registry = default_registry()
        self._captures_ctr = registry.counter(
            PROFILE_CAPTURES_TOTAL,
            "on-demand device-profile captures completed",
        )

    # -- step-window form (trainer) ------------------------------------------

    def start(self, artifact_dir: str) -> None:
        """Begin a capture into ``artifact_dir``. Raises
        :class:`ProfileBusyError` when one is already running."""
        if not self._lock.acquire(blocking=False):
            raise ProfileBusyError(
                "a profile capture is already in progress "
                "(one per process)"
            )
        try:
            import jax.profiler

            os.makedirs(artifact_dir, exist_ok=True)
            jax.profiler.start_trace(artifact_dir)
        except BaseException:
            self._lock.release()
            raise
        self._dir = artifact_dir
        self._t0 = time.monotonic()
        self.active = True

    def stop(self, telemetry: Any = None) -> Dict[str, Any]:
        """End the capture; returns the artifact summary (dir, files,
        total bytes, wall duration) and emits ``profile_capture``."""
        if not self.active:
            raise RuntimeError("no profile capture in progress")
        import jax.profiler

        artifact_dir = self._dir or "."
        try:
            jax.profiler.stop_trace()
        finally:
            # The capture slot frees even if the dump failed — a wedged
            # profiler must not permanently 409 the endpoint.
            self.active = False
            self._dir = None
            self._lock.release()
        dur_ms = round((time.monotonic() - self._t0) * 1e3, 1)
        files = capture_files(artifact_dir)
        summary = {
            "dir": artifact_dir,
            "duration_ms": dur_ms,
            "files": len(files),
            "total_bytes": sum(f["bytes"] for f in files),
        }
        self._captures_ctr.inc()
        if telemetry is not None:
            try:
                telemetry.emit(
                    "profile_capture", **summary,
                    file_list=[f["path"] for f in files][:20],
                )
            except Exception:
                log.debug("profile_capture emit failed", exc_info=True)
        log.info("profile capture: %s", summary)
        return summary

    # -- duration-window form (/admin/profile) -------------------------------

    def capture(
        self, duration_ms: float, *, artifact_dir: str,
        telemetry: Any = None,
    ) -> Dict[str, Any]:
        """Blocking duration-window capture. The caller's thread (an
        HTTP handler — off the serving path by construction) sleeps
        through the window; the annotated dispatch sites do the actual
        marking. Duration is clamped to ``MAX_CAPTURE_MS``."""
        duration_ms = float(duration_ms)
        if not duration_ms > 0:
            raise ValueError(
                f"duration_ms must be > 0, got {duration_ms}"
            )
        duration_ms = min(duration_ms, MAX_CAPTURE_MS)
        self.start(artifact_dir)
        try:
            time.sleep(duration_ms / 1e3)
        finally:
            summary = self.stop(telemetry=telemetry)
        return summary


_profiler: Optional[ProfileManager] = None
_profiler_lock = threading.Lock()


def get_profiler() -> ProfileManager:
    """Process-wide manager — the capture slot is a process property
    (``jax.profiler`` keeps global state)."""
    global _profiler
    with _profiler_lock:
        if _profiler is None:
            _profiler = ProfileManager()
        return _profiler


def default_capture_dir(telemetry_dir: Optional[str]) -> Optional[str]:
    """``<telemetry_dir>/profile`` — THE default artifact location,
    shared by both serving front ends' /admin/profile and the
    trainer's ``--profile-steps`` window (None without a telemetry
    dir; callers then require an explicit dir)."""
    if not telemetry_dir:
        return None
    return os.path.join(telemetry_dir, "profile")


# -- reading a capture (cli profile) -----------------------------------------


def capture_files(artifact_dir: str) -> List[Dict[str, Any]]:
    """Every file under a capture directory with its size — the
    /admin/profile response body and the smoke's load assertion."""
    out: List[Dict[str, Any]] = []
    for root, _, files in os.walk(artifact_dir):
        for name in files:
            p = os.path.join(root, name)
            try:
                out.append({
                    "path": os.path.relpath(p, artifact_dir),
                    "bytes": os.path.getsize(p),
                })
            except OSError:
                continue
    out.sort(key=lambda f: f["path"])
    return out


def find_trace_json(artifact_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under a capture dir (the profiler
    writes one per host under ``plugins/profile/<ts>/``)."""
    best: Optional[str] = None
    best_mtime = -1.0
    for root, _, files in os.walk(artifact_dir):
        for name in files:
            if not name.endswith(".trace.json.gz"):
                continue
            p = os.path.join(root, name)
            try:
                m = os.path.getmtime(p)
            except OSError:
                continue
            if m > best_mtime:
                best, best_mtime = p, m
    return best


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """The Chrome-trace events of one ``*.trace.json.gz``."""
    with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
        data = json.load(f)
    return list(data.get("traceEvents", []))


def summarize_capture(
    artifact_dir: str, *, top: int = 15,
) -> Dict[str, Any]:
    """Fold a capture into a terminal-readable summary: top ops by
    total duration (python frame events — ``$file:line`` names — are
    grouped separately so XLA op names float to the top), the
    compile-vs-non-compile split, and any ``jg_step`` marker trace ids
    (the host-span join keys). Approximate by design: Chrome-trace
    events nest, so totals over-count parents — good enough to answer
    "what dominated" without TensorBoard."""
    trace_path = find_trace_json(artifact_dir)
    if trace_path is None:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {artifact_dir} — is this a "
            "jax.profiler capture directory?"
        )
    events = load_trace_events(trace_path)
    ops: Dict[str, List[float]] = {}
    compile_us = 0.0
    total_us = 0.0
    trace_ids = set()
    steps = 0
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", "?"))
        dur = float(e.get("dur", 0.0) or 0.0)
        args = e.get("args") or {}
        if "jg_trace" in args:
            trace_ids.add(args["jg_trace"])
            steps += 1
        total_us += dur
        if "compile" in name.lower():
            compile_us += dur
        if name.startswith("$"):      # python frame events
            continue
        row = ops.setdefault(name, [0.0, 0.0])
        row[0] += 1
        row[1] += dur
    top_ops = sorted(ops.items(), key=lambda kv: -kv[1][1])[:top]
    return {
        "dir": artifact_dir,
        "trace_json": trace_path,
        "events": len(events),
        "annotated_steps": steps,
        "trace_ids": sorted(trace_ids),
        "compile_ms": round(compile_us / 1e3, 3),
        "other_ms": round(max(total_us - compile_us, 0.0) / 1e3, 3),
        "top_ops": [
            {"name": name, "count": int(c), "total_ms": round(us / 1e3, 3)}
            for name, (c, us) in top_ops
        ],
    }


def render_capture_summary(summary: Dict[str, Any]) -> str:
    """Human-readable capture summary (the ``cli profile`` default)."""
    lines = [
        f"profile capture: {summary['dir']}",
        f"  events {summary['events']}   annotated steps "
        f"{summary['annotated_steps']}   compile {summary['compile_ms']}"
        f" ms   other {summary['other_ms']} ms",
    ]
    if summary["trace_ids"]:
        lines.append(
            "  joinable trace ids: " + ", ".join(summary["trace_ids"][:8])
        )
    lines.append("  top ops by total time (approximate, nested):")
    for op in summary["top_ops"]:
        lines.append(
            f"    {op['total_ms']:>12.3f} ms  x{op['count']:<6} "
            f"{op['name'][:80]}"
        )
    return "\n".join(lines)
