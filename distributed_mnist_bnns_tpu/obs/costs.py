"""Per-program HLO cost ledger — what a compiled program *costs*.

obs/flops.py answers "what SHOULD a step cost" analytically; this module
banks what XLA says each compiled program actually costs: at every
compile (online boot, AOT store hit/miss, trainer init) the executable's
``cost_analysis()`` and ``memory_analysis()`` are folded into one row
per program name — {flops, bytes accessed, argument/output/temp/peak
HBM} — alongside measured dispatch times fed from the serving/training
hot paths, so **measured MFU per program** (cost-analysis flops ÷ mean
dispatch time ÷ chip peak) is derivable live (/healthz), from a bench
record (``bench.py --device-costs-bench``), and post-hoc from an events
dir alone (``cli telemetry`` ``programs`` section, via the
``program_cost`` events + the closing ``metrics`` snapshot's
``program_dispatch_seconds`` histogram).

Reconciliation invariant: for the classifier train step, the
cost-analysis flops and the analytic ``obs/flops.train_step_flops``
walk must agree within a small factor (XLA's model counts elementwise/
optimizer noise the 3×2×MACs convention deliberately excludes, so they
are close but not equal) — tested per backend, and the disagreement
surfacing IS the signal (a backend whose GEMMs stopped lowering to
``dot``/``conv`` shows up as a ratio jump long before a wall-clock
regression does).

Cost discipline (OBSERVABILITY.md "Device profiling"):

  * **off by default** — every hot-path feed (``observe``) and every
    compile-site hook (``record``) starts with one attribute check on
    ``enabled`` and returns; arming is ``JG_COSTS=1`` or the serving
    ``--costs`` flag;
  * **armed, it must keep a budget-0 recompile fence green** — on
    executables that already expose ``cost_analysis`` (``Compiled``,
    incl. AOT-deserialized ones) ``record`` touches only the object in
    hand, no trace, no compile. A jitted (not-yet-lowered) function is
    only analyzed when the caller passes ``example_args`` — that path
    performs one throwaway ``lower().compile()`` and is therefore
    reserved for pre-fence boot windows (cold boots, trainer init);
  * failures degrade to a row with a ``reason`` — a backend whose cost
    model is unavailable (remote-compile tunnels) must never take down
    the boot that asked.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)

ENV_COSTS = "JG_COSTS"

PROGRAM_COMPILES_TOTAL = "program_compiles_total"
PROGRAM_DISPATCH_SECONDS = "program_dispatch_seconds"
PROGRAM_FLOPS = "program_flops"

# Dispatch-latency buckets (seconds): serving decode iterations sit in
# the 100us-10ms range on CPU, train steps up to seconds.
_DISPATCH_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def extract_costs(compiled: Any) -> Dict[str, Any]:
    """Normalize one executable's ``cost_analysis()`` +
    ``memory_analysis()`` into a plain JSON-able row. Never raises:
    an unavailable cost model yields ``{"reason": ...}``."""
    row: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        # jax returns one properties-dict per computation (usually one).
        if isinstance(ca, dict):
            ca = [ca]
        flops = 0.0
        bytes_accessed = 0.0
        for props in ca or []:
            flops += float(props.get("flops", 0.0) or 0.0)
            bytes_accessed += float(
                props.get("bytes accessed", 0.0) or 0.0
            )
        row["flops"] = flops
        row["bytes_accessed"] = bytes_accessed
    except Exception as e:  # cost model unavailable on this backend
        row["reason"] = f"cost_analysis: {type(e).__name__}: {e}"[:200]
    try:
        ma = compiled.memory_analysis()
        hbm = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(
                ma.generated_code_size_in_bytes
            ),
        }
        # The executable's worst-case live footprint: arguments +
        # outputs + scratch (aliased bytes are counted once — they
        # overlay an argument).
        hbm["peak_bytes"] = (
            hbm["argument_bytes"] + hbm["output_bytes"]
            + hbm["temp_bytes"] - hbm["alias_bytes"]
        )
        row["hbm"] = hbm
    except Exception as e:
        row.setdefault(
            "reason", f"memory_analysis: {type(e).__name__}: {e}"[:200]
        )
    return row


class CostLedger:
    """Process-wide per-program cost + dispatch-time accounting.

    ``record`` banks an executable's static costs under a program name
    (idempotent-ish: a reload/rebank overwrites the row — the ledger
    describes the SERVING program); ``observe`` feeds measured dispatch
    seconds from the hot paths (one attribute check + a locked float
    add when armed, one attribute check when not); ``snapshot`` joins
    both into per-program measured MFU."""

    def __init__(
        self, registry: Any = None, *, enabled: Optional[bool] = None,
    ):
        if enabled is None:
            enabled = os.environ.get(ENV_COSTS, "") not in ("", "0")
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._programs: Dict[str, Dict[str, Any]] = {}
        self._times: Dict[str, Dict[str, float]] = {}
        if registry is None:
            from .registry import default_registry

            registry = default_registry()
        self._registry = registry
        self._compiles_ctr = registry.counter(
            PROGRAM_COMPILES_TOTAL,
            "cost-analyzed program compiles (program, source labels)",
        )
        # Cached handle: observe() runs on dispatch hot paths — the
        # registry's get-or-create lookup must not be paid per call.
        # Created lazily on the first ARMED observe, so a disabled
        # ledger registers nothing (disabled-mode inertness).
        self._dispatch_hist = None

    # -- compile-site hook ---------------------------------------------------

    def record(
        self,
        name: str,
        executable: Any,
        *,
        example_args: Any = None,
        telemetry: Any = None,
        source: str = "online",
        **extra: Any,
    ) -> Optional[Dict[str, Any]]:
        """Bank ``executable``'s costs under ``name``.

        An object exposing ``cost_analysis`` (a ``Compiled``, incl.
        AOT-deserialized) is analyzed in place — no compile. A jitted
        function is analyzed only when ``example_args`` is given, via a
        throwaway ``lower(*example_args).compile()`` — that DOES fire a
        backend compile, so callers reserve it for pre-fence boot
        windows. Emits one ``program_cost`` event when ``telemetry`` is
        attached. No-op (one attribute check) when disabled."""
        if not self.enabled:
            return None
        row: Dict[str, Any] = {"program": name, "source": source}
        try:
            target = executable
            if not hasattr(target, "cost_analysis"):
                if example_args is None or not hasattr(target, "lower"):
                    row["reason"] = "no cost_analysis and no example_args"
                    target = None
                else:
                    # Throwaway analysis compile (boot window only).
                    target = target.lower(*example_args).compile()
            if target is not None:
                row.update(extract_costs(target))
        except Exception as e:  # never take down the boot that asked
            log.warning("cost record for %s failed: %s", name, e)
            row["reason"] = f"{type(e).__name__}: {e}"[:200]
        row.update(extra)
        with self._lock:
            self._programs[name] = row
        self._compiles_ctr.inc(program=name, source=source)
        if row.get("flops"):
            self._registry.gauge(
                PROGRAM_FLOPS, "cost-analysis flops per dispatch"
            ).set(row["flops"], program=name)
        if telemetry is not None:
            try:
                telemetry.emit("program_cost", **row)
            except Exception:  # telemetry is best-effort here
                log.debug("program_cost emit failed", exc_info=True)
        return row

    # -- hot-path dispatch-time feed -----------------------------------------

    def observe(self, name: str, seconds: float, n: int = 1) -> None:
        """Feed ``n`` dispatches of ``name`` totalling ``seconds``.
        Call sites guard with ``if ledger.enabled`` so the disabled
        cost is exactly one attribute check."""
        if not self.enabled:
            return
        n = max(int(n), 1)
        with self._lock:
            t = self._times.setdefault(name, {"n": 0.0, "s": 0.0})
            t["n"] += n
            t["s"] += float(seconds)
        hist = self._dispatch_hist
        if hist is None:
            # Idempotent get-or-create; a racing first observe caches
            # the same instrument.
            hist = self._dispatch_hist = self._registry.histogram(
                PROGRAM_DISPATCH_SECONDS,
                "measured dispatch latency per cost-analyzed program",
                buckets=_DISPATCH_BUCKETS,
            )
        # One histogram observation PER DISPATCH (the per-dispatch mean
        # repeated n times), so the series count/sum agree with the
        # internal tally — post-hoc readers joining this histogram get
        # the same dispatch counts /healthz reports. n is small
        # (spec drafts, prefill chunks); the loop is a few locked adds.
        per = float(seconds) / n
        for _ in range(n):
            hist.observe(per, program=name)

    # -- reads ---------------------------------------------------------------

    def costs(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            row = self._programs.get(name)
            return dict(row) if row else None

    def measured_mfu(self, name: str) -> Optional[float]:
        """flops-per-dispatch ÷ mean dispatch seconds ÷ chip peak —
        None until both a cost row and a dispatch observation exist."""
        with self._lock:
            row = self._programs.get(name)
            t = self._times.get(name)
        if not row or not row.get("flops") or not t or not t["n"]:
            return None
        from .flops import mfu
        from .telemetry import peak_for_default_device

        peak, _ = peak_for_default_device()
        return mfu(row["flops"], t["s"] / t["n"], peak)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-program view for /healthz and bench sections: static
        costs + dispatch count/mean + measured MFU."""
        with self._lock:
            programs = {k: dict(v) for k, v in self._programs.items()}
            times = {k: dict(v) for k, v in self._times.items()}
        from .flops import mfu
        from .telemetry import peak_for_default_device

        peak, precision = peak_for_default_device()
        for name, row in programs.items():
            t = times.get(name)
            if t and t["n"]:
                mean_s = t["s"] / t["n"]
                row["dispatches"] = int(t["n"])
                row["mean_dispatch_ms"] = round(mean_s * 1e3, 4)
                m = mfu(row.get("flops"), mean_s, peak)
                if m is not None:
                    row["mfu"] = m
                    row["peak_precision"] = precision
        return programs


_ledger: Optional[CostLedger] = None
_ledger_lock = threading.Lock()


def get_ledger() -> CostLedger:
    """The process-wide ledger every compile site and hot path feeds
    (compiles are a process property, like recompile counts)."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = CostLedger()
        return _ledger


def arm_ledger(flag: Optional[bool]) -> CostLedger:
    """The process ledger with an explicit-flag override — the one
    arming precedence both serving front ends share: an explicit
    ``--costs``/``--no-costs`` wins; None keeps the JG_COSTS env
    default the ledger was constructed with."""
    ledger = get_ledger()
    if flag is not None:
        ledger.enabled = bool(flag)
    return ledger
