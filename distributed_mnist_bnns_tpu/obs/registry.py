"""Metrics registry — counters, gauges and fixed-bucket histograms with
labeled series, behind one thread-safe snapshot API.

The reference repo's only "metrics" are rank-0 prints of AverageMeter
deltas (mnist-dist2.py:109-150); this registry is the production
counterpart: every layer (trainer, infer paths, parallel backends, bench)
records into named series, and one ``snapshot()`` renders the whole
process state as plain dicts — the data the JSONL event sink
(obs/events.py) and the ``telemetry`` CLI consume.

Threading: instruments are updated from the training loop, the heartbeat
thread and async checkpoint writers concurrently; every mutation holds
the owning registry's lock. Updates are O(1) host work (a float add
under a lock), cheap enough for per-step hot-loop use.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Optional, Tuple

# Default latency buckets (seconds): 100us .. ~2min, roughly x2 spaced —
# wide enough for a CPU smoke step and a remote-tunnel dispatch alike.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing count, optionally split by labels."""

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._series: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, **labels: str) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "type": "counter",
                "help": self.help,
                "series": [
                    {"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())
                ],
            }


class Gauge:
    """Last-written value (can go up or down), optionally labeled."""

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._series: Dict[Tuple, float] = {}

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def value(self, **labels: str) -> Optional[float]:
        with self._lock:
            return self._series.get(_label_key(labels))

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "type": "gauge",
                "help": self.help,
                "series": [
                    {"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())
                ],
            }


class _HistSeries:
    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1: overflow bucket
        self.sum = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = float("-inf")


class Histogram:
    """Fixed-bucket histogram with percentile estimation.

    Buckets are upper bounds (le semantics); one implicit +inf overflow
    bucket catches the tail. ``percentile`` interpolates linearly inside
    the owning bucket — exact enough for p50/p95/p99 latency reporting
    (the buckets are ~x2 spaced, so the estimate is within ~2x and
    usually much closer; min/max are tracked exactly)."""

    def __init__(
        self, name: str, help: str, lock: threading.Lock,
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS,
    ):
        self.name = name
        self.help = help
        self._lock = lock
        self.buckets: List[float] = sorted(float(b) for b in buckets)
        if not self.buckets:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self._series: Dict[Tuple, _HistSeries] = {}

    def _get(self, labels: Dict[str, str]) -> _HistSeries:
        key = _label_key(labels)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _HistSeries(len(self.buckets))
        return s

    def observe(self, value: float, **labels: str) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            s = self._get(labels)
            s.counts[idx] += 1
            s.sum += v
            s.count += 1
            s.min = min(s.min, v)
            s.max = max(s.max, v)

    def percentile(self, q: float, **labels: str) -> Optional[float]:
        """Estimated q-th percentile (q in [0, 100]) for a label set."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s.count == 0:
                return None
            rank = q / 100.0 * s.count
            seen = 0
            for i, c in enumerate(s.counts):
                if c == 0:
                    continue
                if seen + c >= rank:
                    hi = (
                        self.buckets[i] if i < len(self.buckets) else s.max
                    )
                    lo = self.buckets[i - 1] if i > 0 else min(s.min, hi)
                    frac = (rank - seen) / c
                    return min(max(lo + (hi - lo) * frac, s.min), s.max)
                seen += c
            return s.max

    def mean(self, **labels: str) -> Optional[float]:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.sum / s.count if s is not None and s.count else None

    def count(self, **labels: str) -> int:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.count if s is not None else 0

    def snapshot(self) -> Dict:
        with self._lock:
            series = []
            for k, s in sorted(self._series.items()):
                series.append({
                    "labels": dict(k),
                    "count": s.count,
                    "sum": s.sum,
                    "min": s.min if s.count else None,
                    "max": s.max if s.count else None,
                    "bucket_counts": list(s.counts),
                })
            return {
                "type": "histogram",
                "help": self.help,
                "buckets": list(self.buckets),
                "series": series,
            }


class MetricsRegistry:
    """Name -> instrument map. ``counter``/``gauge``/``histogram`` are
    get-or-create (repeat calls return the same instrument, so call
    sites don't need to coordinate); a name registered as one kind
    cannot be re-registered as another."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get_or_create(self, name: str, cls, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                # jg: disable=JG010 -- factories are this module's own instrument constructors (the counter/gauge/histogram lambdas below), never user code: they cannot re-enter the registry, and get-or-create must stay atomic so a name maps to ONE instrument
                inst = self._instruments[name] = factory()
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(
            name, Counter, lambda: Counter(name, help, self._lock)
        )

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(
            name, Gauge, lambda: Gauge(name, help, self._lock)
        )

    def histogram(
        self, name: str, help: str = "",
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            name, Histogram,
            lambda: Histogram(name, help, self._lock, buckets),
        )

    def snapshot(self) -> Dict[str, Dict]:
        """Plain-dict view of every instrument (JSON-serializable)."""
        with self._lock:
            instruments = dict(self._instruments)
        return {name: inst.snapshot() for name, inst in
                sorted(instruments.items())}


def _prom_escape(value: str) -> str:
    """Label-VALUE escaping (backslash, quote, newline)."""
    return (
        str(value)
        .replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _prom_escape_help(value: str) -> str:
    """HELP-line escaping — the exposition format escapes only
    backslash and newline here (quotes are label-value-only)."""
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_prom_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _prom_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus(snapshot: Dict[str, Dict]) -> str:
    """Render a registry ``snapshot()`` in the Prometheus text
    exposition format (version 0.0.4) — the ``/metrics`` content both
    serving front ends return under ``Accept: text/plain``, so a stock
    Prometheus scraper can watch a replica fleet without a JSON
    adapter. Counters/gauges map directly; histograms emit cumulative
    ``_bucket{le=...}`` series (the snapshot's per-bucket counts summed
    left to right), ``_sum`` and ``_count``."""
    lines: List[str] = []
    for name, inst in sorted(snapshot.items()):
        kind = inst.get("type")
        if kind not in ("counter", "gauge", "histogram"):
            continue
        if inst.get("help"):
            lines.append(
                f"# HELP {name} {_prom_escape_help(inst['help'])}"
            )
        lines.append(f"# TYPE {name} {kind}")
        if kind in ("counter", "gauge"):
            for s in inst.get("series", []):
                lines.append(
                    f"{name}{_prom_labels(s.get('labels', {}))} "
                    f"{_prom_num(s['value'])}"
                )
            continue
        buckets = inst.get("buckets", [])
        for s in inst.get("series", []):
            labels = s.get("labels", {})
            cum = 0
            counts = s.get("bucket_counts", [])
            for le, c in zip(buckets, counts):
                cum += int(c)
                lines.append(
                    f"{name}_bucket"
                    f"{_prom_labels({**labels, 'le': repr(float(le))})} "
                    f"{cum}"
                )
            lines.append(
                f"{name}_bucket{_prom_labels({**labels, 'le': '+Inf'})} "
                f"{int(s.get('count', 0))}"
            )
            lines.append(
                f"{name}_sum{_prom_labels(labels)} "
                f"{_prom_num(s.get('sum', 0.0))}"
            )
            lines.append(
                f"{name}_count{_prom_labels(labels)} "
                f"{int(s.get('count', 0))}"
            )
    return "\n".join(lines) + ("\n" if lines else "")


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every layer records into by default.

    One registry per process keeps the ``telemetry`` CLI and the event
    sink's snapshots complete without plumbing a registry handle through
    every call site; tests that need isolation construct their own
    MetricsRegistry."""
    return _default_registry
