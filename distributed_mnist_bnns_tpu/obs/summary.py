"""Run-log summarization — the read side of the telemetry subsystem.

``summarize(path)`` folds a JSONL event log (obs/events.py schema) into
one plain dict; ``render_table`` formats it for humans. Both are exact:
percentiles here come from the per-step latencies recorded in the
events, not the registry's bucketed estimates (the registry serves the
live process; the log serves post-hoc analysis).

Fleet read side: ``summarize_fleet(dir)`` walks a fleet telemetry tree
(router events.jsonl + one subdirectory per replica) into one combined
summary (`cli telemetry --fleet`), and ``decision_timeline`` /
``render_decision_timeline`` fold the control plane's ``decision`` and
``slo_alert`` events into the replayable timeline behind
`cli fleet explain` and the perf gate's self-explaining fleet trips.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from .events import MANIFEST_KIND, read_events
from .heartbeat import read_heartbeats
from .trace import percentile as _percentile


def summarize(path: str) -> Dict[str, Any]:
    """Fold an event log into a summary dict (see OBSERVABILITY.md for
    the schema). Raises FileNotFoundError for a missing log."""
    all_events = list(read_events(path))
    # A reused telemetry dir appends runs to one file; report the LATEST
    # run (everything from the last manifest on) so a re-run never has
    # its numbers attributed to an older run's config/git rev. A log
    # with no manifest (hand-built, tests) aggregates everything.
    # Rotation copies (``rotated_copy`` — obs/events re-emits the
    # manifest into each fresh segment so pruning can't lose it) are
    # DATA fallbacks only: they must never re-scope the run to the
    # segment they open.
    last_manifest = max(
        (i for i, e in enumerate(all_events)
         if e.get("kind") == MANIFEST_KIND
         and not e.get("rotated_copy")),
        default=None,
    )
    if last_manifest is not None:
        events_in_run = all_events[last_manifest:]
    else:
        events_in_run = all_events

    manifests: List[Dict] = []
    latencies: List[float] = []
    mfus: List[Dict] = []
    losses: List[float] = []
    steps_total = 0
    examples_total = 0
    latency_weighted_s = 0.0
    epochs: List[Dict] = []
    evals: List[Dict] = []
    checkpoints = 0
    errors: List[Dict] = []
    recompiles: Optional[int] = None
    compile_seconds: Optional[float] = None
    wall_seconds: Optional[float] = None
    kinds: Dict[str, int] = {}
    bench_sections: List[Dict] = []
    infer_runs: List[Dict] = []
    programs: Dict[str, Dict[str, Any]] = {}
    metrics_snapshot: Optional[Dict] = None
    profile_captures: List[Dict] = []

    def _program(name: Any) -> Dict[str, Any]:
        return programs.setdefault(
            str(name), {"compiles": 0, "aot": {}}
        )

    manifest_copies: List[Dict] = []

    for ev in events_in_run:
        kind = ev.get("kind", "?")
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == MANIFEST_KIND:
            if ev.get("rotated_copy"):
                manifest_copies.append(ev)
            else:
                manifests.append(ev)
        elif kind == "step":
            n = int(ev.get("n_steps", 1) or 1)
            lat = ev.get("latency_s")
            if isinstance(lat, (int, float)):
                latencies.append(float(lat))
                latency_weighted_s += float(lat) * n
                if isinstance(ev.get("mfu"), (int, float)):
                    mfus.append({"mfu": float(ev["mfu"]),
                                 "w": float(lat) * n})
            if isinstance(ev.get("loss"), (int, float)):
                losses.append(float(ev["loss"]))
            steps_total += n
            examples_total += n * int(ev.get("batch_size", 0) or 0)
        elif kind == "epoch":
            epochs.append(ev)
            if isinstance(ev.get("recompiles_total"), int):
                recompiles = ev["recompiles_total"]
        elif kind == "eval":
            evals.append(ev)
        elif kind == "checkpoint":
            checkpoints += 1
        elif kind == "error":
            errors.append(ev)
        elif kind == "run_end":
            if isinstance(ev.get("recompiles_total"), int):
                recompiles = ev["recompiles_total"]
            compile_seconds = ev.get("compile_seconds")
            wall_seconds = ev.get("wall_seconds")
        elif kind == "bench":
            bench_sections.append(ev)
        elif kind == "infer":
            infer_runs.append(ev)
        elif kind == "program_cost":
            # Per-program cost ledger rows (obs/costs): the latest row
            # describes the serving program (a reload overwrites); the
            # close-time snapshot rows (final=True) carry dispatch
            # stats + measured MFU and are NOT extra compiles.
            row = _program(ev.get("program"))
            if not ev.get("final") and ev.get("source") != "aot_hit":
                # An AOT hit analyzes a stored executable — it is a
                # cost row, not a compile (the hit itself is counted
                # under the aot_hit event).
                row["compiles"] += 1
            for k in ("flops", "bytes_accessed", "hbm", "source",
                      "reason", "dispatches", "mean_dispatch_ms",
                      "mfu", "peak_precision"):
                if ev.get(k) is not None:
                    row[k] = ev[k]
        elif kind in ("aot_hit", "aot_miss", "aot_bank", "aot_fallback"):
            row = _program(ev.get("name"))
            aot = row["aot"]
            short = kind[len("aot_"):]
            aot[short] = aot.get(short, 0) + 1
        elif kind == "metrics":
            metrics_snapshot = ev.get("registry")
        elif kind == "profile_capture":
            profile_captures.append(ev)

    latencies.sort()
    if not manifests and manifest_copies:
        # The original segment was pruned by rotation: the earliest
        # surviving copy IS the run's manifest data.
        manifests = manifest_copies[:1]
    manifest = manifests[0] if manifests else {}
    summary: Dict[str, Any] = {
        "path": path,
        "schema_versions": sorted({
            m.get("v") for m in manifests
        }) if manifests else [],
        "manifest_count": len(manifests),
        "run": {
            "model": (manifest.get("config") or {}).get("model"),
            "started": manifest.get("ts"),
            "git_rev": manifest.get("git_rev"),
            "jax_version": manifest.get("jax_version"),
            "backend": (manifest.get("topology") or {}).get("backend"),
            "device_kind": (
                manifest.get("topology") or {}
            ).get("device_kind"),
            "device_count": (
                manifest.get("topology") or {}
            ).get("device_count"),
            "wall_seconds": wall_seconds,
        },
        "steps": {
            "count": steps_total,
            "examples": examples_total,
            "latency_s": {
                "p50": _percentile(latencies, 50),
                "p95": _percentile(latencies, 95),
                "p99": _percentile(latencies, 99),
                "min": latencies[0] if latencies else None,
                "max": latencies[-1] if latencies else None,
            },
            # Aggregates weight by recorded time so they telescope: on
            # async backends individual dispatch latencies are bimodal
            # (dispatch-only vs sync-drain), but their SUM is the loop's
            # wall time, making these ratios exact where a mean of
            # per-step ratios would be dominated by the tiny
            # dispatch-only entries.
            "examples_per_sec_mean": (
                examples_total / latency_weighted_s
                if latency_weighted_s > 0 else None
            ),
            "mfu_mean": (
                sum(m["mfu"] * m["w"] for m in mfus)
                / sum(m["w"] for m in mfus)
                if mfus and sum(m["w"] for m in mfus) > 0 else None
            ),
            "mfu_max": max((m["mfu"] for m in mfus), default=None),
            "final_loss": losses[-1] if losses else None,
        },
        "recompiles_total": recompiles,
        "compile_seconds": compile_seconds,
        "epochs": len(epochs),
        "evals": len(evals),
        "best_test_acc": max(
            (e.get("test_acc") for e in evals
             if isinstance(e.get("test_acc"), (int, float))),
            default=None,
        ),
        "checkpoints": checkpoints,
        "errors": [
            {"ts": e.get("ts"), "type": e.get("error_type"),
             "error": e.get("error")}
            for e in errors
        ],
        "event_counts": kinds,
    }
    if bench_sections:
        summary["bench_events"] = len(bench_sections)
    if infer_runs:
        summary["infer_events"] = len(infer_runs)
    if profile_captures:
        summary["profile_captures"] = [
            {"dir": c.get("dir"), "duration_ms": c.get("duration_ms"),
             "total_bytes": c.get("total_bytes")}
            for c in profile_captures
        ]
    if programs:
        # The run's device story from the events dir alone: join the
        # cost rows with the closing metrics snapshot's per-program
        # dispatch histogram for measured MFU (no live server needed).
        from .flops import NOMINAL_HOST_PEAK, chip_peak_bf16
        from .flops import mfu as _mfu

        kind_str = (
            manifest.get("topology") or {}
        ).get("device_kind") or ""
        peak = chip_peak_bf16(kind_str) or NOMINAL_HOST_PEAK
        hist = (metrics_snapshot or {}).get(
            "program_dispatch_seconds"
        ) or {}
        for series in hist.get("series", []):
            name = (series.get("labels") or {}).get("program")
            if name not in programs:
                continue
            row = programs[name]
            count = int(series.get("count", 0) or 0)
            if count:
                mean_s = float(series.get("sum", 0.0)) / count
                row["dispatches"] = count
                row["mean_dispatch_ms"] = round(mean_s * 1e3, 4)
                m = _mfu(row.get("flops"), mean_s, peak)
                if m is not None:
                    row["mfu"] = m
        summary["programs"] = programs
    heartbeats = read_heartbeats(os.path.dirname(path) or ".")
    if heartbeats:
        summary["heartbeats"] = {
            str(idx): {"ts": hb.get("ts"), "beat": hb.get("beat")}
            for idx, hb in sorted(heartbeats.items())
        }
    return summary


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v * 1e3:.2f} ms" if v < 1.0 else f"{v:.3f} s"


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_table(summary: Dict[str, Any]) -> str:
    """Human-readable run summary (the `telemetry` CLI's default)."""
    run = summary["run"]
    st = summary["steps"]
    lat = st["latency_s"]
    rows = [
        ("model", _fmt(run.get("model"))),
        ("started", _fmt(run.get("started"))),
        ("backend / device", f"{_fmt(run.get('backend'))} / "
                             f"{_fmt(run.get('device_kind'))} "
                             f"x{_fmt(run.get('device_count'))}"),
        ("jax / git", f"{_fmt(run.get('jax_version'))} / "
                      f"{_fmt((run.get('git_rev') or '')[:12] or None)}"),
        ("steps / examples", f"{st['count']} / {st['examples']}"),
        ("step latency p50", _fmt_s(lat["p50"])),
        ("step latency p95", _fmt_s(lat["p95"])),
        ("step latency p99", _fmt_s(lat["p99"])),
        ("examples/sec (mean)", _fmt(st["examples_per_sec_mean"])),
        ("MFU mean / max", f"{_fmt(st['mfu_mean'])} / "
                           f"{_fmt(st['mfu_max'])}"),
        ("final train loss", _fmt(st["final_loss"])),
        ("recompiles total", _fmt(summary.get("recompiles_total"))),
        ("epochs / evals", f"{summary['epochs']} / {summary['evals']}"),
        ("best test acc", _fmt(summary.get("best_test_acc"))),
        ("checkpoints", _fmt(summary.get("checkpoints"))),
        ("errors", str(len(summary.get("errors", [])))),
    ]
    if "heartbeats" in summary:
        beats = ", ".join(
            f"p{idx}@{hb.get('ts')}"
            for idx, hb in summary["heartbeats"].items()
        )
        rows.append(("last heartbeats", beats))
    width = max(len(k) for k, _ in rows)
    lines = [f"telemetry summary: {summary['path']}"]
    lines += [f"  {k.ljust(width)}  {v}" for k, v in rows]
    programs = summary.get("programs")
    if programs:
        # The device story (OBSERVABILITY.md "Device profiling"): one
        # line per compiled program — compiles, cost flops, measured
        # MFU, AOT hit/miss — readable without a live server.
        lines.append("  programs:")
        for name, row in sorted(programs.items()):
            aot = row.get("aot") or {}
            aot_s = (
                f" aot {aot}" if aot else ""
            )
            lines.append(
                f"    {name:<20} compiles {row.get('compiles', 0)}  "
                f"flops {_fmt(row.get('flops'))}  "
                f"mfu {_fmt(row.get('mfu'))}  "
                f"dispatches {_fmt(row.get('dispatches'))}"
                f"{aot_s}"
            )
    for cap in summary.get("profile_captures", []):
        lines.append(
            f"  profile capture: {cap.get('dir')} "
            f"({cap.get('duration_ms')} ms, {cap.get('total_bytes')} B)"
        )
    for err in summary.get("errors", [])[:5]:
        lines.append(
            f"  ! {err.get('ts')} {err.get('type')}: {err.get('error')}"
        )
    return "\n".join(lines)


# -- fleet read side ---------------------------------------------------------


def summarize_fleet(root: str) -> Dict[str, Any]:
    """Fold a fleet telemetry tree — the router's events.jsonl at
    ``root`` plus each replica's under ``root/<rid>/`` — into one
    combined summary. Each log is read through :func:`summarize` (so
    rotated segments are spanned per log); replica subdirectories
    without an event log (e.g. ``staging/``) are skipped. Raises
    FileNotFoundError when the ROUTER log is missing — a fleet dir
    without its control-plane log is the wrong directory."""
    from .telemetry import EVENTS_FILE

    router_log = os.path.join(root, EVENTS_FILE)
    out: Dict[str, Any] = {
        "path": root,
        "router": summarize(router_log),
        "replicas": {},
    }
    for name in sorted(os.listdir(root)):
        sub = os.path.join(root, name, EVENTS_FILE)
        if os.path.isfile(sub):
            out["replicas"][name] = summarize(sub)
    combined: Dict[str, int] = dict(out["router"]["event_counts"])
    errors = len(out["router"].get("errors", []))
    for rep in out["replicas"].values():
        for k, v in rep["event_counts"].items():
            combined[k] = combined.get(k, 0) + v
        errors += len(rep.get("errors", []))
    out["fleet"] = {
        "replica_logs": len(out["replicas"]),
        "event_counts": combined,
        "events_total": sum(combined.values()),
        "decisions": combined.get("decision", 0),
        "slo_alerts": combined.get("slo_alert", 0),
        "errors_total": errors,
    }
    return out


def render_fleet_table(summary: Dict[str, Any]) -> str:
    """Human-readable fleet summary (`cli telemetry --fleet`): one line
    per process log plus the combined rollup."""

    def counts(ec: Dict[str, int]) -> str:
        top = sorted(ec.items(), key=lambda kv: (-kv[1], kv[0]))[:8]
        rest = len(ec) - len(top)
        s = "  ".join(f"{k} x{v}" for k, v in top)
        return s + (f"  (+{rest} kinds)" if rest > 0 else "")

    lines = [f"fleet telemetry: {summary['path']}"]
    names = ["router", "combined"] + sorted(summary["replicas"])
    width = max(len(n) for n in names)
    lines.append(
        f"  {'router'.ljust(width)}  "
        f"{counts(summary['router']['event_counts'])}"
    )
    for name in sorted(summary["replicas"]):
        rep = summary["replicas"][name]
        lines.append(
            f"  {name.ljust(width)}  {counts(rep['event_counts'])}"
        )
    fl = summary["fleet"]
    lines.append(
        f"  {'combined'.ljust(width)}  {fl['events_total']} event(s) "
        f"across {1 + fl['replica_logs']} log(s); "
        f"{fl['decisions']} decision(s), {fl['slo_alerts']} slo "
        f"alert(s), {fl['errors_total']} error(s)"
    )
    for err in summary["router"].get("errors", [])[:5]:
        lines.append(
            f"  ! router {err.get('ts')} {err.get('type')}: "
            f"{err.get('error')}"
        )
    return "\n".join(lines)


def decision_timeline(events) -> List[Dict[str, Any]]:
    """The control-plane audit trail: every ``decision`` event (router
    ejections/readmits/breaker transitions, supervisor scale/hold/
    respawn/retire, rollout gate verdicts, operator overrides) joined
    against the ``slo_alert`` open/close transitions, in log order.
    Accepts raw event dicts (from ``read_events`` or the in-memory
    fleet-harness capture)."""
    rows: List[Dict[str, Any]] = []
    for ev in events:
        kind = ev.get("kind")
        if kind == "decision":
            rows.append({
                "ts": ev.get("ts"),
                "actor": ev.get("actor") or "?",
                "action": ev.get("action") or "?",
                "replica": ev.get("replica"),
                "inputs": dict(ev.get("inputs") or {}),
            })
        elif kind == "slo_alert":
            rows.append({
                "ts": ev.get("ts"),
                "actor": "slo",
                "action": f"{ev.get('state', '?')} {ev.get('slo', '?')}",
                "replica": None,
                "inputs": {
                    k: ev[k] for k in (
                        "burn_fast", "burn_slow", "events_fast",
                        "budget_remaining", "severity",
                    ) if ev.get(k) is not None
                },
            })
    return rows


def render_decision_timeline(
    rows: List[Dict[str, Any]], *, title: Optional[str] = None,
) -> str:
    """The `cli fleet explain` rendering: one line per decision, its
    inputs inline, so "why did the fleet do that" reads top to
    bottom."""
    lines = [title or f"fleet decision timeline ({len(rows)} entries)"]
    if not rows:
        lines.append(
            "  (no decision/slo_alert events — pre-observability log, "
            "or nothing happened)"
        )
        return "\n".join(lines)
    for r in rows:
        ts = r.get("ts") or ""
        if isinstance(ts, str) and "T" in ts:
            ts = ts.split("T", 1)[1].rstrip("Z")[:12]
        who = f"[{r['actor']}]"
        target = f" {r['replica']}" if r.get("replica") else ""
        inputs = "  ".join(
            f"{k}={_fmt(v)}" for k, v in r["inputs"].items()
        )
        lines.append(
            f"  {str(ts):<13} {who:<12} {r['action']}{target}"
            + (f"  {inputs}" if inputs else "")
        )
    return "\n".join(lines)
