"""Structured JSONL run events — one append-only file per run.

Every record is one JSON object per line with three envelope fields:
``v`` (schema version), ``kind`` (event type) and ``ts`` (UTC ISO-8601).
The first record of a run is the ``run_manifest`` — config, device/mesh
topology, jax version and git rev — so a log file is self-describing:
any later reader knows exactly what produced the numbers that follow.

Event kinds (schema v1) form a closed registry: ``EVENT_KINDS`` below
is the single source of truth — one entry per kind with a one-line
description. OBSERVABILITY.md's event table mirrors it row for row
(``scripts/check_event_docs.py`` fails CI on drift), and the linter's
event-schema contract rules enforce call sites against it: JG017 flags
an ``emit()`` with a kind literal missing from the registry, JG018
flags payload keys that would collide with the envelope fields
(``ENVELOPE_FIELDS``) — the bug class that shipped twice (the PR 4
``reload`` payload and the PR 6 ``cli export`` payload both carried a
``kind`` key that silently clobbered the envelope's, now nested).

Writes happen only on the primary host (process_index 0) unless
``primary_only=False`` — the multi-host analogue of the reference's
``if rank == 0`` print guards. Heartbeats intentionally bypass that rule
(every process writes its own file) so a stalled non-primary host is
diagnosable after the fact.

Rotation: long-lived servers grow span/request-heavy logs without
bound, so ``EventLog(max_bytes=...)`` rotates in place — the live file
is renamed to ``events.jsonl.<seq>`` (ascending = older) and reopened
fresh, keeping the newest ``keep_segments`` segments (the heartbeat
history's bound-the-file discipline, segment-shaped because readers
must still see one continuous stream). ``read_events`` — and therefore
``cli trace`` / ``cli telemetry`` / ``summarize`` — reads across the
surviving segments in order; rotations are counted by the owner (the
``events_rotated_total`` counter Telemetry wires up).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from ..utils.logging_utils import is_primary_host

SCHEMA_VERSION = 1
MANIFEST_KIND = "run_manifest"

#: Envelope fields every record carries; a payload key with one of these
#: names would silently clobber the envelope (the shipped PR 4 / PR 6
#: collision bug) — JG018 flags such call sites statically.
ENVELOPE_FIELDS = ("v", "kind", "ts")

#: The canonical kind registry (schema v1): every event kind any writer
#: emits, with a one-line description. Kept as a plain dict literal so
#: the linter (analysis/lint, JG017) and scripts/check_event_docs.py can
#: read it with ``ast.literal_eval`` — no jax, no package import.
#: OBSERVABILITY.md's event table mirrors this registry row for row.
EVENT_KINDS: Dict[str, str] = {
    "run_manifest": "config, devices, mesh, versions, git rev (once)",
    "step": "step index, latency, examples/sec, mfu, loss/acc",
    "epoch": "per-epoch aggregates + device memory stats",
    "eval": "test metrics",
    "checkpoint": "epoch, path, best flag",
    "bench": "a bench.py section result (same envelope as training)",
    "infer": "packed-serving run summary",
    "error": "exception type/message before a crash propagates",
    "heartbeat": "liveness records (written per process, obs/heartbeat)",
    "fault_injected": "a resilience/chaos fault fired (kind, point, step)",
    "graceful_stop": "preemption honored at a step boundary",
    "resume": "a run restored checkpoint state before training",
    "rollback": "restore skipped corrupt generation(s) (resilience)",
    "restart": "the retry loop rebuilt the trainer (cause, attempt)",
    "membership_change": "elastic data-parallel membership change",
    "remesh": "elastic mesh rebuild + state re-placement at a new world",
    "comm_compress": "the run's 1-bit gradient-exchange plan (PERF.md)",
    "metrics": "final registry snapshot at run close, before run_end",
    "run_end": "run outcome summary — the log's closing record",
    "sanitizer_trip": "a runtime fence (recompile/transfer/nan) fired",
    "request": "one served prediction request's final status (serve/)",
    "shed": "admission rejected a request (serve/)",
    "breaker_open": "the serving circuit breaker tripped open",
    "breaker_close": "it closed again after half-open probes",
    "drain": "SIGTERM graceful drain completed (serve/)",
    "reload": "hot artifact swap on the running server (serve/)",
    "export": "cli export wrote a packed artifact (path, size info)",
    "lm_admit": "a generation request took a batch slot (serve/lm/)",
    "lm_evict": "a generation request left its slot or died queued",
    "lm_decode": "periodic decode-iteration snapshot (serve/lm/)",
    "lm_decode_error": "a decode dispatch failed and was retried",
    "lm_prefix_hit": "admission forked a cached prompt prefix COW",
    "lm_spec_round": "periodic speculative-decode round snapshot",
    "lm_warmup": "the LM engine finished warmup (programs, kernels)",
    "aot_hit": "a boot installed a stored AOT executable (no compile)",
    "aot_miss": "AOT store had no entry; online compile + re-bank",
    "aot_bank": "an executable was serialized into the AOT store",
    "aot_fallback": "corrupt/incompatible AOT entry quarantined",
    "span": "one completed tracing span (obs/trace, `cli trace`)",
    "program_cost": "one compiled program's HLO cost row (obs/costs)",
    "profile_capture": "an on-demand jax.profiler capture completed",
    "fleet_dispatch": "router routed (or failed) one fleet request",
    "replica_health": "a replica health probe changed state (fleet)",
    "replica_spawn": "the supervisor started a replica process",
    "replica_exit": "a replica process exited (cause, respawn plan)",
    "autoscale": "the supervisor changed the replica target (fleet)",
    "rollout": "one rolling-deploy phase (ship/start/trip/...)",
    "decision": "one control-plane decision with its inputs (fleet)",
    "slo_alert": "a multiwindow burn-rate alert transitioned (obs/slo)",
    "multihost_init": "cluster bootstrap outcome (attempts, classified)",
    "host_membership": "host-level elastic membership change (multihost)",
}


def utc_now(epoch_s: Optional[float] = None) -> str:
    return time.strftime(
        "%Y-%m-%dT%H:%M:%SZ",
        time.gmtime(epoch_s) if epoch_s is not None else time.gmtime(),
    )


def git_rev(cwd: Optional[str] = None) -> Optional[str]:
    """Current git commit of the source tree, or None outside a repo."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except (OSError, subprocess.SubprocessError):
        return None


def _jsonable(value: Any) -> Any:
    """Best-effort conversion to JSON-serializable builtins (numpy/jax
    scalars -> float/int, arrays -> lists only when tiny, else shape)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
        return value.item()
    if hasattr(value, "shape"):
        return {"shape": list(value.shape), "dtype": str(value.dtype)}
    return str(value)


def device_topology() -> Dict[str, Any]:
    """Device/process topology as manifest data. Tolerates an
    uninitialized jax (pure-host tooling reading logs)."""
    try:
        import jax

        devices = jax.devices()
        return {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "device_kind": devices[0].device_kind if devices else None,
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
    except (ImportError, RuntimeError):
        return {"backend": None, "device_count": 0}


class EventLog:
    """Append-only JSONL sink for one run.

    ``emit`` is a no-op on non-primary hosts (see module docstring), so
    call sites need no rank guards. Flush policy: the high-rate kinds —
    ``step`` (one per hot-loop dispatch), ``request`` (one per served
    request, written from the serving engine's single worker thread)
    ``lm_admit``/``lm_evict`` (one per generation stream, written
    from the LM scheduler thread between decode iterations) and
    ``span`` (several per traced request, batch-flushed by the tracer's
    own staging buffer first) — are
    buffered (a flushed syscall per record would serialize file I/O
    against the hot path) and flushed every ``flush_every`` records;
    every other kind — manifest, epoch, error, shed, breaker
    transitions, drain, run_end — flushes immediately, so a crashed run
    loses at most the last few high-rate lines, never the milestone
    records."""

    BUFFERED_KINDS = ("step", "request", "lm_admit", "lm_evict",
                      "lm_prefix_hit", "span")

    def __init__(
        self, path: str, *, primary_only: bool = True,
        flush_every: int = 32,
        max_bytes: Optional[int] = None,
        keep_segments: int = 4,
    ):
        self.path = path
        self._active = is_primary_host() or not primary_only
        self._fh = None
        self._manifest_written = False
        self._flush_every = max(int(flush_every), 1)
        self._unflushed = 0
        # Size-based rotation (module docstring): None = unbounded
        # (training runs are epoch-bounded; only long-lived servers
        # need the cap). Rotation happens on flush boundaries only, so
        # a segment can overshoot by at most one flush batch.
        self._max_bytes = int(max_bytes) if max_bytes else None
        self._keep_segments = max(int(keep_segments), 1)
        self.rotations = 0             # guarded-by: _lock
        self.on_rotate = None          # owner's counter hook
        self._size = 0                 # guarded-by: _lock
        self._manifest_record = None   # re-emitted into fresh segments
        # One log is written from many threads (trainer + heartbeat +
        # async checkpointer; the serving engine worker + HTTP handler
        # threads + drain): TextIOWrapper writes are not thread-safe,
        # and an interleaved partial line silently vanishes in
        # read_events.
        self._lock = threading.Lock()
        if self._active:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")
            try:
                self._size = os.path.getsize(path)
            except OSError:
                self._size = 0

    @property
    def active(self) -> bool:
        return self._active

    def emit(self, kind: str, **fields: Any) -> None:
        # jg: disable=JG007 -- lock-free fast path; the None check is re-done under the lock below, this read only skips the json encode and never acts on the handle
        if not self._active or self._fh is None:
            return
        record = {"v": SCHEMA_VERSION, "kind": kind, "ts": utc_now()}
        record.update({k: _jsonable(v) for k, v in fields.items()})
        if kind == MANIFEST_KIND and not record.get("rotated_copy"):
            # Keep the run-scoping record survivable: rotation prunes
            # old segments, so each fresh segment re-opens with a
            # marked copy of the manifest (see _rotate_locked).
            self._manifest_record = record
        line = json.dumps(record) + "\n"
        rotated = False
        with self._lock:
            if self._fh is None:  # closed concurrently
                return
            # jg: disable=JG009 -- serializing THIS write is the lock's whole job (interleaved TextIOWrapper writes mangle lines); the json encode already ran outside it
            self._fh.write(line)
            self._size += len(line)
            self._unflushed += 1
            if (kind not in self.BUFFERED_KINDS
                    or self._unflushed >= self._flush_every):
                # jg: disable=JG009 -- same critical section: the flush must pair with the write it flushes; the buffered-kind policy bounds how often hot paths hit it
                self._fh.flush()
                self._unflushed = 0
                if (self._max_bytes is not None
                        and self._size >= self._max_bytes):
                    # jg: disable=JG009 -- rotation must swap the handle every writer is serialized on; it runs only when a flushed segment crossed max_bytes, never on the per-record path
                    self._rotate_locked()
                    rotated = True
        if rotated and self.on_rotate is not None:
            try:
                self.on_rotate()
            # jg: disable=JG005 -- a rotation-counter hook must never fail the write that triggered it
            except Exception:
                pass

    def _rotate_locked(self) -> None:  # holds-lock: _lock
        """Rename the live file to the next ``.<seq>`` segment, prune
        segments beyond ``keep_segments``, reopen fresh. Caller holds
        ``_lock`` (the handle swap must be atomic w.r.t. writers)."""
        self._fh.close()
        self._fh = None
        seqs = [s for _, s in _segments(self.path)]
        nxt = (max(seqs) + 1) if seqs else 1
        try:
            os.replace(self.path, f"{self.path}.{nxt}")
        except OSError:
            pass  # rename raced an external mover: just reopen
        for seg_path, seq in _segments(self.path):
            if seq <= nxt - self._keep_segments:
                try:
                    os.remove(seg_path)
                except OSError:
                    pass
        # jg: disable=JG009 -- the reopen must happen under the same lock every writer serializes on (a writer observing _fh=None mid-rotation would drop its record); rotation is a rare flush-boundary event, not the per-record path
        self._fh = open(self.path, "a")
        self._size = 0
        if self._manifest_record is not None:
            # The run-scoping record must survive segment pruning:
            # every fresh segment opens with a MARKED manifest copy
            # (readers use it as data only — ``rotated_copy`` keeps it
            # from re-scoping the run in summarize()).
            line = json.dumps(
                {**self._manifest_record, "rotated_copy": True}
            ) + "\n"
            # jg: disable=JG009 -- same critical section as the reopen above: the copy must land before any writer's next record, and rotation only runs at rare flush boundaries
            self._fh.write(line)
            self._size = len(line)
        self.rotations += 1

    def manifest(
        self, config: Optional[Dict[str, Any]] = None,
        mesh: Any = None, **extra: Any,
    ) -> None:
        """Emit the run manifest (once; later calls are ignored so
        resume/retry paths can call unconditionally)."""
        if self._manifest_written:
            return
        self._manifest_written = True
        mesh_info = None
        if mesh is not None:
            try:
                mesh_info = {
                    "axis_names": list(mesh.axis_names),
                    "shape": {
                        str(k): int(v) for k, v in dict(mesh.shape).items()
                    },
                }
            except (AttributeError, TypeError, ValueError):
                mesh_info = str(mesh)
        try:
            import jax

            jax_version = jax.__version__
        except ImportError:
            jax_version = None
        self.emit(
            MANIFEST_KIND,
            config=config or {},
            topology=device_topology(),
            mesh=mesh_info,
            jax_version=jax_version,
            python_version=sys.version.split()[0],
            hostname=socket.gethostname(),
            pid=os.getpid(),
            git_rev=git_rev(),
            argv=list(sys.argv),
            **extra,
        )

    def error(self, exc: BaseException, **fields: Any) -> None:
        self.emit(
            "error",
            error_type=type(exc).__name__,
            error=str(exc)[:2000],
            **fields,
        )

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _segments(path: str) -> List[tuple]:
    """Rotated segments of ``path`` as ascending ``(seg_path, seq)``
    pairs (``events.jsonl.1`` is older than ``.2``; the live file is
    not included)."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path) + "."
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not name.startswith(base):
            continue
        suffix = name[len(base):]
        if suffix.isdigit():
            out.append((os.path.join(d, name), int(suffix)))
    out.sort(key=lambda t: t[1])
    return out


def read_events(path: str) -> Iterator[Dict[str, Any]]:
    """Stream a JSONL event log — rotated segments first (oldest to
    newest), then the live file, so readers (`cli trace`/`telemetry`,
    ``summarize``) see one continuous stream across rotation.
    Malformed lines (a crash mid-write) are skipped rather than
    poisoning the whole read."""
    paths = [p for p, _ in _segments(path)] + [path]
    for p in paths:
        try:
            f = open(p)
        except OSError:
            if p == path and not _segments(path):
                raise  # no log at all: keep the historical contract
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue


def load_events(path: str) -> List[Dict[str, Any]]:
    return list(read_events(path))
