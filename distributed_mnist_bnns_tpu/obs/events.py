"""Structured JSONL run events — one append-only file per run.

Every record is one JSON object per line with three envelope fields:
``v`` (schema version), ``kind`` (event type) and ``ts`` (UTC ISO-8601).
The first record of a run is the ``run_manifest`` — config, device/mesh
topology, jax version and git rev — so a log file is self-describing:
any later reader knows exactly what produced the numbers that follow.

Event kinds (schema v1):
  run_manifest   config, devices, mesh, versions, git rev  (exactly once)
  step           step index, latency, examples/sec, mfu, loss/acc
  epoch          per-epoch aggregates + device memory stats
  eval           test metrics
  checkpoint     epoch, path, best flag
  bench          a bench.py section result (same envelope as training)
  infer          packed-serving run summary
  error          exception type/message before a crash propagates
  heartbeat      liveness records (written per process by obs/heartbeat)
  fault_injected a resilience/chaos fault fired (kind, point, step/epoch)
  graceful_stop  preemption honored at a step boundary (mid-epoch
                 checkpoint state, reason)
  resume         a run restored checkpoint state before training
                 (epoch/step/data position, digest_verified flag)
  rollback       restore skipped corrupt generation(s) (resilience)
  restart        the retry loop rebuilt the trainer (cause, attempt,
                 backoff, world_size/mesh_shape — resilience/policy)
  membership_change  the elastic supervisor noted a data-parallel
                 membership change (event=lost|restored,
                 world_from/world_to, step — resilience/elastic)
  remesh         the elastic loop rebuilt the mesh at a new world and
                 re-placed state from the newest verified checkpoint
                 generation (direction=shrink|grow, world_from/
                 world_to, event, step — resilience/elastic)
  comm_compress  the run's 1-bit gradient-exchange plan (mode, layout=
                 dp|fsdp, buckets, per-phase rs/ag wire bytes/step vs
                 fp32 — PERF.md)
  metrics        final registry snapshot (counters/gauges/histograms)
                 emitted once at run close, just before run_end
  request        one served prediction request's final status (serve/)
  shed           admission rejected a request (queue_full |
                 breaker_open | draining — serve/)
  breaker_open   the serving circuit breaker tripped open
  breaker_close  it closed again after successful half-open probes
  drain          SIGTERM graceful drain completed (flush stats, serve/)
  reload         hot artifact swap on the running server (serve/)
  export         cli export wrote a packed artifact (path, size info)
  lm_admit       a generation request took a batch slot (serve/lm/ —
                 prompt/pages/prefill stats, the iteration it joined at)
  lm_evict       a generation request left its slot or died queued
                 (status, tokens emitted, pages freed)
  lm_decode      periodic decode-iteration snapshot (active streams,
                 iteration latency, page occupancy, recompile count)
  lm_decode_error a decode dispatch failed and was retried (serve/lm/)
  lm_prefix_hit  admission found a cached prompt prefix: forked its
                 pages COW and prefilled only the suffix (serve/lm/,
                 SERVING.md "Prefix caching")
  lm_spec_round  periodic speculative-decode round snapshot (spec_k,
                 drafts accepted/rejected, cumulative acceptance rate)
  aot_hit        a boot installed a stored AOT executable — no trace,
                 no compile (aot/, PERF.md "Cold start")
  aot_miss       the AOT store had no entry; normal compile + re-bank
  aot_bank       an executable was serialized into the AOT store
  aot_fallback   a corrupt/incompatible AOT entry was quarantined and
                 the boot fell back to online compile (reason field)
  span           one completed tracing span (obs/trace): trace/span/
                 parent ids, name, span_kind, monotonic t0_ms/dur_ms,
                 status, tid, attrs — the per-request span trees
                 `cli trace` folds into Perfetto exports and tail
                 attribution (OBSERVABILITY.md "Tracing")
  program_cost   one compiled program's HLO cost row (obs/costs):
                 flops, bytes accessed, argument/output/temp/peak HBM,
                 source=online|aot_hit|aot_miss — the per-program cost
                 ledger behind measured MFU (OBSERVABILITY.md "Device
                 profiling")
  profile_capture  an on-demand jax.profiler capture completed
                 (obs/profile): artifact dir, file count, total bytes,
                 wall duration — /admin/profile and `cli train
                 --profile-steps` both emit it
  decision       one control-plane decision with the inputs that drove
                 it (serve/fleet/): actor=router|supervisor|rollout|
                 operator, action (scale_up/hold/eject/readmit/
                 breaker_open/gate_trip/rollback/...), optional replica
                 id, and an ``inputs`` dict (queue depth, shed/error
                 rates, thresholds, cooldown state) — the audit trail
                 `cli fleet explain DIR` renders as a timeline
  slo_alert      a multiwindow burn-rate alert transitioned (obs/slo):
                 slo name, state=open|close, signal, objective,
                 burn_fast/burn_slow, window sizes, events_fast,
                 budget_remaining, severity — joined into the decision
                 timeline (OBSERVABILITY.md "Fleet observability")

Writes happen only on the primary host (process_index 0) unless
``primary_only=False`` — the multi-host analogue of the reference's
``if rank == 0`` print guards. Heartbeats intentionally bypass that rule
(every process writes its own file) so a stalled non-primary host is
diagnosable after the fact.

Rotation: long-lived servers grow span/request-heavy logs without
bound, so ``EventLog(max_bytes=...)`` rotates in place — the live file
is renamed to ``events.jsonl.<seq>`` (ascending = older) and reopened
fresh, keeping the newest ``keep_segments`` segments (the heartbeat
history's bound-the-file discipline, segment-shaped because readers
must still see one continuous stream). ``read_events`` — and therefore
``cli trace`` / ``cli telemetry`` / ``summarize`` — reads across the
surviving segments in order; rotations are counted by the owner (the
``events_rotated_total`` counter Telemetry wires up).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from ..utils.logging_utils import is_primary_host

SCHEMA_VERSION = 1
MANIFEST_KIND = "run_manifest"


def utc_now(epoch_s: Optional[float] = None) -> str:
    return time.strftime(
        "%Y-%m-%dT%H:%M:%SZ",
        time.gmtime(epoch_s) if epoch_s is not None else time.gmtime(),
    )


def git_rev(cwd: Optional[str] = None) -> Optional[str]:
    """Current git commit of the source tree, or None outside a repo."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except (OSError, subprocess.SubprocessError):
        return None


def _jsonable(value: Any) -> Any:
    """Best-effort conversion to JSON-serializable builtins (numpy/jax
    scalars -> float/int, arrays -> lists only when tiny, else shape)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if hasattr(value, "item") and getattr(value, "ndim", None) == 0:
        return value.item()
    if hasattr(value, "shape"):
        return {"shape": list(value.shape), "dtype": str(value.dtype)}
    return str(value)


def device_topology() -> Dict[str, Any]:
    """Device/process topology as manifest data. Tolerates an
    uninitialized jax (pure-host tooling reading logs)."""
    try:
        import jax

        devices = jax.devices()
        return {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "device_kind": devices[0].device_kind if devices else None,
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
    except (ImportError, RuntimeError):
        return {"backend": None, "device_count": 0}


class EventLog:
    """Append-only JSONL sink for one run.

    ``emit`` is a no-op on non-primary hosts (see module docstring), so
    call sites need no rank guards. Flush policy: the high-rate kinds —
    ``step`` (one per hot-loop dispatch), ``request`` (one per served
    request, written from the serving engine's single worker thread)
    ``lm_admit``/``lm_evict`` (one per generation stream, written
    from the LM scheduler thread between decode iterations) and
    ``span`` (several per traced request, batch-flushed by the tracer's
    own staging buffer first) — are
    buffered (a flushed syscall per record would serialize file I/O
    against the hot path) and flushed every ``flush_every`` records;
    every other kind — manifest, epoch, error, shed, breaker
    transitions, drain, run_end — flushes immediately, so a crashed run
    loses at most the last few high-rate lines, never the milestone
    records."""

    BUFFERED_KINDS = ("step", "request", "lm_admit", "lm_evict",
                      "lm_prefix_hit", "span")

    def __init__(
        self, path: str, *, primary_only: bool = True,
        flush_every: int = 32,
        max_bytes: Optional[int] = None,
        keep_segments: int = 4,
    ):
        self.path = path
        self._active = is_primary_host() or not primary_only
        self._fh = None
        self._manifest_written = False
        self._flush_every = max(int(flush_every), 1)
        self._unflushed = 0
        # Size-based rotation (module docstring): None = unbounded
        # (training runs are epoch-bounded; only long-lived servers
        # need the cap). Rotation happens on flush boundaries only, so
        # a segment can overshoot by at most one flush batch.
        self._max_bytes = int(max_bytes) if max_bytes else None
        self._keep_segments = max(int(keep_segments), 1)
        self.rotations = 0             # guarded-by: _lock
        self.on_rotate = None          # owner's counter hook
        self._size = 0                 # guarded-by: _lock
        self._manifest_record = None   # re-emitted into fresh segments
        # One log is written from many threads (trainer + heartbeat +
        # async checkpointer; the serving engine worker + HTTP handler
        # threads + drain): TextIOWrapper writes are not thread-safe,
        # and an interleaved partial line silently vanishes in
        # read_events.
        self._lock = threading.Lock()
        if self._active:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")
            try:
                self._size = os.path.getsize(path)
            except OSError:
                self._size = 0

    @property
    def active(self) -> bool:
        return self._active

    def emit(self, kind: str, **fields: Any) -> None:
        # jg: disable=JG007 -- lock-free fast path; the None check is re-done under the lock below, this read only skips the json encode and never acts on the handle
        if not self._active or self._fh is None:
            return
        record = {"v": SCHEMA_VERSION, "kind": kind, "ts": utc_now()}
        record.update({k: _jsonable(v) for k, v in fields.items()})
        if kind == MANIFEST_KIND and not record.get("rotated_copy"):
            # Keep the run-scoping record survivable: rotation prunes
            # old segments, so each fresh segment re-opens with a
            # marked copy of the manifest (see _rotate_locked).
            self._manifest_record = record
        line = json.dumps(record) + "\n"
        rotated = False
        with self._lock:
            if self._fh is None:  # closed concurrently
                return
            # jg: disable=JG009 -- serializing THIS write is the lock's whole job (interleaved TextIOWrapper writes mangle lines); the json encode already ran outside it
            self._fh.write(line)
            self._size += len(line)
            self._unflushed += 1
            if (kind not in self.BUFFERED_KINDS
                    or self._unflushed >= self._flush_every):
                # jg: disable=JG009 -- same critical section: the flush must pair with the write it flushes; the buffered-kind policy bounds how often hot paths hit it
                self._fh.flush()
                self._unflushed = 0
                if (self._max_bytes is not None
                        and self._size >= self._max_bytes):
                    # jg: disable=JG009 -- rotation must swap the handle every writer is serialized on; it runs only when a flushed segment crossed max_bytes, never on the per-record path
                    self._rotate_locked()
                    rotated = True
        if rotated and self.on_rotate is not None:
            try:
                self.on_rotate()
            # jg: disable=JG005 -- a rotation-counter hook must never fail the write that triggered it
            except Exception:
                pass

    def _rotate_locked(self) -> None:  # holds-lock: _lock
        """Rename the live file to the next ``.<seq>`` segment, prune
        segments beyond ``keep_segments``, reopen fresh. Caller holds
        ``_lock`` (the handle swap must be atomic w.r.t. writers)."""
        self._fh.close()
        self._fh = None
        seqs = [s for _, s in _segments(self.path)]
        nxt = (max(seqs) + 1) if seqs else 1
        try:
            os.replace(self.path, f"{self.path}.{nxt}")
        except OSError:
            pass  # rename raced an external mover: just reopen
        for seg_path, seq in _segments(self.path):
            if seq <= nxt - self._keep_segments:
                try:
                    os.remove(seg_path)
                except OSError:
                    pass
        # jg: disable=JG009 -- the reopen must happen under the same lock every writer serializes on (a writer observing _fh=None mid-rotation would drop its record); rotation is a rare flush-boundary event, not the per-record path
        self._fh = open(self.path, "a")
        self._size = 0
        if self._manifest_record is not None:
            # The run-scoping record must survive segment pruning:
            # every fresh segment opens with a MARKED manifest copy
            # (readers use it as data only — ``rotated_copy`` keeps it
            # from re-scoping the run in summarize()).
            line = json.dumps(
                {**self._manifest_record, "rotated_copy": True}
            ) + "\n"
            # jg: disable=JG009 -- same critical section as the reopen above: the copy must land before any writer's next record, and rotation only runs at rare flush boundaries
            self._fh.write(line)
            self._size = len(line)
        self.rotations += 1

    def manifest(
        self, config: Optional[Dict[str, Any]] = None,
        mesh: Any = None, **extra: Any,
    ) -> None:
        """Emit the run manifest (once; later calls are ignored so
        resume/retry paths can call unconditionally)."""
        if self._manifest_written:
            return
        self._manifest_written = True
        mesh_info = None
        if mesh is not None:
            try:
                mesh_info = {
                    "axis_names": list(mesh.axis_names),
                    "shape": {
                        str(k): int(v) for k, v in dict(mesh.shape).items()
                    },
                }
            except (AttributeError, TypeError, ValueError):
                mesh_info = str(mesh)
        try:
            import jax

            jax_version = jax.__version__
        except ImportError:
            jax_version = None
        self.emit(
            MANIFEST_KIND,
            config=config or {},
            topology=device_topology(),
            mesh=mesh_info,
            jax_version=jax_version,
            python_version=sys.version.split()[0],
            hostname=socket.gethostname(),
            pid=os.getpid(),
            git_rev=git_rev(),
            argv=list(sys.argv),
            **extra,
        )

    def error(self, exc: BaseException, **fields: Any) -> None:
        self.emit(
            "error",
            error_type=type(exc).__name__,
            error=str(exc)[:2000],
            **fields,
        )

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _segments(path: str) -> List[tuple]:
    """Rotated segments of ``path`` as ascending ``(seg_path, seq)``
    pairs (``events.jsonl.1`` is older than ``.2``; the live file is
    not included)."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path) + "."
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not name.startswith(base):
            continue
        suffix = name[len(base):]
        if suffix.isdigit():
            out.append((os.path.join(d, name), int(suffix)))
    out.sort(key=lambda t: t[1])
    return out


def read_events(path: str) -> Iterator[Dict[str, Any]]:
    """Stream a JSONL event log — rotated segments first (oldest to
    newest), then the live file, so readers (`cli trace`/`telemetry`,
    ``summarize``) see one continuous stream across rotation.
    Malformed lines (a crash mid-write) are skipped rather than
    poisoning the whole read."""
    paths = [p for p, _ in _segments(path)] + [path]
    for p in paths:
        try:
            f = open(p)
        except OSError:
            if p == path and not _segments(path):
                raise  # no log at all: keep the historical contract
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue


def load_events(path: str) -> List[Dict[str, Any]]:
    return list(read_events(path))
