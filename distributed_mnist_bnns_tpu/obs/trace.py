"""Per-request distributed tracing — span trees over the event log.

The obs/ layer so far answers "how fast is a step" (histograms,
percentiles); this module answers "where did THIS request's 600 ms go".
A :class:`Span` is one named monotonic-clock interval with structured
attributes; spans form trees via ``trace_id``/``span_id``/``parent``
links, and completed spans are written into the run's existing
``events.jsonl`` as ``span`` events — one schema, one file, one reader
(`cli trace` renders Chrome-trace JSON for Perfetto and a p99
tail-attribution report from the same log the request events live in).

Design constraints (all load-bearing for the serving hot path):

  * **near-zero cost when disabled** — every entry point starts with one
    attribute check and returns a shared no-op span; nothing allocates;
  * **thread-safe** — spans start on HTTP handler threads, end on the
    engine worker / LM scheduler thread, and race waiter-vs-engine at
    deadlines; ``Span.end`` is claim-once (first caller wins), mirroring
    ``Request.finish``;
  * **bounded buffer, explicit drops** — completed spans stage in a
    bounded in-memory buffer and flush to the sink in batches; a full
    buffer DROPS (counted in ``trace_spans_dropped_total`` and
    ``Tracer.dropped``) rather than growing without bound;
  * **no span I/O under held locks** (the JG009 discipline): the buffer
    lock guards only list ops; all sink writes happen after release.

Trace context propagates across processes via the ``x-jg-trace`` HTTP
header — ``<trace_id>-<span_id>``, both lowercase hex. **Clients mint
it, servers adopt it**: a server that receives the header roots its
request span under the client's span (same trace id), so a future
multi-replica router inherits cross-process causality for free; a
malformed or absent header falls back to a fresh trace, never an error.

Request ids: :func:`next_request_id` is the run-scoped id source both
serving engines share — an ``<8-hex run nonce>-<monotonic counter>``
string, so ids cannot collide across replicas nor repeat across
restarts (a bare process-local ``itertools.count()`` did both, which
breaks joining ``request``/``lm_evict`` events to their span trees in a
multi-replica log merge).

See OBSERVABILITY.md "Tracing" for the span event schema and the
`cli trace` usage.
"""

from __future__ import annotations

import itertools
import re
import secrets
import threading
import time
from typing import Any, Dict, Iterable, List, NamedTuple, Optional

from .events import read_events

TRACE_HEADER = "x-jg-trace"
SPANS_DROPPED_TOTAL = "trace_spans_dropped_total"

_HEADER_RE = re.compile(r"^([0-9a-f]{8,32})-([0-9a-f]{8,32})$")


def _tid() -> int:
    """OS thread id where available (small, matches what a profiler
    shows); the Python ident is the fallback."""
    try:
        return threading.get_native_id()
    except AttributeError:  # pragma: no cover
        return threading.get_ident()


class TraceContext(NamedTuple):
    """The propagatable half of a span: what a client puts on the wire
    and a server adopts."""

    trace_id: str
    span_id: str


def mint_context() -> TraceContext:
    """A fresh (trace, span) pair — what a client mints before its
    first outbound request."""
    return TraceContext(secrets.token_hex(8), secrets.token_hex(8))


def format_header(ctx: TraceContext) -> str:
    return f"{ctx.trace_id}-{ctx.span_id}"


def parse_header(value: Optional[str]) -> Optional[TraceContext]:
    """Parse an ``x-jg-trace`` header; None for absent/malformed input —
    a bad trace header must degrade to an untraced-by-the-client
    request, never a 400 (tracing is observability, not validation)."""
    if not value:
        return None
    m = _HEADER_RE.match(value.strip().lower())
    if not m:
        return None
    return TraceContext(m.group(1), m.group(2))


# -- run-scoped request ids --------------------------------------------------


class RequestIdSource:
    """Run-nonce-prefixed monotonic request ids (``"3fa9c1d2-17"``).

    ``itertools.count.__next__`` is atomic under the GIL, so one source
    serves every handler thread without a lock."""

    def __init__(self, nonce: Optional[str] = None):
        self.nonce = nonce or secrets.token_hex(4)
        self._counter = itertools.count()

    def next(self) -> str:
        return f"{self.nonce}-{next(self._counter)}"


_default_ids = RequestIdSource()


def next_request_id() -> str:
    """The process-wide id source both serving engines draw from."""
    return _default_ids.next()


# -- spans -------------------------------------------------------------------


class _NullSpan:
    """Shared no-op span: the disabled-tracing fast path. Supports the
    full Span surface so call sites need no ``if enabled`` guards."""

    __slots__ = ()
    trace_id = ""
    span_id = ""

    @property
    def context(self) -> Optional[TraceContext]:
        return None

    def end(self, status: str = "ok", **attrs: Any) -> bool:
        return False

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


NULL_SPAN = _NullSpan()


class Span:
    """One live interval. Created by :meth:`Tracer.start`; ``end`` is
    claim-once (the waiter-vs-engine deadline race calls it from both
    sides — exactly one record is written). Usable as a context manager:
    ``with tracer.start(...):`` additionally makes the span the
    thread-local *current* span, so nested spans (and chaos fault
    points) parent to it automatically."""

    __slots__ = (
        "tracer", "name", "span_kind", "trace_id", "span_id",
        "parent_id", "t0", "tid", "attrs", "_lock", "_ended", "_entered",
    )

    def __init__(
        self, tracer: "Tracer", name: str, span_kind: str,
        trace_id: str, parent_id: Optional[str],
        attrs: Dict[str, Any],
    ):
        self.tracer = tracer
        self.name = name
        self.span_kind = span_kind
        self.trace_id = trace_id
        self.span_id = secrets.token_hex(8)
        self.parent_id = parent_id
        self.t0 = time.monotonic()
        self.tid = _tid()
        self.attrs = attrs
        self._lock = threading.Lock()
        self._ended = False
        self._entered = False

    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def end(self, status: str = "ok", **attrs: Any) -> bool:
        """Close the span; the first caller wins and returns True. The
        record is built and enqueued AFTER the claim lock is released —
        no I/O, no allocation of consequence inside the critical
        section."""
        with self._lock:
            if self._ended:
                return False
            self._ended = True
        t1 = time.monotonic()
        if attrs:
            self.attrs = {**self.attrs, **attrs}
        self.tracer._enqueue(_record(
            self.trace_id, self.span_id, self.parent_id, self.name,
            self.span_kind, self.t0, t1, status, self.tid, self.attrs,
        ))
        return True

    def __enter__(self) -> "Span":
        self._entered = True
        self.tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.tracer._pop(self)
        self.end("error" if exc is not None else "ok")


def _record(
    trace_id: str, span_id: str, parent_id: Optional[str], name: str,
    span_kind: str, t0: float, t1: float, status: str, tid: int,
    attrs: Dict[str, Any],
) -> Dict[str, Any]:
    rec: Dict[str, Any] = {
        "trace": trace_id,
        "span": span_id,
        "parent": parent_id,
        "name": name,
        "span_kind": span_kind,
        "t0_ms": round(t0 * 1e3, 3),
        "dur_ms": round(max(t1 - t0, 0.0) * 1e3, 3),
        "status": status,
        "tid": tid,
    }
    if attrs:
        rec["attrs"] = attrs
    return rec


class Tracer:
    """Span factory + bounded staging buffer in front of the event sink.

    ``sink`` is anything with ``emit(kind, **fields)`` (the run's
    :class:`~.events.EventLog` / :class:`~.telemetry.Telemetry`); None
    keeps completed spans in the buffer for :meth:`drain` (tests,
    in-process consumers). Completed spans flush to the sink in batches
    of ``flush_every``; the buffer never exceeds ``capacity`` — beyond
    it spans are dropped and counted, because a tracer that can stall
    or OOM the serving engine is worse than a gap in the trace."""

    def __init__(
        self,
        sink: Any = None,
        *,
        enabled: bool = True,
        capacity: int = 8192,
        flush_every: int = 32,
        registry: Any = None,
    ):
        self.enabled = bool(enabled)
        self.run_trace = secrets.token_hex(8)
        self._sink = sink
        self._capacity = int(capacity)
        self._flush_every = max(int(flush_every), 1)
        self._lock = threading.Lock()
        self._spans: List[Dict[str, Any]] = []   # guarded-by: _lock
        self._dropped = 0                        # guarded-by: _lock
        self._local = threading.local()
        self._drop_ctr = None
        if registry is not None:
            self._drop_ctr = registry.counter(
                SPANS_DROPPED_TOTAL,
                "completed spans dropped on a full trace buffer",
            )

    # -- current-span stack (thread-local) -----------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()

    def current(self) -> Optional[Span]:
        """This thread's innermost ``with``-entered span (chaos fault
        points parent their spans to it)."""
        if not self.enabled:
            return None
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    # -- span creation -------------------------------------------------------

    def _resolve(
        self, ctx: Optional[TraceContext], parent: Any, fresh: bool,
    ) -> tuple:
        """(trace_id, parent_id) from the caller's intent: an adopted
        wire context wins, then an explicit parent span/context, then
        the thread-local current span, then a fresh trace (request
        roots) or the tracer's run trace (engine/trainer internals)."""
        if ctx is not None:
            return ctx.trace_id, ctx.span_id
        if parent is not None and not isinstance(parent, _NullSpan):
            # Span and TraceContext both expose trace_id/span_id.
            return parent.trace_id, parent.span_id
        cur = self.current()
        if cur is not None:
            return cur.trace_id, cur.span_id
        if fresh:
            return secrets.token_hex(8), None
        return self.run_trace, None

    def start(
        self, name: str, *, kind: str = "span",
        ctx: Optional[TraceContext] = None, parent: Any = None,
        fresh: bool = False, **attrs: Any,
    ):
        """A live span handle (end it explicitly, or use as a context
        manager). ``ctx``: adopt a wire context (server side of the
        header contract). ``parent``: an explicit Span/TraceContext —
        the cross-thread parenting path. ``fresh=True`` mints a new
        trace when no context applies (one trace per request)."""
        if not self.enabled:
            return NULL_SPAN
        trace_id, parent_id = self._resolve(ctx, parent, fresh)
        return Span(self, name, kind, trace_id, parent_id, attrs)

    def record(
        self, name: str, *, kind: str = "span",
        t0: float, t1: Optional[float] = None,
        ctx: Optional[TraceContext] = None, parent: Any = None,
        fresh: bool = False, status: str = "ok", **attrs: Any,
    ) -> Optional[str]:
        """Record a completed span retrospectively from explicit
        monotonic timestamps — the hot-path-friendly form: the engine
        measures with plain floats and banks the spans after delivery.
        Returns the span id (for chaining parents), or None when
        disabled."""
        if not self.enabled:
            return None
        trace_id, parent_id = self._resolve(ctx, parent, fresh)
        span_id = secrets.token_hex(8)
        self._enqueue(_record(
            trace_id, span_id, parent_id, name, kind, t0,
            t0 if t1 is None else t1, status, _tid(), attrs,
        ))
        return span_id

    # -- buffer / sink -------------------------------------------------------

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def _enqueue(self, rec: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        dropped = False
        with self._lock:
            if len(self._spans) >= self._capacity:
                self._dropped += 1
                dropped = True
            else:
                self._spans.append(rec)
            pending = len(self._spans)
        if dropped:
            if self._drop_ctr is not None:
                self._drop_ctr.inc()
            return
        if self._sink is not None and pending >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        """Drain the staged spans to the sink — called on batch
        boundaries and by ``Telemetry.close()`` so a sealed log carries
        every completed span. All emits happen outside the buffer
        lock."""
        if self._sink is None:
            return
        while True:
            with self._lock:
                if not self._spans:
                    return
                batch = self._spans
                self._spans = []
            for rec in batch:
                self._sink.emit("span", **rec)

    def drain(self) -> List[Dict[str, Any]]:
        """Pop and return the staged records (sink-less tracers /
        tests)."""
        with self._lock:
            batch = self._spans
            self._spans = []
        return batch


#: Shared disabled tracer — what call sites fall back to when no
#: telemetry is attached, so instrumentation never needs None checks.
NULL_TRACER = Tracer(sink=None, enabled=False)


# -- reading a traced run ----------------------------------------------------


def load_spans(path: str) -> List[Dict[str, Any]]:
    """The ``span`` events of an events.jsonl, in file order."""
    return [e for e in read_events(path) if e.get("kind") == "span"]


def children_index(
    spans: Iterable[Dict[str, Any]]
) -> Dict[tuple, List[Dict[str, Any]]]:
    """(trace, parent span id) -> child spans. Parent links only bind
    within one trace — a span id is only unique per trace."""
    idx: Dict[tuple, List[Dict[str, Any]]] = {}
    for s in spans:
        if s.get("parent"):
            idx.setdefault((s.get("trace"), s["parent"]), []).append(s)
    return idx


def request_roots(
    spans: Iterable[Dict[str, Any]], kind: str = "request"
) -> List[Dict[str, Any]]:
    return [s for s in spans if s.get("span_kind") == kind]


def unresolved_parents(spans: List[Dict[str, Any]]) -> List[str]:
    """Span ids whose parent does not exist in the same trace — broken
    tree links. Request roots are exempt: their parent may legitimately
    live in the CLIENT's process (the adopted ``x-jg-trace`` span)."""
    by_trace: Dict[Any, set] = {}
    for s in spans:
        by_trace.setdefault(s.get("trace"), set()).add(s.get("span"))
    broken = []
    for s in spans:
        if not s.get("parent") or s.get("span_kind") == "request":
            continue
        if s["parent"] not in by_trace.get(s.get("trace"), set()):
            broken.append(s.get("span"))
    return broken


def percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Linear-interpolated percentile over an ASCENDING-sorted list;
    None on empty input. The one exact-percentile helper shared by the
    run-log summary, the tail-attribution report and the serving
    saturation harness — the p99 the perf gate bands and the p99 the
    trace report shows must come from the same arithmetic."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q / 100.0 * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _self_times(
    span: Dict[str, Any],
    kids_idx: Dict[tuple, List[Dict[str, Any]]],
    out: Dict[str, float],
    _depth: int = 0,
) -> None:
    """Critical-path accounting for a sequentially-composed tree: each
    span contributes its SELF time (duration minus children, clipped at
    zero) under its kind; the root's own self time is the unattributed
    remainder (handler hop, response write)."""
    if _depth > 64:          # defensive: a cyclic parent link must not recurse forever
        return
    dur = float(span.get("dur_ms") or 0.0)
    kid_sum = 0.0
    for kid in kids_idx.get((span.get("trace"), span.get("span")), ()):
        kid_sum += min(float(kid.get("dur_ms") or 0.0), dur)
        _self_times(kid, kids_idx, out, _depth + 1)
    kind = span.get("span_kind") or "span"
    out[kind] = out.get(kind, 0.0) + max(dur - kid_sum, 0.0)


def tail_attribution(
    spans: List[Dict[str, Any]], *, pct: float = 99.0,
) -> Dict[str, Any]:
    """Break down where the slow tail's time went.

    Takes the request-root spans at or above the ``pct`` latency
    percentile and attributes each one's duration to span kinds by
    critical-path self time (``queue`` vs ``prefill`` vs ``decode`` vs
    ``infer`` vs ``stall`` ...; the root's own self time shows up under
    ``request`` = unattributed). The aggregate answers "is the p99
    queue-dominated or a slow dispatch" in one number per kind."""
    roots = request_roots(spans)
    durs = sorted(float(r.get("dur_ms") or 0.0) for r in roots)
    cutoff = percentile(durs, pct)
    report: Dict[str, Any] = {
        "n_requests": len(roots),
        "pct": pct,
        "cutoff_ms": cutoff,
        "p50_ms": percentile(durs, 50.0),
        "p99_ms": percentile(durs, 99.0),
        "tail": [],
        "aggregate_ms": {},
        "dominant": None,
    }
    if not roots:
        return report
    kids_idx = children_index(spans)
    tail = sorted(
        (r for r in roots if float(r.get("dur_ms") or 0.0) >= cutoff),
        key=lambda r: float(r.get("dur_ms") or 0.0), reverse=True,
    )
    agg: Dict[str, float] = {}
    for root in tail:
        breakdown: Dict[str, float] = {}
        _self_times(root, kids_idx, breakdown)
        for k, v in breakdown.items():
            agg[k] = agg.get(k, 0.0) + v
        dominant = max(breakdown, key=breakdown.get) if breakdown else None
        report["tail"].append({
            "id": (root.get("attrs") or {}).get("id"),
            "trace": root.get("trace"),
            "status": root.get("status"),
            "dur_ms": root.get("dur_ms"),
            "breakdown_ms": {
                k: round(v, 3) for k, v in sorted(
                    breakdown.items(), key=lambda kv: -kv[1]
                )
            },
            "dominant": dominant,
        })
    report["aggregate_ms"] = {
        k: round(v, 3)
        for k, v in sorted(agg.items(), key=lambda kv: -kv[1])
    }
    if agg:
        report["dominant"] = max(agg, key=agg.get)
    return report


def stitch_spans(
    groups: Dict[str, List[Dict[str, Any]]], *,
    source_attr: str = "process",
) -> Dict[str, Any]:
    """Join span logs from N fleet process dirs into one tree per hop
    chain — the multi-directory half of the ``x-jg-trace`` contract.

    ``groups`` maps a process name (the telemetry dir basename: the
    router dir plus one dir per replica rid) to its loaded spans. The
    router's ``fleet.dispatch`` span carries ``attrs.replica`` — the
    rid it dispatched to — and the replica's ``serve.request`` root
    shares the forwarded trace id, so the join key is
    ``(trace_id, replica)``: each replica-side request root is
    re-parented UNDER its dispatch span (overriding whatever parent
    the wire context gave it — with a traced client the replica root
    natively parents to the CLIENT's span and is a sibling of the
    router's ``fleet.request``, which is correct causality but useless
    for attribution) and demoted from ``span_kind="request"`` to
    ``"replica_request"`` so :func:`tail_attribution` keeps exactly one
    root per request and the breakdown splits router self time
    (``request`` + ``dispatch`` = router queueing/hop) from replica
    time (``queue``/``assemble``/``infer``/``respond`` +
    ``replica_request`` = replica-side unattributed).

    Span clocks are per-process monotonic, so each joined replica
    subtree is time-shifted to start at its dispatch span's ``t0_ms``
    — after stitching all spans share the ROUTER's clock lane (exact
    within a process, aligned-at-dispatch across the hop).

    Retries: a trace with N dispatch attempts to the same replica
    consumes dispatches in ``t0_ms`` order against that replica's
    request roots in ``t0_ms`` order. Every input span is copied (the
    caller's lists are never mutated) and tagged with
    ``attrs[source_attr] = <group name>``.

    Returns ``{"spans", "joined", "replica_roots", "unjoined"}``.
    """
    tagged: Dict[str, List[Dict[str, Any]]] = {}
    for gname, spans in groups.items():
        rows = []
        for s in spans:
            c = dict(s)
            c["attrs"] = {**(s.get("attrs") or {}), source_attr: gname}
            rows.append(c)
        tagged[gname] = rows

    # (trace, replica rid) -> dispatch spans, oldest first
    disp_idx: Dict[tuple, List[Dict[str, Any]]] = {}
    router_groups = set()
    for gname, rows in tagged.items():
        for s in rows:
            if s.get("span_kind") == "dispatch":
                router_groups.add(gname)
                rep = (s.get("attrs") or {}).get("replica")
                disp_idx.setdefault((s.get("trace"), rep), []).append(s)
    for lst in disp_idx.values():
        lst.sort(key=lambda s: float(s.get("t0_ms") or 0.0))

    joined = 0
    replica_roots = 0
    unjoined: List[str] = []
    for gname, rows in tagged.items():
        if gname in router_groups:
            continue
        kids_idx = children_index(rows)
        roots = sorted(
            (s for s in rows if s.get("span_kind") == "request"),
            key=lambda s: float(s.get("t0_ms") or 0.0),
        )
        for root in roots:
            replica_roots += 1
            lst = disp_idx.get((root.get("trace"), gname))
            if not lst:
                # dir name != rid: fall back to the trace id alone when
                # it is unambiguous (exactly one unconsumed dispatch)
                cands = [
                    (key, l) for key, l in disp_idx.items()
                    if key[0] == root.get("trace") and l
                    and key[1] not in tagged
                ]
                lst = cands[0][1] if len(cands) == 1 else None
            if not lst:
                unjoined.append(root.get("span"))
                continue
            dispatch = lst.pop(0)
            offset = (float(dispatch.get("t0_ms") or 0.0)
                      - float(root.get("t0_ms") or 0.0))
            stack, seen = [root], set()
            while stack:
                s = stack.pop()
                if id(s) in seen:
                    continue
                seen.add(id(s))
                s["t0_ms"] = round(
                    float(s.get("t0_ms") or 0.0) + offset, 3
                )
                stack.extend(
                    kids_idx.get((s.get("trace"), s.get("span")), ())
                )
            root["parent"] = dispatch.get("span")
            root["span_kind"] = "replica_request"
            joined += 1

    all_spans = [s for rows in tagged.values() for s in rows]
    all_spans.sort(key=lambda s: float(s.get("t0_ms") or 0.0))
    return {
        "spans": all_spans,
        "joined": joined,
        "replica_roots": replica_roots,
        "unjoined": unjoined,
    }


def span_kind_totals(spans: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-kind span counts + total duration — the fallback report for
    logs with no request roots (a traced TRAINING run: step/checkpoint/
    restore/remesh spans)."""
    out: Dict[str, Dict[str, float]] = {}
    for s in spans:
        k = s.get("span_kind") or "span"
        row = out.setdefault(k, {"count": 0, "total_ms": 0.0})
        row["count"] += 1
        row["total_ms"] += float(s.get("dur_ms") or 0.0)
    return {
        k: {"count": int(v["count"]), "total_ms": round(v["total_ms"], 3)}
        for k, v in sorted(out.items(), key=lambda kv: -kv[1]["total_ms"])
    }


def render_attribution(report: Dict[str, Any]) -> str:
    """Human-readable tail-attribution table (the `cli trace`
    default)."""
    lines = [
        f"trace tail attribution: p{report['pct']:g} over "
        f"{report['n_requests']} request(s)",
        f"  latency p50 {_fmt_ms(report['p50_ms'])}   "
        f"p99 {_fmt_ms(report['p99_ms'])}   "
        f"cutoff {_fmt_ms(report['cutoff_ms'])}",
    ]
    total = sum(report["aggregate_ms"].values()) or 1.0
    for kind, ms in report["aggregate_ms"].items():
        label = "(unattributed)" if kind == "request" else kind
        lines.append(
            f"  {label:<16} {ms:>10.3f} ms  {100.0 * ms / total:5.1f}%"
        )
    if report["dominant"]:
        lines.append(f"  dominant kind: {report['dominant']}")
    for row in report["tail"][:10]:
        lines.append(
            f"  tail request {row['id']} ({row['status']}, "
            f"{_fmt_ms(row['dur_ms'])}): dominant {row['dominant']} — "
            + ", ".join(
                f"{k}={v:.1f}ms" for k, v in row["breakdown_ms"].items()
            )
        )
    return "\n".join(lines)


def _fmt_ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.1f}ms"


# -- Chrome trace-event export (Perfetto / chrome://tracing) -----------------


def to_chrome_trace(
    spans: List[Dict[str, Any]], *, pid: int = 0,
    process_name: Optional[str] = None,
) -> Dict[str, Any]:
    """Render span events as Chrome trace-event JSON — the object
    format (``{"traceEvents": [...]}``), complete ("X") events with
    microsecond ``ts``/``dur``, loadable in Perfetto / chrome://tracing
    as-is. Timestamps are the process monotonic clock; spans from one
    process align exactly, cross-process traces align per-lane (each
    pid keeps its own zero)."""
    events: List[Dict[str, Any]] = []
    if process_name:
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        })
    for s in spans:
        args: Dict[str, Any] = {
            "trace": s.get("trace"),
            "span": s.get("span"),
            "parent": s.get("parent"),
            "status": s.get("status"),
        }
        args.update(s.get("attrs") or {})
        events.append({
            "name": s.get("name", "?"),
            "cat": s.get("span_kind", "span"),
            "ph": "X",
            "ts": round(float(s.get("t0_ms") or 0.0) * 1e3, 1),
            "dur": max(round(float(s.get("dur_ms") or 0.0) * 1e3, 1), 0.0),
            "pid": pid,
            "tid": int(s.get("tid") or 0),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
