"""obs — the unified telemetry subsystem.

One layer answering "how fast is this step, why did it recompile, and
which host is unhealthy" across the trainer, the infer paths, the
parallel backends and bench.py. See OBSERVABILITY.md for the event
schema and how to read a run.

  registry   thread-safe counters/gauges/histograms + snapshot()
  events     structured JSONL run events + run manifest (size-rotated
             for long-lived servers; readers span segments)
  flops      analytic model FLOPs, chip peaks, MFU, HBM stats
  costs      per-program HLO cost ledger (cost_analysis/memory_analysis
             at every compile) + measured per-program MFU
  profile    on-demand jax.profiler captures (/admin/profile,
             --profile-steps) + the `profile` CLI's capture summary
  recompile  jit cache-miss counting (jax.monitoring + spike fallback)
  heartbeat  per-process liveness records
  telemetry  the facade the training/serving layers talk to
  summary    fold a run log into a report (the `telemetry` CLI)
  trace      per-request span trees + x-jg-trace propagation, run-scoped
             request ids, Perfetto export and p99 tail attribution
             (the `trace` CLI)
  aggregate  fleet-wide registry-snapshot merging (counters sum,
             gauges fan out per replica, histograms merge le-exactly)
             behind the fleet /metrics + /healthz rollup
  slo        declarative SLOs with multiwindow burn-rate alerting
             (slo_alert events, slo_burn_rate/slo_budget_remaining)
"""

from .aggregate import (
    FleetMetricsStore,
    FleetMetricsView,
    FleetSnapshot,
    healthz_rollup,
    merge_snapshots,
)
from .costs import CostLedger, extract_costs, get_ledger
from .events import (
    EventLog,
    MANIFEST_KIND,
    SCHEMA_VERSION,
    git_rev,
    load_events,
    read_events,
    utc_now,
)
from .flops import (
    chip_peak,
    chip_peak_bf16,
    dense_macs_per_example,
    device_memory_stats,
    device_peak_flops,
    jaxpr_macs_per_example,
    mfu,
    train_step_flops,
)
from .heartbeat import Heartbeat, read_heartbeats
from .profile import (
    ProfileBusyError,
    ProfileManager,
    get_profiler,
    render_capture_summary,
    summarize_capture,
)
from .recompile import RecompileTracker, get_tracker
from .registry import (
    Counter,
    DEFAULT_TIME_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    render_prometheus,
)
from .slo import SLOMonitor, SLOSpec, default_fleet_slos
from .summary import (
    decision_timeline,
    render_decision_timeline,
    render_fleet_table,
    render_table,
    summarize,
    summarize_fleet,
)
from .telemetry import Telemetry, peak_for_default_device
from .trace import (
    TRACE_HEADER,
    TraceContext,
    Tracer,
    format_header,
    load_spans,
    mint_context,
    next_request_id,
    parse_header,
    stitch_spans,
    tail_attribution,
    to_chrome_trace,
)

__all__ = [
    "CostLedger",
    "Counter",
    "DEFAULT_TIME_BUCKETS",
    "EventLog",
    "FleetMetricsStore",
    "FleetMetricsView",
    "FleetSnapshot",
    "Gauge",
    "Heartbeat",
    "Histogram",
    "MANIFEST_KIND",
    "MetricsRegistry",
    "ProfileBusyError",
    "ProfileManager",
    "RecompileTracker",
    "SCHEMA_VERSION",
    "SLOMonitor",
    "SLOSpec",
    "TRACE_HEADER",
    "Telemetry",
    "TraceContext",
    "Tracer",
    "chip_peak",
    "chip_peak_bf16",
    "decision_timeline",
    "default_fleet_slos",
    "default_registry",
    "dense_macs_per_example",
    "device_memory_stats",
    "device_peak_flops",
    "extract_costs",
    "format_header",
    "get_ledger",
    "get_profiler",
    "get_tracker",
    "git_rev",
    "healthz_rollup",
    "jaxpr_macs_per_example",
    "load_events",
    "load_spans",
    "merge_snapshots",
    "mfu",
    "mint_context",
    "next_request_id",
    "parse_header",
    "peak_for_default_device",
    "read_events",
    "read_heartbeats",
    "render_capture_summary",
    "render_decision_timeline",
    "render_fleet_table",
    "render_prometheus",
    "render_table",
    "stitch_spans",
    "summarize",
    "summarize_capture",
    "summarize_fleet",
    "tail_attribution",
    "to_chrome_trace",
    "train_step_flops",
    "utc_now",
]
