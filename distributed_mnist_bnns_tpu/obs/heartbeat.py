"""Multi-host heartbeats — each process periodically writes a small
liveness record so a stalled host is diagnosable after the fact.

Two artifacts per process, under the run's telemetry directory:

  heartbeat_p<idx>.json    latest-state file, atomically replaced each
                           beat (a monitor reads ONE file per host and
                           compares ``ts`` against the wall clock)
  heartbeat_p<idx>.jsonl   bounded history (schema-v1 ``heartbeat``
                           events) — the post-mortem trail; rotated in
                           place once it exceeds ``max_lines`` records,
                           keeping the newest half.

Unlike every other event stream, heartbeats are written by EVERY
process, not just the primary — a primary-only heartbeat cannot tell you
which non-primary host stalled. The writer is a daemon thread so a hung
device dispatch on the main thread does not stop the beats; the payload
callback runs host-side only (never touches device state)."""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, Optional

from .events import SCHEMA_VERSION, utc_now


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except (ImportError, RuntimeError):
        return 0


class Heartbeat:
    """Background heartbeat writer; use as a context manager or call
    ``start()``/``stop()``. ``payload_fn`` supplies extra fields per
    beat (e.g. the trainer's current step counter)."""

    def __init__(
        self,
        directory: str,
        *,
        interval_s: float = 30.0,
        payload_fn: Optional[Callable[[], Dict]] = None,
        max_lines: int = 512,
    ):
        self.directory = directory
        self.interval_s = interval_s
        self.payload_fn = payload_fn
        self.max_lines = max(int(max_lines), 2)
        self.process_index = _process_index()
        base = os.path.join(
            directory, f"heartbeat_p{self.process_index}"
        )
        self.state_path = base + ".json"
        self.history_path = base + ".jsonl"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._beats = 0

    # -- single beat (also usable synchronously, e.g. from tests) -----------

    def beat(self) -> Dict:
        self._beats += 1
        record = {
            "v": SCHEMA_VERSION,
            "kind": "heartbeat",
            "ts": utc_now(),
            "process_index": self.process_index,
            "pid": os.getpid(),
            "beat": self._beats,
        }
        if self.payload_fn is not None:
            try:
                record.update(self.payload_fn())
            except Exception as e:  # a payload bug must not kill liveness
                record["payload_error"] = repr(e)[:200]
        os.makedirs(self.directory, exist_ok=True)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, self.state_path)
        with open(self.history_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        self._maybe_rotate()
        return record

    def _maybe_rotate(self) -> None:
        """Bound the history file: once past 2x max_lines, keep the
        newest max_lines (atomic rewrite — a reader never sees a
        truncated file)."""
        try:
            with open(self.history_path) as f:
                lines = f.readlines()
        except OSError:
            return
        if len(lines) <= 2 * self.max_lines:
            return
        tmp = self.history_path + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(lines[-self.max_lines:])
        os.replace(tmp, self.history_path)

    # -- background thread --------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.beat()
            # jg: disable=JG005 -- an IO hiccup must not kill liveness
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="obs-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, final_beat: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_beat:
            try:
                self.beat()
            # jg: disable=JG005 -- best-effort last beat during teardown
            except Exception:
                pass

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def read_heartbeats(directory: str) -> Dict[int, Dict]:
    """Latest heartbeat per process index from a telemetry directory —
    the monitor/post-mortem read path."""
    out: Dict[int, Dict] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("heartbeat_p") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                rec = json.load(f)
            out[int(rec.get("process_index", -1))] = rec
        except (OSError, ValueError, TypeError):
            continue  # truncated/corrupt beat file: skip, don't poison
    return out
