"""Fleet-wide metric aggregation: merge N registry snapshots into one.

PR 14 gave every fleet process its own registry and `/metrics`; this
module is the missing reduce step. A snapshot here is exactly what
:meth:`~.registry.MetricsRegistry.snapshot` renders — plain dicts, no
live instruments — so merging works identically over HTTP-scraped
replica bodies, in-process registries, and post-mortem `metrics`
events from a log.

Merge semantics (the contract OBSERVABILITY.md "Fleet observability"
documents and tests/test_fleet_obs.py pins):

  * **counters** sum by label key — fleet `requests_total` is the sum
    of replica `requests_total`, per label set.
  * **gauges** cannot meaningfully sum alone (queue depths on two
    replicas are two facts, not one), so every source series survives
    with an added ``replica=<source>`` label, plus synthesized
    ``replica="fleet"`` series carrying ``agg="min"|"max"|"sum"`` per
    original label set — dashboards get both the per-replica fan-out
    and the fleet envelope.
  * **histograms** merge their cumulative ``le`` buckets EXACTLY:
    element-wise ``bucket_counts`` sums plus summed ``sum``/``count``
    and min/max of the exact extrema. This is only exact when every
    source used identical bucket boundaries (true for a fleet running
    one code version); a source with mismatched boundaries is dropped
    from that metric and recorded in ``FleetSnapshot.conflicts``
    rather than merged approximately — a silently-wrong p99 is worse
    than a missing replica.

Type conflicts (one source says counter, another histogram) keep the
first-seen type and record the rest as conflicts, same policy.

:class:`FleetMetricsStore` holds the latest scraped snapshot +
`/healthz` payload per replica (the router's scrape loop writes it),
and :class:`FleetMetricsView` fronts the store plus the router's own
local registry behind a single ``.snapshot()`` — the exact duck type
``serve/httpbase.py``'s ``_reply_metrics`` negotiates into JSON or
Prometheus text, so the fleet `/metrics` endpoint is one object swap.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

__all__ = [
    "FleetSnapshot",
    "FleetMetricsStore",
    "FleetMetricsView",
    "merge_snapshots",
    "healthz_rollup",
]


def _label_key(labels: Mapping[str, Any]) -> Tuple[Tuple[str, str], ...]:
    """The registry's canonical series key (sorted string pairs)."""
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class FleetSnapshot(dict):
    """A merged snapshot: a plain ``{name: metric}`` dict (renders
    through ``render_prometheus`` / JSON unchanged) plus a
    ``conflicts`` attribute listing every source×metric the merge had
    to drop (type or bucket-boundary mismatch)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.conflicts: List[str] = []


def merge_snapshots(
    sources: Mapping[str, Mapping[str, dict]],
    *,
    source_label: str = "replica",
) -> FleetSnapshot:
    """Merge ``{source_name: registry_snapshot}`` into one snapshot.

    Deterministic: sources are processed in sorted name order, so two
    scrapes of the same fleet state render byte-identical Prometheus
    text. Input snapshots are never mutated.
    """
    merged = FleetSnapshot()
    # name -> type/help/buckets resolved from the first source seen
    shapes: Dict[str, dict] = {}
    # counters: name -> {label_key: (labels, value)}
    counters: Dict[str, Dict[tuple, list]] = {}
    # gauges: name -> {orig_label_key: (labels, [per-source values])}
    gauge_rows: Dict[str, List[dict]] = {}
    gauge_aggs: Dict[str, Dict[tuple, list]] = {}
    # histograms: name -> {label_key: merged series row}
    hists: Dict[str, Dict[tuple, dict]] = {}

    for src in sorted(sources):
        snapshot = sources[src] or {}
        for name in sorted(snapshot):
            metric = snapshot[name]
            if not isinstance(metric, dict) or "type" not in metric:
                merged.conflicts.append(f"{src}/{name}: malformed metric")
                continue
            mtype = metric["type"]
            shape = shapes.get(name)
            if shape is None:
                shape = {
                    "type": mtype,
                    "help": metric.get("help", ""),
                    "buckets": list(metric.get("buckets") or []),
                }
                shapes[name] = shape
            elif shape["type"] != mtype:
                merged.conflicts.append(
                    f"{src}/{name}: type {mtype!r} != {shape['type']!r}"
                )
                continue
            series = metric.get("series") or []
            if mtype == "counter":
                rows = counters.setdefault(name, {})
                for s in series:
                    key = _label_key(s.get("labels") or {})
                    row = rows.get(key)
                    if row is None:
                        rows[key] = [dict(s.get("labels") or {}),
                                     float(s.get("value", 0.0))]
                    else:
                        row[1] += float(s.get("value", 0.0))
            elif mtype == "gauge":
                rows_out = gauge_rows.setdefault(name, [])
                aggs = gauge_aggs.setdefault(name, {})
                for s in series:
                    labels = dict(s.get("labels") or {})
                    value = float(s.get("value", 0.0))
                    rows_out.append({
                        "labels": {**labels, source_label: src},
                        "value": value,
                    })
                    agg = aggs.setdefault(_label_key(labels),
                                          [labels, []])
                    agg[1].append(value)
            elif mtype == "histogram":
                if list(metric.get("buckets") or []) != shape["buckets"]:
                    merged.conflicts.append(
                        f"{src}/{name}: bucket boundaries "
                        f"{metric.get('buckets')} != {shape['buckets']} "
                        "(dropped: cannot merge exactly)"
                    )
                    continue
                rows = hists.setdefault(name, {})
                n_counts = len(shape["buckets"]) + 1
                for s in series:
                    key = _label_key(s.get("labels") or {})
                    counts = list(s.get("bucket_counts") or [])
                    if len(counts) != n_counts:
                        merged.conflicts.append(
                            f"{src}/{name}: bucket_counts length "
                            f"{len(counts)} != {n_counts} (dropped)"
                        )
                        continue
                    row = rows.get(key)
                    if row is None:
                        rows[key] = {
                            "labels": dict(s.get("labels") or {}),
                            "count": int(s.get("count", 0)),
                            "sum": float(s.get("sum", 0.0)),
                            "min": s.get("min"),
                            "max": s.get("max"),
                            "bucket_counts": counts,
                        }
                    else:
                        row["count"] += int(s.get("count", 0))
                        row["sum"] += float(s.get("sum", 0.0))
                        for lo_hi, pick in (("min", min), ("max", max)):
                            v = s.get(lo_hi)
                            if v is not None:
                                row[lo_hi] = (
                                    v if row[lo_hi] is None
                                    else pick(row[lo_hi], v)
                                )
                        row["bucket_counts"] = [
                            a + b for a, b in zip(row["bucket_counts"],
                                                  counts)
                        ]
            else:
                merged.conflicts.append(
                    f"{src}/{name}: unknown type {mtype!r}"
                )

    for name, rows in counters.items():
        merged[name] = {
            "type": "counter",
            "help": shapes[name]["help"],
            "series": [{"labels": labels, "value": value}
                       for labels, value in rows.values()],
        }
    for name, rows_out in gauge_rows.items():
        fleet_rows = []
        for labels, values in gauge_aggs[name].values():
            for agg, value in (("min", min(values)), ("max", max(values)),
                               ("sum", sum(values))):
                fleet_rows.append({
                    "labels": {**labels, source_label: "fleet",
                               "agg": agg},
                    "value": value,
                })
        merged[name] = {
            "type": "gauge",
            "help": shapes[name]["help"],
            "series": rows_out + fleet_rows,
        }
    for name, rows in hists.items():
        merged[name] = {
            "type": "histogram",
            "help": shapes[name]["help"],
            "buckets": shapes[name]["buckets"],
            "series": list(rows.values()),
        }
    return merged


def healthz_rollup(
    replica_rows: List[Mapping[str, Any]],
    healthz: Mapping[str, Mapping[str, Any]],
) -> Dict[str, Any]:
    """Fold the router's per-replica rows plus the scraped `/healthz`
    payloads into the fleet rollup the fleet `/healthz` reports:
    healthy/total counts, the worst replica status, and the per-replica
    detail (router view + last scraped body side by side)."""
    order = {"ok": 0, "draining": 1, "unknown": 2, "failed": 3}
    worst = "ok" if replica_rows else "unknown"
    per_replica = []
    healthy = 0
    for row in replica_rows:
        rid = row.get("replica") or row.get("id")
        scraped = dict(healthz.get(rid) or {})
        status = scraped.get("status") or (
            "ok" if row.get("healthy") else "unknown"
        )
        if row.get("healthy"):
            healthy += 1
        else:
            status = scraped.get("status") or "failed"
            if status == "ok":      # router ejected it since the scrape
                status = "unknown"
        if order.get(status, 3) > order.get(worst, 0):
            worst = status
        per_replica.append({**row, "scraped": scraped or None,
                            "status": status})
    return {
        "replicas_total": len(replica_rows),
        "replicas_healthy": healthy,
        "status": worst if healthy else ("unknown" if not replica_rows
                                         else "failed"),
        "replicas": per_replica,
    }


class FleetMetricsStore:
    """Latest scraped snapshot + `/healthz` body per replica, written
    by the router's scrape loop and read by the fleet `/metrics` /
    `/healthz` endpoints. Thread-safe; ``clock`` injectable for
    tests."""

    def __init__(self, *, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._snapshots: Dict[str, dict] = {}
        self._healthz: Dict[str, dict] = {}
        self._scraped_at: Dict[str, float] = {}
        self._errors: Dict[str, str] = {}

    def update(self, rid: str, *, snapshot: Optional[dict] = None,
               healthz: Optional[dict] = None,
               error: Optional[str] = None) -> None:
        with self._lock:
            if error is not None:
                self._errors[rid] = error
                return
            self._errors.pop(rid, None)
            if snapshot is not None:
                self._snapshots[rid] = snapshot
            if healthz is not None:
                self._healthz[rid] = healthz
            self._scraped_at[rid] = self._clock()

    def discard(self, rid: str) -> None:
        """Forget a retired/dead replica — its counters would otherwise
        freeze into the fleet sums forever."""
        with self._lock:
            self._snapshots.pop(rid, None)
            self._healthz.pop(rid, None)
            self._scraped_at.pop(rid, None)
            self._errors.pop(rid, None)

    def snapshots(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._snapshots)

    def healthz(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._healthz)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            now = self._clock()
            return {
                "replicas_scraped": len(self._snapshots),
                "scrape_age_s": {
                    rid: round(now - t, 3)
                    for rid, t in self._scraped_at.items()
                },
                "scrape_errors": dict(self._errors),
            }


class FleetMetricsView:
    """``.snapshot()`` facade over (local control-plane registry) +
    (scraped replica snapshots): the object the fleet `/metrics` hands
    to ``_reply_metrics``, which then renders JSON or Prometheus via
    the existing content negotiation."""

    def __init__(self, local_registry: Any, store: FleetMetricsStore,
                 *, local_name: str = "router"):
        self._local = local_registry
        self._store = store
        self._local_name = local_name

    def snapshot(self) -> FleetSnapshot:
        sources = {self._local_name: self._local.snapshot()}
        sources.update(self._store.snapshots())
        return merge_snapshots(sources)
