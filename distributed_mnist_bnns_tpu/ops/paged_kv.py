"""Block-paged KV cache — the allocator + device primitives behind the
continuous-batching LM engine (serve/lm/, SERVING.md "Continuous LM
serving").

The contiguous per-sequence (B, L, H, D) cache of
``infer_transformer.make_lm_decoder`` wastes a full max-length strip per
batch slot and forces every sequence in a batch to share one lifetime.
PagedAttention (vLLM, SOSP '23) replaces the strip with fixed-size
**pages** drawn from one shared pool: a sequence holds a *page table*
(list of page ids covering its positions so far), pages are allocated as
the sequence grows and returned to the free list the moment it finishes,
and the decode step addresses the cache through the table — so requests
can join and leave the decode batch at any iteration while the jitted
step only ever sees ONE signature (fixed slot count, fixed table shape).

Layout and conventions:

  * a K (or V) pool is ``(num_pages, page_size, H, D)`` fp32; logical
    position ``p`` of a sequence lives at page ``table[p // page_size]``,
    offset ``p % page_size``;
  * **page 0 is the null page** — never allocated, it absorbs the writes
    of inactive batch slots and of padding positions (their flat index
    is forced into page 0), so a fixed-shape scatter needs no masking
    branches. Null-page contents are garbage by design and are always
    masked out of attention (positions > the slot's length get -inf
    before the softmax; exp(-inf) = 0 exactly);
  * page tables are host-side int32 arrays shaped ``(max_pages,)`` per
    sequence, 0-filled beyond the allocated prefix — the device never
    sees a ragged structure.

The allocator is deliberately host-side and trivial (a free list under a
lock): allocation happens at admission/grow time on the scheduler
thread, never inside the jitted step.
"""

from __future__ import annotations

import functools
import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .flash_attention import NEG_INF

NULL_PAGE = 0


def pages_needed(n_tokens: int, page_size: int) -> int:
    """Pages required to hold ``n_tokens`` positions."""
    if n_tokens <= 0:
        return 0
    return -(-int(n_tokens) // int(page_size))


class PageAllocator:
    """Refcounted free-list allocator over ``num_pages`` pages; page 0
    reserved.

    ``alloc`` is all-or-nothing: a request that cannot get every page it
    asked for gets none (the caller re-queues instead of holding a
    partial reservation that could deadlock admission). Thread-safe —
    the HTTP handlers query occupancy while the scheduler allocates.

    Pages carry **refcounts** so sequences can share an immutable prompt
    prefix copy-on-write (SERVING.md "Prefix caching"): ``alloc``
    returns pages at refcount 1, ``fork`` takes an extra reference on
    live pages (the prefix-cache hit path maps them into a second
    sequence's page table), and ``free`` *releases* one reference — the
    page only returns to the free list when its last holder releases
    it. The double-free hard error is preserved exactly for that last
    holder: releasing a page whose refcount is already 0 means the
    caller's page-lifetime bookkeeping is corrupt.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"need >= 2 pages (page 0 is the reserved null page), "
                f"got {num_pages}"
            )
        self.num_pages = int(num_pages)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._refs: List[int] = [0] * self.num_pages

    @property
    def capacity(self) -> int:
        """Allocatable pages (excludes the null page)."""
        return self.num_pages - 1

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def used_count(self) -> int:
        return self.capacity - self.free_count()

    def occupancy(self) -> float:
        """Fraction of allocatable pages currently held, in [0, 1]."""
        return self.used_count() / max(self.capacity, 1)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` page ids at refcount 1, or None if fewer than ``n``
        are free."""
        n = int(n)
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        with self._lock:
            if len(self._free) < n:
                return None
            out = [self._free.pop() for _ in range(n)]
            for p in out:
                self._refs[p] = 1
        return out

    def fork(self, pages: Sequence[int]) -> None:
        """Take one extra reference on each live page — the COW prefix
        share: a second sequence maps the same physical pages read-only
        (its own writes land at positions past the shared prefix, in
        pages it allocated itself). Forking a free page is a hard error
        — the prefix index is holding a page it no longer owns."""
        with self._lock:
            for p in pages:
                p = int(p)
                if p == NULL_PAGE or not 0 < p < self.num_pages:
                    raise ValueError(f"cannot fork page {p}")
                if self._refs[p] <= 0:
                    raise ValueError(f"fork of free page {p}")
            for p in pages:
                self._refs[int(p)] += 1

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs[int(page)]

    def free(self, pages: Sequence[int]) -> None:
        """Release one reference per page; a page returns to the free
        list only when its LAST holder releases it. Double-free (a
        release past refcount 0) and null-page frees are hard errors —
        both mean the caller's page-lifetime bookkeeping is corrupt,
        and silently absorbing them would let two sequences share a
        page one of them no longer owns."""
        with self._lock:
            seen = set()
            for p in pages:
                p = int(p)
                if p == NULL_PAGE or not 0 < p < self.num_pages:
                    raise ValueError(f"cannot free page {p}")
                if p in seen:
                    # One owner releasing the same page twice in one
                    # call is the classic double-free shape even when
                    # other holders keep the refcount positive.
                    raise ValueError(f"double free of page {p}")
                if self._refs[p] <= 0:
                    raise ValueError(f"double free of page {p}")
                seen.add(p)
            for p in pages:
                p = int(p)
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    self._free.append(p)


def init_pools(
    num_blocks: int, num_pages: int, page_size: int,
    num_heads: int, head_dim: int,
) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]:
    """Zeroed per-block (K, V) page pools:
    ``((k0, v0), (k1, v1), ...)``, each ``(num_pages, page_size, H, D)``
    fp32 — the whole KV memory of the engine, shared by every sequence
    through page tables."""
    shape = (int(num_pages), int(page_size), int(num_heads), int(head_dim))
    # Distinct buffers per pool — the decode/prefill programs donate the
    # whole pools pytree, and XLA rejects donating one buffer twice.
    return tuple(
        (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))
        for _ in range(int(num_blocks))
    )


# ---------------------------------------------------------------------------
# Device-side primitives (trace-pure: no host syncs, fixed shapes)
# ---------------------------------------------------------------------------


def flat_write_indices(
    page_table: jnp.ndarray, positions: jnp.ndarray,
    page_size: int, valid: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Flat row indices into a ``(num_pages * page_size, ...)`` pool view
    for writing ``positions`` of the sequence(s) described by
    ``page_table``.

    Shapes: ``page_table`` (..., P) int32 with leading dims matching
    ``positions`` (...,) int32 — or a single shared ``(P,)`` table for a
    batch of positions (the chunked-prefill case). Positions flagged
    invalid (or whose page index would overrun the table) are redirected
    into the null page — index arithmetic stays branch-free and
    in-bounds, matching XLA's clamping gather/scatter semantics without
    relying on them.
    """
    ps = int(page_size)
    max_pages = page_table.shape[-1]
    page_idx = jnp.clip(positions // ps, 0, max_pages - 1)
    if page_table.ndim == 1:
        page = page_table[page_idx]
    else:
        page = jnp.take_along_axis(
            page_table, page_idx[..., None], axis=-1
        )[..., 0]
    in_table = positions // ps < max_pages
    ok = in_table if valid is None else (valid & in_table)
    page = jnp.where(ok, page, NULL_PAGE)
    return page * ps + positions % ps


def write_kv(
    pool: jnp.ndarray, flat_idx: jnp.ndarray, rows: jnp.ndarray
) -> jnp.ndarray:
    """Scatter ``rows`` (..., H, D) into the pool at ``flat_idx`` rows of
    its flattened ``(num_pages * page_size, H, D)`` view. Duplicate
    indices only ever occur inside the null page (invalid positions all
    map there), where last-writer-wins is fine."""
    n, ps, h, d = pool.shape
    flat = pool.reshape(n * ps, h, d)
    flat = flat.at[flat_idx.reshape(-1)].set(
        rows.reshape(-1, h, d), mode="drop"
    )
    return flat.reshape(n, ps, h, d)


def gather_kv(pool: jnp.ndarray, page_table: jnp.ndarray) -> jnp.ndarray:
    """Materialize the logical cache strip(s) a page table describes:
    ``page_table`` (..., P) over a ``(num_pages, page_size, H, D)`` pool
    -> (..., P * page_size, H, D), where gathered row ``l`` is logical
    position ``l`` (tables list pages in sequence order). Rows drawn
    through null-page entries are garbage and MUST be masked downstream
    (``paged_attention`` does)."""
    n, ps, h, d = pool.shape
    gathered = pool[page_table]                    # (..., P, ps, H, D)
    return gathered.reshape(*page_table.shape[:-1],
                            page_table.shape[-1] * ps, h, d)


def paged_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_tables: jnp.ndarray,
    positions: jnp.ndarray,
) -> jnp.ndarray:
    """Single-position attention through page tables.

    ``q`` (S, H, D) — one query per batch slot; ``page_tables`` (S, P);
    ``positions`` (S,) — the position being decoded (its K/V must
    already be written). Keys at logical positions > ``positions[s]``
    (unwritten tail, null-page garbage, other-sequence leftovers in
    freed-and-reused pages) are masked to -inf before the softmax, so
    the result equals contiguous-cache attention over the slot's real
    prefix exactly.
    """
    kc = gather_kv(k_pool, page_tables)            # (S, L, H, D)
    vc = gather_kv(v_pool, page_tables)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("shd,slhd->shl", q, kc) * scale
    l = kc.shape[1]
    mask = jnp.arange(l)[None, :] <= positions[:, None]
    scores = jnp.where(mask[:, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("shl,slhd->shd", probs, vc)


def paged_verify_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_tables: jnp.ndarray,
    positions: jnp.ndarray,
) -> jnp.ndarray:
    """K-position attention through page tables — the speculative-decode
    verify dispatch (SERVING.md "Speculative decoding").

    ``q`` (S, K, H, D) — K consecutive queries per batch slot, query j
    of slot s sitting at global position ``positions[s] + j``;
    ``page_tables`` (S, P); ``positions`` (S,) — the base position of
    each slot's verify window (its K/V, and the window's, must already
    be written). Query j attends causally to key positions
    <= positions[s] + j; everything later (unwritten tail, null-page
    garbage, rejected-draft leftovers) is masked to -inf before the
    softmax, so each query equals :func:`paged_attention` at its own
    position exactly.
    """
    kc = gather_kv(k_pool, page_tables)            # (S, L, H, D)
    vc = gather_kv(v_pool, page_tables)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("skhd,slhd->skhl", q, kc) * scale
    l = kc.shape[1]
    qpos = positions[:, None] + jnp.arange(q.shape[1])[None, :]
    mask = jnp.arange(l)[None, None, :] <= qpos[:, :, None]  # (S, K, L)
    scores = jnp.where(mask[:, :, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("skhl,slhd->skhd", probs, vc)


def paged_prefill_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    q_positions: jnp.ndarray,
) -> jnp.ndarray:
    """Chunked-prefill attention for ONE sequence: ``q`` (C, H, D)
    queries at global positions ``q_positions`` (C,), attending causally
    (key position <= query position) through the sequence's page table.
    The chunk's own K/V must be written before the call; padding queries
    (positions >= the real length) produce garbage rows the caller
    ignores — their mask row is non-empty so no NaN escapes."""
    kc = gather_kv(k_pool, page_table)             # (L, H, D)
    vc = gather_kv(v_pool, page_table)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("chd,lhd->chl", q, kc) * scale
    l = kc.shape[0]
    mask = jnp.arange(l)[None, :] <= q_positions[:, None]
    scores = jnp.where(mask[:, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("chl,lhd->chd", probs, vc)


# ---------------------------------------------------------------------------
# Pallas paged-attention kernel (decode / prefill / verify share one body)
# ---------------------------------------------------------------------------
#
# The gather oracles above materialize every K/V page a table references
# — (S, P * page_size, H, D) of HBM traffic per layer per step — before
# a single flop of attention runs. The kernel below never materializes
# that copy: the page table rides in as a *scalar-prefetch* operand, the
# grid walks (slot, page-block) with the page axis innermost-sequential,
# and the k/v BlockSpec index maps read ``tables[s, p]`` directly, so
# the Pallas pipeline fetches exactly one (page_size, H, D) page per
# step straight out of the pool. Softmax is the online accumulation of
# ``_flash_kernel`` (m/l/acc in VMEM scratch persisting across the
# sequential page steps); causal/validity masking (``kpos <= qpos``) is
# applied in-kernel, which also neutralizes null-page garbage exactly as
# the oracle's -inf mask does — every position past a slot's length,
# including everything a null-page entry covers, is masked before the
# softmax.
#
# One body serves all three call shapes via queries (S, C, H, D) with
# per-query positions (S, C): decode is C=1, verify is C=spec_k, prefill
# is S=1 with C=chunk.


def _paged_attn_kernel(
    tables_ref, q_ref, qpos_ref, k_ref, v_ref, o_ref,
    m_ref, l_ref, acc_ref, *, page_size, n_pblocks, scale,
):
    del tables_ref  # consumed by the BlockSpec index maps
    from jax.experimental import pallas as pl

    p = pl.program_id(1)

    @pl.when(p == 0)
    def _reset():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)               # (C, H, D)
    k = k_ref[0].astype(jnp.float32)               # (ps, H, D)
    v = v_ref[0].astype(jnp.float32)
    qpos = qpos_ref[0]                             # (C,) int32
    c = q.shape[0]

    # (H, C, ps) scores: contract D, batch over heads.
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((1,), (1,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ) * scale
    kpos = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (c, page_size), 1
    )
    mask = kpos <= qpos[:, None]                   # (C, ps)
    s = jnp.where(mask[None, :, :], s, NEG_INF)

    m_prev = m_ref[...]                            # (H, C)
    l_prev = l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=2))
    p_ = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new[..., None]), 0.0)
    corr = jnp.where(m_prev > NEG_INF / 2, jnp.exp(m_prev - m_new), 0.0)
    l_ref[...] = corr * l_prev + jnp.sum(p_, axis=2)
    m_ref[...] = m_new
    pv = jax.lax.dot_general(                      # (H, C, D)
        p_, v, (((2,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    acc_ref[...] = corr[..., None] * acc_ref[...] + pv

    @pl.when(p == n_pblocks - 1)
    def _finalize():
        l_fin = l_ref[...]
        safe_l = jnp.where(l_fin == 0.0, 1.0, l_fin)
        out = acc_ref[...] / safe_l[..., None]     # (H, C, D)
        o_ref[0] = jnp.transpose(out, (1, 0, 2)).astype(o_ref.dtype)


def _paged_attention_pallas(q4, qpos, k_pool, v_pool, tables, *, interpret):
    """Shared launcher: ``q4`` (S, C, H, D), ``qpos`` (S, C) int32,
    ``tables`` (S, P) int32 -> (S, C, H, D) fp32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, c, h, d = q4.shape
    _, ps, _, _ = k_pool.shape
    n_pblocks = tables.shape[-1]
    kernel = functools.partial(
        _paged_attn_kernel,
        page_size=ps, n_pblocks=n_pblocks, scale=d ** -0.5,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s, n_pblocks),
        in_specs=[
            pl.BlockSpec((1, c, h, d), lambda si, p, tb: (si, 0, 0, 0)),
            pl.BlockSpec((1, c), lambda si, p, tb: (si, 0)),
            # The in-kernel page-table walk: the pipeline fetches pool
            # page tables[si, p] for grid step (si, p) — no gather.
            pl.BlockSpec((1, ps, h, d),
                         lambda si, p, tb: (tb[si, p], 0, 0, 0)),
            pl.BlockSpec((1, ps, h, d),
                         lambda si, p, tb: (tb[si, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, c, h, d), lambda si, p, tb: (si, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, c), jnp.float32),
            pltpu.VMEM((h, c), jnp.float32),
            pltpu.VMEM((h, c, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, c, h, d), jnp.float32),
        interpret=bool(interpret),
    )(tables.astype(jnp.int32), q4.astype(jnp.float32),
      qpos.astype(jnp.int32), k_pool, v_pool)


def paged_attention_kernel(
    q, k_pool, v_pool, page_tables, positions, *, interpret: bool = False
):
    """Kernel twin of :func:`paged_attention` — same signature and
    semantics, no materialized gather. ``q`` (S, H, D)."""
    out = _paged_attention_pallas(
        q[:, None], positions[:, None], k_pool, v_pool, page_tables,
        interpret=interpret,
    )
    return out[:, 0]


def paged_verify_attention_kernel(
    q, k_pool, v_pool, page_tables, positions, *, interpret: bool = False
):
    """Kernel twin of :func:`paged_verify_attention`. ``q`` (S, K, H, D);
    query j of slot s sits at global position ``positions[s] + j``."""
    qpos = positions[:, None] + jnp.arange(
        q.shape[1], dtype=jnp.int32
    )[None, :]
    return _paged_attention_pallas(
        q, qpos, k_pool, v_pool, page_tables, interpret=interpret
    )


def paged_prefill_attention_kernel(
    q, k_pool, v_pool, page_table, q_positions, *, interpret: bool = False
):
    """Kernel twin of :func:`paged_prefill_attention`. ``q`` (C, H, D)
    for ONE sequence with table (P,) and positions (C,)."""
    out = _paged_attention_pallas(
        q[None], q_positions[None], k_pool, v_pool, page_table[None],
        interpret=interpret,
    )
    return out[0]
