"""Bitplane packing of ±1 tensors into int32 words.

This is the storage format for the XNOR-popcount GEMM backend (the TPU-native
replacement for the fp32 GEMM-on-±1-values the reference runs through cuDNN,
models/binarized_modules.py:80). Convention: bit = 1  ⟺  value = +1.

With that convention, for two packed words a, b covering 32 positions:
    mismatches = popcount(a XOR b)
    dot        = matches - mismatches = 32 - 2 * mismatches
so a full K-length ±1 dot product is  K - 2 * sum_w popcount(a_w XOR b_w).
Zero-padding *both* operands' tail words adds equal bits (matches only), so
the formula stays exact with the *unpadded* K — no masking needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

WORD_BITS = 32


def packed_dim(k: int, multiple: int = 1) -> int:
    """Number of int32 words needed to pack k bits, rounded up to `multiple`."""
    words = -(-k // WORD_BITS)
    return -(-words // multiple) * multiple


def pack_bits(x: jnp.ndarray, pad_words_to: int = 1) -> jnp.ndarray:
    """Pack ±1 values along the last axis into int32 bitplanes.

    x: (..., K) array of ±1 (any float/int dtype; >0 is treated as +1).
    Returns (..., packed_dim(K, pad_words_to)) int32.
    """
    k = x.shape[-1]
    kw = packed_dim(k, pad_words_to)
    pad = kw * WORD_BITS - k
    bits = (x > 0).astype(jnp.uint32)
    if pad:
        bits = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    bits = bits.reshape(*bits.shape[:-1], kw, WORD_BITS)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    words = jnp.sum(bits << shifts, axis=-1, dtype=jnp.uint32)
    return words.astype(jnp.int32)


_BITS_PER_PLANE = 7  # plane products <= 2^6 = 64, safe in int8


def _pack_matrix(k: int, kw: int) -> np.ndarray:
    """(k, planes*kw) int8 matrix P with P[32w + 7j + t, planes*w + j] = 2^t:
    bits @ P yields per-word 7-bit plane sums (int8 MXU, int32 accumulate)."""
    planes = -(-WORD_BITS // _BITS_PER_PLANE)
    P = np.zeros((kw * WORD_BITS, planes * kw), np.int8)
    for w in range(kw):
        for j in range(planes):
            base = WORD_BITS * w + _BITS_PER_PLANE * j
            for t in range(_BITS_PER_PLANE):
                if base + t < WORD_BITS * (w + 1):
                    P[base + t, planes * w + j] = 1 << t
    return P[:k]


def pack_bits_mxu(x: jnp.ndarray, pad_words_to: int = 1) -> jnp.ndarray:
    """pack_bits computed on the MXU: the bit-to-word reduction becomes an
    int8 matmul against a constant power-of-two pattern, followed by a
    5-way shift-or per word. ~2x faster than the VPU shift-reduce on TPU
    (the MXU is otherwise idle during packing); bit-identical output.
    The pattern matrix is a trace-time constant (int8, k x ~0.16k bytes)."""
    *lead, k = x.shape
    kw = packed_dim(k)
    planes = -(-WORD_BITS // _BITS_PER_PLANE)
    P = jnp.asarray(_pack_matrix(k, kw))
    bits = (x > 0).astype(jnp.int8).reshape(-1, k)
    sums = jax.lax.dot_general(
        bits, P, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    s = sums.reshape(-1, kw, planes).astype(jnp.uint32)
    word = s[..., 0]
    for j in range(1, planes):
        word = word | (s[..., j] << jnp.uint32(_BITS_PER_PLANE * j))
    words = word.astype(jnp.int32).reshape(*lead, kw)
    kw_padded = packed_dim(k, pad_words_to)
    if kw_padded != kw:
        words = jnp.pad(
            words, [(0, 0)] * (words.ndim - 1) + [(0, kw_padded - kw)]
        )
    return words


def unpack_bits(words: jnp.ndarray, k: int, dtype=jnp.float32) -> jnp.ndarray:
    """Inverse of pack_bits: (..., KW) int32 -> (..., k) ±1 array."""
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    bits = (words.astype(jnp.uint32)[..., None] >> shifts) & jnp.uint32(1)
    flat = bits.reshape(*words.shape[:-1], words.shape[-1] * WORD_BITS)
    pm1 = flat.astype(dtype) * 2 - 1
    return pm1[..., :k]


def pack_bits_np(x: np.ndarray, pad_words_to: int = 1) -> np.ndarray:
    """NumPy host-side variant of pack_bits (used by the data pipeline and
    the C++ loader's pure-python fallback)."""
    k = x.shape[-1]
    kw = packed_dim(k, pad_words_to)
    pad = kw * WORD_BITS - k
    bits = (x > 0).astype(np.uint32)
    if pad:
        bits = np.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    bits = bits.reshape(*bits.shape[:-1], kw, WORD_BITS)
    shifts = np.arange(WORD_BITS, dtype=np.uint32)
    words = np.sum(bits << shifts, axis=-1, dtype=np.uint64).astype(np.uint32)
    return words.view(np.int32)
