"""1-bit gradient compression for the data-parallel exchange.

The source paper's premise is distributed BNN training over a slow
commodity network, yet the plain DP step moves full fp32 gradients every
step — the one tensor class this codebase already knows how to make 32x
smaller (ops/bitpack: bitplane packing, 0.031 bytes/param). This module
compresses the gradient exchange itself: per-bucket **sign bitplanes**
(int32 words, the exact pack_bits wire format the XNOR kernels use) plus
one fp32 **scale per bucket** (mean |g| — the L2-optimal 1-bit
magnitude), following signSGD with majority vote (Bernstein et al.,
2018) and error-feedback sign compression (EF-SignSGD, Karimireddy et
al., 2019; two-stage residuals as in 1-bit Adam).

Exchange topology — two compressed phases, not one all_gather:

  phase 1 (compressed reduce-scatter): the flattened gradient is split
      into ``world`` segments; ``lax.all_to_all`` routes every worker's
      sign-planes for segment *j* to worker *j*, which decodes the
      ``world`` contributions and combines them (mean of scale*sign, or
      the Bernstein majority vote over raw signs).
  phase 2 (compressed all-gather): each segment owner re-compresses its
      combined segment (exact for majority output, whose magnitude is
      bucket-constant; a second error-feedback residual absorbs the
      requantization loss in mean mode) and ``lax.all_gather``
      broadcasts the result.

Per-worker wire bytes are ``2*(N-1)/N * (D/8 + 4*n_buckets)`` vs the
fp32 ring all-reduce's ``2*(N-1)/N * 4*D`` — a ~32x reduction (~1/31
with the default 1024-element buckets), independent of N. A single
all_gather of everyone's planes would instead cost ``(N-1)*D/8``
received bytes — only 8x at N=8 — which is why the reduce-scatter
shape matters on the slow interconnects this targets.

Overlap: the bucket axis is split into ``chunks`` independent groups,
each with its own pack -> all_to_all -> combine -> all_gather chain and
no data dependency on its neighbors, so XLA's async collectives overlap
the exchange of group *i* with the packing compute of group *i+1*
(the in-jit analogue of DDP's bucketed backward hooks).

All functions are pure and shard_map-friendly: with ``axis_name=None``
(world 1) the collectives drop out and the pipeline degenerates to
local compress/decompress — the single-process form the NumPy oracle
tests check bit-for-bit.

SPMD lockstep contract: the collective schedule here — ``2 * chunks``
``all_to_all`` + ``2 * chunks`` ``all_gather`` calls per exchange, in
plan order — depends only on the :class:`CommPlan` (static at trace
time) and NEVER on gradient values or the process index. Every
``if axis_name is not None`` guard branches on a host-static, so all
processes take the same path; data-dependent branching around a
collective is the multi-host hang the linter's JG012/JG014 flag and
``analysis/spmd.py``'s lockstep checker (CI ``spmd-lockstep``,
``cli lint --spmd``) verifies against at world 2/4/8. Keep any future
collective on the unconditional path or mirrored across branches.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .bitpack import WORD_BITS, pack_bits, unpack_bits

MODES = ("none", "sign", "sign_ef")


def _signs(x: jnp.ndarray) -> jnp.ndarray:
    """±1 with the pack_bits convention (bit = 1 ⟺ value > 0): the
    residual math must quantize exactly the way peers decode, or the
    error feedback would track a value nobody applied."""
    return jnp.where(x > 0, 1.0, -1.0).astype(x.dtype)


@dataclass(frozen=True)
class CommPlan:
    """Static shape/byte accounting for one compressed exchange.

    Byte counts use the standard ring-collective model (the convention
    DDP bucket accounting uses): per worker and per step, a ring
    all-reduce of D fp32 values moves ``2*(N-1)/N * 4*D`` bytes, and
    each compressed phase moves ``(N-1)/N`` of one worker's full
    compressed message (planes + scales). They are derived from the
    actual packed-array sizes, not measured on the NIC — XLA exposes no
    portable wire counter — and ``tests/test_comm_compress.py`` pins
    them to the real buffer ``nbytes``.
    """

    mode: str           # "sign" | "sign_ef" | "fp32" (uncompressed DP)
    world: int          # data-parallel workers
    n_params: int       # true flattened gradient length D
    bucket_size: int    # elements per scale bucket (multiple of 32)
    chunks: int         # independent overlap groups over the bucket axis
    nb: int             # buckets per segment
    padded: int         # world * nb * bucket_size >= n_params
    layout: str = "dp"  # "dp" (replicated update) | "fsdp" (ZeRO: the
                        # segment owner runs the optimizer, phase 2
                        # broadcasts the 1-bit update delta) — labels
                        # telemetry; the byte model is identical

    @property
    def seg(self) -> int:
        return self.nb * self.bucket_size

    @property
    def words(self) -> int:
        return self.bucket_size // WORD_BITS

    @property
    def message_bytes(self) -> int:
        """One worker's full compressed gradient: sign planes + scales."""
        return self.padded // 8 + 4 * self.world * self.nb

    @property
    def fp32_bytes_per_step(self) -> int:
        """Ring all-reduce cost of the uncompressed fp32 gradient."""
        return int(2 * (self.world - 1) / max(self.world, 1)
                   * 4 * self.n_params)

    @property
    def wire_bytes_rs(self) -> int:
        """Reduce-scatter-phase bytes per worker per step: (N-1)/N of
        one full message (compressed modes: the all_to_all of sign
        planes + scales; fp32: the RS half of the ring all-reduce, which
        is also what a GSPMD FSDP gradient reduce-scatter moves)."""
        if self.world <= 1:
            return 0
        if self.mode == "fp32":
            return int((self.world - 1) / self.world * 4 * self.n_params)
        return int((self.world - 1) / self.world * self.message_bytes)

    @property
    def wire_bytes_ag(self) -> int:
        """All-gather-phase bytes per worker per step (compressed: the
        broadcast of the owner's recompressed segment — under 'fsdp'
        layout that segment is the 1-bit update delta replacing the
        fp32 param all-gather; fp32: the AG half of the pair)."""
        return self.wire_bytes_per_step - self.wire_bytes_rs

    @property
    def wire_bytes_per_step(self) -> int:
        if self.world <= 1:
            return 0
        if self.mode == "fp32":
            return self.fp32_bytes_per_step
        # phase 1 all_to_all + phase 2 all_gather, each (N-1)/N of one
        # full message per worker
        return int(2 * (self.world - 1) / self.world * self.message_bytes)

    @property
    def saved_bytes_per_step(self) -> int:
        return max(self.fp32_bytes_per_step - self.wire_bytes_per_step, 0)

    @property
    def wire_ratio(self) -> Optional[float]:
        """Wire bytes as a fraction of the fp32 exchange (None when
        there is no exchange to compare against)."""
        if self.fp32_bytes_per_step == 0:
            return None
        return self.wire_bytes_per_step / self.fp32_bytes_per_step


def make_plan(
    n_params: int,
    *,
    world: int,
    mode: str,
    bucket_size: int = 1024,
    chunks: int = 4,
    layout: str = "dp",
) -> CommPlan:
    """Size the segment/bucket layout for a D-element gradient.

    ``bucket_size`` must be a multiple of 32 so sign planes pack into
    whole int32 words with no cross-bucket masking."""
    if mode not in ("sign", "sign_ef", "fp32"):
        raise ValueError(
            f"unknown compression mode {mode!r} "
            "(have: sign, sign_ef, fp32)"
        )
    if layout not in ("dp", "fsdp"):
        raise ValueError(
            f"unknown comm layout {layout!r} (have: dp, fsdp)"
        )
    if bucket_size <= 0 or bucket_size % WORD_BITS:
        raise ValueError(
            f"bucket_size must be a positive multiple of {WORD_BITS}, "
            f"got {bucket_size}"
        )
    world = max(int(world), 1)
    nb = max(-(-n_params // (world * bucket_size)), 1)
    chunks = max(min(int(chunks), nb), 1)
    return CommPlan(
        mode=mode, world=world, n_params=int(n_params),
        bucket_size=int(bucket_size), chunks=chunks, nb=nb,
        padded=world * nb * bucket_size, layout=layout,
    )


def compress_buckets(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sign-compress bucketed values: x (..., B) -> (planes (..., B/32)
    int32, scale (...,) = mean |x| fp32). ``decompress_buckets`` of the
    result is ``scale * signs(x)`` exactly."""
    scale = jnp.mean(jnp.abs(x), axis=-1)
    return pack_bits(x), scale


def decompress_buckets(
    planes: jnp.ndarray, scale: jnp.ndarray, bucket_size: int
) -> jnp.ndarray:
    """Inverse of compress_buckets: (..., B/32) planes + (...,) scales
    -> (..., B) values ``scale * sign``."""
    return unpack_bits(planes, bucket_size) * scale[..., None]


def _chunk_slices(plan: CommPlan):
    """The bucket-axis slices of the independent overlap groups: no
    chunk's ops depend on a neighbor's, so XLA's async collectives
    overlap chunk i's all_to_all/all_gather with chunk i+1's packing
    compute."""
    per = -(-plan.nb // plan.chunks)
    for c in range(plan.chunks):
        sl = slice(c * per, min((c + 1) * per, plan.nb))
        if sl.start >= plan.nb:
            return
        yield sl


def reduce_scatter_compressed(
    flat: jnp.ndarray,
    plan: CommPlan,
    *,
    axis_name: Optional[str],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Phase 1 alone — the 1-bit compressed reduce-scatter.

    flat: (plan.padded,) this worker's (error-corrected) gradient.

    Returns ``(own, sent)``:
      own:  (plan.seg,) the combined global gradient for the segment
            THIS worker owns (the quantity a ZeRO owner feeds its
            sharded optimizer — FSDP layout stops here and phase 2
            carries the update delta instead);
      sent: (plan.padded,) what this worker's phase-1 message decodes
            to — the quantity worker error feedback subtracts.

    With ``axis_name=None`` (world 1) the all_to_all is identity and
    this is local compress/combine.
    """
    world, nb, B = plan.world, plan.nb, plan.bucket_size
    x = flat.reshape(world, nb, B)
    own, sent = [], []
    for sl in _chunk_slices(plan):
        xc = x[:, sl]                               # (world, nbc, B)
        planes, scale = compress_buckets(xc)
        sent.append(decompress_buckets(planes, scale, B))
        if axis_name is not None:
            # worker j receives every worker's planes for segment j
            # (compressed reduce-scatter).
            planes = jax.lax.all_to_all(
                planes, axis_name, split_axis=0, concat_axis=0
            )
            scale = jax.lax.all_to_all(
                scale, axis_name, split_axis=0, concat_axis=0
            )
        if plan.mode == "sign":
            # Bernstein majority vote on raw signs; magnitude = mean of
            # the contributed bucket scales (constant per bucket, so the
            # phase-2 recompression is exact).
            votes = jnp.sum(unpack_bits(planes, B), axis=0)
            y = _signs(votes) * jnp.mean(scale, axis=0)[..., None]
        else:
            contrib = decompress_buckets(planes, scale, B)
            y = jnp.mean(contrib, axis=0)           # (nbc, B)
        own.append(y)
    own_flat = jnp.concatenate(own, axis=0).reshape(plan.seg)
    sent_flat = jnp.concatenate(sent, axis=1).reshape(plan.padded)
    return own_flat, sent_flat


def all_gather_compressed(
    seg: jnp.ndarray,
    plan: CommPlan,
    *,
    axis_name: Optional[str],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Phase 2 alone — the 1-bit compressed all-gather/broadcast.

    seg: (plan.seg,) this worker's owned-segment values (the combined
    gradient under DP layout; the optimizer's update delta under FSDP
    layout, where this broadcast REPLACES the fp32 all-gather of
    updated param shards).

    Returns ``(full, own_dec)``:
      full:    (plan.padded,) the decoded broadcast, identical on every
               worker;
      own_dec: (plan.seg,) what this worker's own segment decodes to —
               the quantity the owner-side error feedback subtracts.
    """
    nb, B = plan.nb, plan.bucket_size
    y = seg.reshape(nb, B)
    full, own_dec = [], []
    for sl in _chunk_slices(plan):
        planes, scale = compress_buckets(y[sl])
        dec = decompress_buckets(planes, scale, B)   # (nbc, B)
        own_dec.append(dec)
        if axis_name is not None:
            planes = jax.lax.all_gather(planes, axis_name, axis=0)
            scale = jax.lax.all_gather(scale, axis_name, axis=0)
            dec_full = decompress_buckets(planes, scale, B)
        else:
            dec_full = dec[None]                     # (1, nbc, B)
        full.append(dec_full)
    full_flat = jnp.concatenate(full, axis=1).reshape(plan.padded)
    own_flat = jnp.concatenate(own_dec, axis=0).reshape(plan.seg)
    return full_flat, own_flat


def exchange(
    flat: jnp.ndarray,
    plan: CommPlan,
    *,
    axis_name: Optional[str],
    e2: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, Optional[jnp.ndarray]]:
    """Run the two-phase compressed exchange on a padded flat gradient
    (``reduce_scatter_compressed`` -> owner residual -> ``all_gather_
    compressed`` — the DP composition; the FSDP path interposes the
    sharded optimizer update between the phases instead, see
    train/optim.sign_compress_fsdp).

    flat: (plan.padded,) this worker's (error-corrected) gradient.
    e2:   (plan.seg,) this worker's segment-owner residual (sign_ef
          mode; None for majority/sign mode).

    Returns ``(combined, sent, e2_new)``:
      combined: (plan.padded,) the decoded global update, identical on
                every worker (all inputs to the final decode came off
                the same all_gather);
      sent:     (plan.padded,) what THIS worker's phase-1 message decodes
                to — the quantity worker error feedback subtracts;
      e2_new:   (plan.seg,) updated segment residual (None in sign mode).

    With ``axis_name=None`` (world 1) both collectives are identity and
    the function reduces to local compress/decompress.
    """
    y, sent = reduce_scatter_compressed(flat, plan, axis_name=axis_name)
    if e2 is not None:
        y = y + e2
    combined, own_dec = all_gather_compressed(y, plan, axis_name=axis_name)
    e2_new = None if e2 is None else y - own_dec
    return combined, sent, e2_new


# -- two-level hierarchical exchange ---------------------------------------
#
# The flat exchange above treats every pair of workers as equally far
# apart. A multi-host cluster is not like that: devices within a host
# share a fast interconnect (ICI / shared memory) while hosts see each
# other over the slow commodity link the source paper trained across.
# The hierarchical form spends the 1-bit budget only where it buys
# wall-clock: a plain fp32 ring reduce over the intra-host 'local' mesh
# axis (cheap, exact), then the two-phase compressed exchange over the
# inter-host axis only. One error-feedback pair per HOST (not per
# device) — every device on a host holds the identical post-pmean
# gradient, so the host's EF rows are replicated over 'local' and
# sharded over the host axis, exactly the layout
# parallel/fsdp.compressed_state_specs already produces.


@dataclass(frozen=True)
class HierPlan:
    """Static accounting for one two-level (hosts x local) exchange.

    ``inter`` is an ordinary :class:`CommPlan` sized for ``hosts``
    workers — the compressed half of the hierarchy reuses the flat
    machinery verbatim, it just runs over the host axis. ``local`` is
    the intra-host fanout whose fp32 ring reduce precedes it.
    """

    inter: CommPlan     # compressed plan over the inter-host axis
    local: int          # devices per host ('local' mesh axis size)

    @property
    def hosts(self) -> int:
        return self.inter.world

    @property
    def world(self) -> int:
        return self.hosts * self.local

    @property
    def intra_bytes_per_step(self) -> int:
        """fp32 ring all-reduce over the local axis, per device per
        step: ``2*(L-1)/L * 4*D`` — the fast-link half."""
        if self.local <= 1:
            return 0
        return int(
            2 * (self.local - 1) / self.local * 4 * self.inter.n_params
        )

    @property
    def inter_bytes_per_step(self) -> int:
        """1-bit two-phase exchange over the host axis, per host per
        step — the slow-link half, the number that sets wall-clock."""
        return self.inter.wire_bytes_per_step

    @property
    def flat_fp32_bytes_per_step(self) -> int:
        """What a flat fp32 ring all-reduce over the FULL world would
        move per worker — the baseline both levels are judged against."""
        if self.world <= 1:
            return 0
        return int(
            2 * (self.world - 1) / self.world * 4 * self.inter.n_params
        )

    @property
    def inter_ratio_vs_flat_fp32(self) -> Optional[float]:
        """Slow-link bytes as a fraction of the flat fp32 ring at the
        same world — the perf-gated band (<= 1/8 by acceptance)."""
        if self.flat_fp32_bytes_per_step == 0:
            return None
        return self.inter_bytes_per_step / self.flat_fp32_bytes_per_step


def make_hier_plan(
    n_params: int,
    *,
    hosts: int,
    local: int,
    mode: str,
    bucket_size: int = 1024,
    chunks: int = 4,
    layout: str = "dp",
) -> HierPlan:
    """Size the two-level layout: a flat compressed plan over ``hosts``
    segment owners, plus the ``local`` intra-host fanout."""
    if local < 1:
        raise ValueError(f"local must be >= 1, got {local}")
    inter = make_plan(
        n_params, world=hosts, mode=mode,
        bucket_size=bucket_size, chunks=chunks, layout=layout,
    )
    return HierPlan(inter=inter, local=int(local))


def hier_exchange(
    flat: jnp.ndarray,
    hier: HierPlan,
    *,
    host_axis: Optional[str],
    local_axis: Optional[str],
    e2: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, Optional[jnp.ndarray]]:
    """Two-level exchange: fp32 pmean over ``local_axis`` (the in-host
    ring reduce), then the two-phase 1-bit exchange over ``host_axis``.

    flat: (hier.inter.padded,) this DEVICE's padded flat gradient —
    after the local pmean every device on a host carries the identical
    host-mean gradient, so the compressed half runs redundantly but
    identically across a host's devices (same schedule, same bits).

    Return contract matches :func:`exchange`; ``sent`` is what this
    HOST's phase-1 message decodes to (the quantity the per-host error
    feedback subtracts). With both axes None the whole thing degenerates
    to the local compress/decompress the NumPy oracles pin down.
    """
    if local_axis is not None:
        flat = jax.lax.pmean(flat, local_axis)
    return exchange(flat, hier.inter, axis_name=host_axis, e2=e2)


def pad_flat(flat: jnp.ndarray, plan: CommPlan) -> jnp.ndarray:
    """Zero-pad the true-D flat gradient to the plan's padded length
    (zero pads decode to -1 * scale-of-a-partly-real-bucket; they are
    sliced off before unraveling, and the worker residual keeps the
    tail's quantization error from accumulating silently)."""
    return jnp.pad(flat, (0, plan.padded - plan.n_params))


def tree_size(tree: Any) -> int:
    """Flattened element count of a pytree (the D a plan is sized for)."""
    return sum(
        int(leaf.size) for leaf in jax.tree.leaves(tree)
        if hasattr(leaf, "size")
    )
