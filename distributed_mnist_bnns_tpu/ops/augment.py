"""Device-side image augmentation — runs INSIDE the jitted train step.

No reference counterpart (its transforms are normalize-only,
mnist-dist2.py:96-99); included because the CIFAR stretch configs
(XNOR-ResNets) need crop/flip augmentation to train to competitive
accuracy, and on TPU the right place for it is the device: a pad +
per-sample dynamic-slice crop + lax flip fuses into the step program, so
augmentation costs no host work and composes with the scan /
device-resident dispatch paths (train/trainer.py) — the torchvision
RandomCrop(padding=4) + RandomHorizontalFlip recipe, functionally.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_crop_flip(
    images: jnp.ndarray, key: jax.Array, *, pad: int = 4
) -> jnp.ndarray:
    """Per-sample random shifted crop (zero padding) + horizontal flip.

    images: (B, H, W, C); returns the same shape. Each sample draws its
    own crop offset in [0, 2*pad] per spatial axis and its own flip coin.
    """
    b, h, w, c = images.shape
    ky, kx, kf = jax.random.split(key, 3)
    padded = jnp.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0))
    )
    oy = jax.random.randint(ky, (b,), 0, 2 * pad + 1)
    ox = jax.random.randint(kx, (b,), 0, 2 * pad + 1)

    def crop(img, oy, ox):
        return jax.lax.dynamic_slice(img, (oy, ox, 0), (h, w, c))

    out = jax.vmap(crop)(padded, oy, ox)
    flip = jax.random.bernoulli(kf, 0.5, (b,))
    return jnp.where(flip[:, None, None, None], out[:, :, ::-1, :], out)
