"""Binary (±1) matrix multiply with selectable TPU backends.

This is the performance core: the role cuDNN/ATen's fp32 GEMM plays for the
reference (nn.functional.linear on ±1 values, models/binarized_modules.py:80)
is played here by one of:

  * "xla"         — fp32 jnp.dot of the ±1 values (correctness oracle; what
                    the reference effectively computes).
  * "bf16"        — cast ±1 to bfloat16 and hit the MXU with fp32
                    accumulation. ±1 is exactly representable in bf16, so
                    this is bit-exact w.r.t. the fp32 oracle while running at
                    MXU bf16 rate.
  * "int8"        — cast ±1 to int8 and hit the MXU's int8 pipeline with
                    int32 accumulation (peak int8 rate is 2x bf16 on
                    v4/v5e). Exact: a ±1 dot over K <= 2^31 fits int32.
  * "xnor"        — int32 bitplane XNOR+popcount GEMM written in pure
                    jax.numpy (XLA-compiled; also the CPU-runnable oracle for
                    the Pallas kernel).
  * "pallas_xnor" — the hand-written Pallas TPU kernel (bitplanes in VMEM,
                    popcount on the VPU, fori_loop over packed-K).

All backends are exact (no approximation): a ±1 dot product is an integer
with |dot| <= K <= 2^24, representable in fp32/int32.

Gradients: `binary_matmul` carries a custom_vjp whose backward is the pair of
fp32 matmuls (g @ w^T, x^T @ g) on the ±1 operands — the same gradients the
reference's autograd computes through nn.functional.linear on binarized
values (SURVEY §3.2).
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp

from .bitpack import WORD_BITS, pack_bits_mxu as pack_bits

Backend = Literal["xla", "bf16", "int8", "xnor", "pallas_xnor"]

BACKENDS = ("xla", "bf16", "int8", "xnor", "pallas_xnor")

_DEFAULT_BACKEND: Backend = "bf16"


def set_default_backend(backend: Backend) -> None:
    global _DEFAULT_BACKEND
    if backend not in BACKENDS:
        raise ValueError(f"unknown backend {backend!r}")
    _DEFAULT_BACKEND = backend


def get_default_backend() -> Backend:
    return _DEFAULT_BACKEND


# ---------------------------------------------------------------------------
# XNOR-popcount GEMM — pure-jnp reference implementation
# ---------------------------------------------------------------------------


def _xnor_matmul_jnp(x_pm1: jnp.ndarray, w_pm1: jnp.ndarray) -> jnp.ndarray:
    """(M, K) @ (K, N) on ±1 values via bitplanes, in pure jax.numpy."""
    k = x_pm1.shape[-1]
    xp = pack_bits(x_pm1)                 # (M, KW) int32
    wp = pack_bits(w_pm1.T)               # (N, KW) int32
    xor = jnp.bitwise_xor(xp[:, None, :], wp[None, :, :])        # (M, N, KW)
    mismatches = jnp.sum(
        jax.lax.population_count(xor), axis=-1, dtype=jnp.int32
    )
    return (k - 2 * mismatches).astype(jnp.float32)


# ---------------------------------------------------------------------------
# XNOR-popcount GEMM — Pallas TPU kernel
# ---------------------------------------------------------------------------


def _xnor_kernel(x_ref, wt_ref, o_ref, *, real_k: int):
    """One (bm, bn, k-chunk) grid step: o -= 2 * sum_w popcount(x ^ w).

    x_ref:  (bm, kc) int32 packed activations for this K chunk
    wt_ref: (kc, bn) int32 packed weights, *K-major* (pre-transposed on the
            host side so each packed word of w is a natural lane vector)

    The packed-K reduction is the *innermost grid dimension* (sequential on
    TPU), revisiting the same output tile: step 0 seeds ``o = real_k`` and
    every step subtracts twice its chunk's mismatch count. Mosaic supports
    this accumulation pattern natively, whereas slicing a loaded tile with
    a loop-carried offset (dynamic_slice on values) does not lower.

    Within the block, the all-pairs XOR is a statically unrolled loop of
    rank-1 outer products — a (bm, 1) lane-broadcast of x's word column XOR
    a (1, bn) sublane-broadcast of w's word row (the same broadcast pattern
    attention kernels use for row-max expansion). Every temporary is a 2-D
    (bm, bn) int32 vreg tile, so nothing gets lane-padded and VMEM stays at
    O(bm*kc + kc*bn + bm*bn). fp32 accumulation is exact: |o| <= K <= 2^24.
    """
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _seed():
        o_ref[...] = jnp.full(o_ref.shape, float(real_k), jnp.float32)

    x = x_ref[...]
    wt = wt_ref[...]
    kc = x.shape[-1]
    bm, bn = o_ref.shape
    mism = jnp.zeros((bm, bn), jnp.int32)
    for t in range(kc):
        xc = jax.lax.slice_in_dim(x, t, t + 1, axis=1)    # (bm, 1)
        wr = jax.lax.slice_in_dim(wt, t, t + 1, axis=0)   # (1, bn)
        mism += jax.lax.population_count(
            jnp.bitwise_xor(
                jnp.broadcast_to(xc, (bm, bn)),
                jnp.broadcast_to(wr, (bm, bn)),
            )
        )
    o_ref[...] -= (2 * mism).astype(jnp.float32)


def _xnor_sign_kernel(
    x_ref, wt_ref, a_ref, t_ref, b_ref, o_ref, *, real_k: int, k_steps: int
):
    """``_xnor_kernel`` with the BN→threshold→sign epilogue fused in: after
    the last K chunk's accumulation the tile becomes
    ``where(a * (y + bias) >= t, +1, -1)`` — the frozen serving path's
    ``binarize(hardtanh(BN(y + bias)))`` (infer._bn_sign_fn) without ever
    writing the (M, N) fp32 pre-activation to HBM.

    Per-column encoding (built by infer._bn_sign_epilogue):
      g > 0:  a = +1, t = theta        (y >= theta)
      g < 0:  a = -1, t = -theta       (y <= theta)
      g == 0: a =  0, t = -sign-const  (0 >= -c picks the constant ±1)
    """
    from jax.experimental import pallas as pl

    _xnor_kernel(x_ref, wt_ref, o_ref, real_k=real_k)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _epilogue():
        y = o_ref[...] + b_ref[...]
        pos = a_ref[...] * y >= t_ref[...]
        o_ref[...] = jnp.where(pos, 1.0, -1.0)


def _xnor_affine_kernel(
    x_ref, wt_ref, a_ref, c_ref, b_ref, o_ref, *, real_k: int, k_steps: int
):
    """``_xnor_kernel`` with the eval-BN affine + hardtanh epilogue fused:
    after the last K chunk the tile becomes
    ``clip(a * (y + bias) + c, -1, 1)`` — the frozen path's
    ``hardtanh(BN(y + bias))`` feeding an fp32 head (infer._bn_affine_fn
    followed by the clip), without the (M, N) fp32 HBM round trip."""
    from jax.experimental import pallas as pl

    _xnor_kernel(x_ref, wt_ref, o_ref, real_k=real_k)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _epilogue():
        y = a_ref[...] * (o_ref[...] + b_ref[...]) + c_ref[...]
        o_ref[...] = jnp.clip(y, -1.0, 1.0)


@functools.partial(
    jax.jit, static_argnames=("k", "n", "block_m", "block_n", "interpret")
)
def xnor_matmul_packed_affine(
    x_pm1: jnp.ndarray,
    w_packed: jnp.ndarray,
    k: int,
    n: int,
    avec: jnp.ndarray,
    cvec: jnp.ndarray,
    bias: jnp.ndarray,
    *,
    block_m: int = 256,
    block_n: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """(M, K) ±1 @ pre-packed weights with the eval-BN affine + hardtanh
    clip fused: returns ``clip(a*(y+bias)+c, -1, 1)`` ready for an fp32
    head — the final-block form of frozen MLP serving (the sign form is
    ``xnor_matmul_packed_sign``)."""
    xp, wtp, lay = _prep_packed_operands(
        x_pm1, w_packed, k, n, block_m, block_n
    )
    return _packed_pallas_call(
        functools.partial(
            _xnor_affine_kernel, real_k=k, k_steps=lay.k_steps
        ),
        lay, xp, wtp,
        [_pad_cols(avec, lay), _pad_cols(cvec, lay), _pad_cols(bias, lay)],
        interpret,
    )


def _pad_cols(vec, lay, fill=0.0):
    """(N,) per-column epilogue vector -> (1, N_p) padded block row."""
    return jnp.pad(
        vec.astype(jnp.float32), (0, lay.np_ - lay.n),
        constant_values=fill,
    ).reshape(1, lay.np_)


def _packed_pallas_call(kernel_fn, lay, xp, wtp, extra, interpret):
    """The one pallas_call shared by every packed entry point: (x, w)
    blocks plus any number of per-column (1, bn) epilogue rows. All
    layout/grid decisions live in ``_prep_packed_operands`` so a tiling
    fix lands everywhere at once (the round-4 K-grid bug was exactly a
    divergence of this scaffolding)."""
    from jax.experimental import pallas as pl

    col = pl.BlockSpec((1, lay.bn), lambda i, j, kk: (0, j))
    out = pl.pallas_call(
        kernel_fn,
        out_shape=jax.ShapeDtypeStruct((lay.mp, lay.np_), jnp.float32),
        grid=(lay.mp // lay.bm, lay.np_ // lay.bn, lay.k_steps),
        in_specs=[
            pl.BlockSpec((lay.bm, lay.kc), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((lay.kc, lay.bn), lambda i, j, kk: (kk, j)),
            *([col] * len(extra)),
        ],
        out_specs=pl.BlockSpec((lay.bm, lay.bn), lambda i, j, kk: (i, j)),
        interpret=interpret,
    )(xp, wtp, *extra)
    return out[: lay.m, : lay.n]


class _PackedLayout:
    """Block/grid layout shared by the packed-kernel entry points."""

    def __init__(self, m, n, bm, bn, mp, np_, kc, k_steps):
        self.m, self.n = m, n
        self.bm, self.bn = bm, bn
        self.mp, self.np_ = mp, np_
        self.kc, self.k_steps = kc, k_steps


def _prep_packed_operands(x_pm1, w_packed, k, n, block_m, block_n):
    """Shared operand prep for ``xnor_matmul_packed`` /
    ``xnor_matmul_packed_sign``: pack the activations, pad both operands
    to the kernel's block layout, and compute the grid.

    The packed-K axis becomes the innermost (sequential) grid dimension.
    Mosaic requires the last block dim to be 128-divisible or equal to
    the whole array dim, so: one chunk of the full packed-K when it is
    small, otherwise 128-word (4096-bit) chunks. Zero words pad *both*
    operands (equal bits -> zero extra mismatches -> the popcount formula
    stays exact), and the K grid covers the PADDED extent (``kw_p``, not
    ``kw`` — a partial final chunk, e.g. K=4160 -> 130 words, must still
    be visited; zero-padding keeps it exact)."""
    m, k2 = x_pm1.shape
    assert k == k2, (x_pm1.shape, k)

    bm = min(block_m, max(8, m))
    bn = min(block_n, max(128, n))
    mp = -(-m // bm) * bm

    xp = pack_bits(x_pm1)                     # (M, KW)
    wtp = w_packed                            # (KW_p, N_p)  K-major
    kw = xp.shape[-1]
    kc = kw if kw <= 128 else 128
    # Padded dims: at least the kernel layout, and at least whatever
    # layout the weights were prepacked with (a larger block_n at prepack
    # time is fine — the extra zero columns are sliced off by callers).
    kw_p = -(-max(kw, wtp.shape[0]) // kc) * kc
    np_ = -(-max(n, wtp.shape[1]) // bn) * bn
    if kw_p != kw:
        xp = jnp.pad(xp, ((0, 0), (0, kw_p - kw)))
    if mp != m:
        xp = jnp.pad(xp, ((0, mp - m), (0, 0)))
    if (kw_p, np_) != wtp.shape:  # unpadded/legacy layout: pad per call
        wtp = jnp.pad(
            wtp,
            ((0, kw_p - wtp.shape[0]), (0, np_ - wtp.shape[1])),
        )
    return xp, wtp, _PackedLayout(m, n, bm, bn, mp, np_, kc, kw_p // kc)


@functools.partial(
    jax.jit, static_argnames=("k", "n", "block_m", "block_n", "interpret")
)
def xnor_matmul_packed_sign(
    x_pm1: jnp.ndarray,
    w_packed: jnp.ndarray,
    k: int,
    n: int,
    avec: jnp.ndarray,
    tvec: jnp.ndarray,
    bias: jnp.ndarray,
    *,
    block_m: int = 256,
    block_n: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """(M, K) ±1 @ pre-packed weights with the threshold-sign epilogue
    fused: returns ±1 activations ready for the next packed layer. Saves
    the unfused path's full (M, N) fp32 round trip through HBM — the
    dominant extra traffic of bandwidth-bound frozen serving at large
    offline batches. ``avec``/``tvec``/``bias`` are (N,) per-output-column
    epilogue params (see ``_xnor_sign_kernel``)."""
    xp, wtp, lay = _prep_packed_operands(
        x_pm1, w_packed, k, n, block_m, block_n
    )
    # Padding columns: a=0, t=+1 -> "0 >= 1" false -> -1, sliced off.
    return _packed_pallas_call(
        functools.partial(
            _xnor_sign_kernel, real_k=k, k_steps=lay.k_steps
        ),
        lay, xp, wtp,
        [
            _pad_cols(avec, lay),
            _pad_cols(tvec, lay, fill=1.0),
            _pad_cols(bias, lay),
        ],
        interpret,
    )


def prepack_weights(
    w_pm1: jnp.ndarray, block_n: int = 256
) -> tuple[jnp.ndarray, int, int]:
    """Pack a ±1 (K, N) weight matrix into the kernel's K-major bitplane
    layout once, for reuse across many ``xnor_matmul_packed`` calls.

    This is the inference fast path: packed weights occupy K/32 the HBM of
    bf16 weights, so small-batch (bandwidth-bound) GEMMs skip both the
    per-call weight pack and 32x of the weight traffic. The output is
    padded to the kernel's block layout (128-word K chunks, ``block_n``
    columns — pass the same block_n as the matmul call) so the hot path
    never copies the weights. Returns (packed (KW_p, N_p) int32, k, n)."""
    k, n = w_pm1.shape
    wtp = pack_bits(w_pm1.T).T
    kw = wtp.shape[0]
    kw_p = kw if kw <= 128 else -(-kw // 128) * 128
    bn = min(block_n, max(128, n))
    np_ = -(-n // bn) * bn
    if (kw_p, np_) != wtp.shape:
        wtp = jnp.pad(wtp, ((0, kw_p - kw), (0, np_ - n)))
    return wtp, k, n


@functools.partial(
    jax.jit, static_argnames=("k", "n", "block_m", "block_n", "interpret")
)
def xnor_matmul_packed(
    x_pm1: jnp.ndarray,
    w_packed: jnp.ndarray,
    k: int,
    n: int,
    *,
    block_m: int = 256,
    block_n: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """(M, K) ±1 activations @ pre-packed weights (see prepack_weights)."""
    xp, wtp, lay = _prep_packed_operands(
        x_pm1, w_packed, k, n, block_m, block_n
    )
    return _packed_pallas_call(
        functools.partial(_xnor_kernel, real_k=k),
        lay, xp, wtp, [], interpret,
    )


# ---------------------------------------------------------------------------
# Fused bitplane-unpack GEMM — packed weights straight into the MXU
# ---------------------------------------------------------------------------


def _fused_unpack_kernel(x_ref, wt_ref, o_ref):
    """One (bm, bn, k-chunk) grid step of ``x @ unpack(w_packed)``: the
    (kc, bn) packed-word tile is expanded to its (kc*32, bn) ±1 bitplane
    IN VMEM (never written back to HBM) and hit with one dot per step.

    x_ref:  (bm, kc*32) fp32 activations for this K chunk
    wt_ref: (kc, bn) int32 packed weights, K-major (prepack_weights)

    Unpack matches ``bitpack.unpack_bits`` exactly: bit b of word kw is
    K index kw*32 + b (LSB-first), bit 1 -> +1, bit 0 -> -1. Zero-padded
    K words therefore unpack to -1 columns — neutralized by the zero
    rows the entry point pads onto x, so the formula stays exact. The
    packed-K axis is the innermost (sequential) grid dimension revisiting
    the output tile, seeded at step 0 — the same accumulation scaffold
    as ``_xnor_kernel``. fp32 accumulation of ±1 dots is exact
    (integers, |o| <= K <= 2^24) in any blocking order.
    """
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    words = wt_ref[...]                       # (kc, bn) int32
    kc, bn = words.shape
    shifts = jax.lax.broadcasted_iota(jnp.int32, (kc, WORD_BITS, bn), 1)
    bits = jnp.right_shift(words[:, None, :], shifts) & 1
    w = (2 * bits - 1).astype(jnp.float32).reshape(kc * WORD_BITS, bn)
    o_ref[...] += jax.lax.dot_general(
        x_ref[...], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


@functools.partial(
    jax.jit, static_argnames=("k", "n", "block_m", "block_n", "interpret")
)
def xnor_matmul_fused_unpack(
    x: jnp.ndarray,
    w_packed: jnp.ndarray,
    k: int,
    n: int,
    *,
    block_m: int = 256,
    block_n: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """(M, K) fp32 activations @ pre-packed ±1 weights with the bitplane
    unpack fused into the GEMM's K loop.

    The decode-hot-path alternative to ``unpack_bits`` + ``jnp.dot``:
    weights cross HBM packed (1/32 byte/param) and expand to ±1 only
    inside VMEM, one (kc*32, bn) tile at a time — the unpacked (K, N)
    weight matrix never exists in HBM. On the ±1 activation domain the
    result is bitwise-equal to unpack-then-GEMM (both are exact integer
    sums in fp32). ``w_packed`` is ``prepack_weights`` layout; ``x`` may
    be any real-valued fp32 (the packed-x popcount path is
    ``xnor_matmul_packed``).

    K chunks are 8 words (256 bits) so the in-VMEM bitplane tile stays
    small; when the whole packed K fits in 8 words it is one chunk.
    """
    m, k2 = x.shape
    assert k == k2, (x.shape, k)
    bm = min(block_m, max(8, m))
    bn = min(block_n, max(128, n))
    mp = -(-m // bm) * bm
    wtp = w_packed
    kw = -(-k // WORD_BITS)
    kw_real = max(kw, wtp.shape[0])
    kc = kw_real if kw_real <= 8 else 8
    kw_p = -(-kw_real // kc) * kc
    np_ = -(-max(n, wtp.shape[1]) // bn) * bn
    if (kw_p, np_) != wtp.shape:
        wtp = jnp.pad(
            wtp,
            ((0, kw_p - wtp.shape[0]), (0, np_ - wtp.shape[1])),
        )
    xf = x.astype(jnp.float32)
    if (mp, kw_p * WORD_BITS) != xf.shape:
        xf = jnp.pad(xf, ((0, mp - m), (0, kw_p * WORD_BITS - k)))

    from jax.experimental import pallas as pl

    out = pl.pallas_call(
        _fused_unpack_kernel,
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        grid=(mp // bm, np_ // bn, kw_p // kc),
        in_specs=[
            pl.BlockSpec((bm, kc * WORD_BITS), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((kc, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        interpret=bool(interpret),
    )(xf, wtp)
    return out[:m, :n]


@functools.partial(jax.jit, static_argnames=("block_m", "block_n", "interpret"))
def xnor_matmul(
    x_pm1: jnp.ndarray,
    w_pm1: jnp.ndarray,
    *,
    block_m: int = 256,
    block_n: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """(M, K) @ (K, N) on ±1 values via the Pallas XNOR-popcount kernel.

    Pads M and N up to block multiples (padding rows/cols are ±1 garbage and
    sliced off), packs K into int32 words zero-padded so the popcount formula
    stays exact (see bitpack.py docstring). Packs both operands per call —
    for fixed weights (inference) use prepack_weights + xnor_matmul_packed."""
    k, n = w_pm1.shape
    w_packed, _, _ = prepack_weights(w_pm1)
    return xnor_matmul_packed(
        x_pm1, w_packed, k, n,
        block_m=block_m, block_n=block_n, interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Unified differentiable entry point
# ---------------------------------------------------------------------------


def _forward(x_pm1, w_pm1, backend, interpret):
    if backend == "xla":
        return jnp.dot(x_pm1, w_pm1, preferred_element_type=jnp.float32)
    if backend == "bf16":
        return jnp.dot(
            x_pm1.astype(jnp.bfloat16),
            w_pm1.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    if backend == "int8":
        return jnp.dot(
            x_pm1.astype(jnp.int8),
            w_pm1.astype(jnp.int8),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
    if backend == "xnor":
        return _xnor_matmul_jnp(x_pm1, w_pm1)
    if backend == "pallas_xnor":
        return xnor_matmul(x_pm1, w_pm1, interpret=interpret)
    raise ValueError(f"unknown backend {backend!r}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def binary_matmul(
    x_pm1: jnp.ndarray,
    w_pm1: jnp.ndarray,
    backend: Backend | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Differentiable ±1 matmul: forward on the chosen backend, backward as
    bf16 MXU matmuls of the ±1 operands (exact, since operands are ±1 and
    cotangents are fp32 — accumulation is fp32)."""
    return _forward(x_pm1, w_pm1, backend or _DEFAULT_BACKEND, interpret)


def _bmm_fwd(x_pm1, w_pm1, backend, interpret):
    return _forward(x_pm1, w_pm1, backend or _DEFAULT_BACKEND, interpret), (
        x_pm1,
        w_pm1,
    )


def _bmm_bwd(backend, interpret, res, g):
    x_pm1, w_pm1 = res
    gx = jnp.dot(g, w_pm1.T.astype(g.dtype), preferred_element_type=jnp.float32)
    gw = jnp.dot(x_pm1.T.astype(g.dtype), g, preferred_element_type=jnp.float32)
    return gx.astype(x_pm1.dtype), gw.astype(w_pm1.dtype)


binary_matmul.defvjp(_bmm_fwd, _bmm_bwd)


# ---------------------------------------------------------------------------
# Differentiable conv for the dense (MXU) backends
# ---------------------------------------------------------------------------


def _conv_fwd_impl(x, w, strides, padding, dtype):
    acc = jnp.int32 if dtype == jnp.int8 else jnp.float32
    return jax.lax.conv_general_dilated(
        x.astype(dtype),
        w.astype(dtype),
        window_strides=strides,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=acc,
    ).astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def binary_conv2d(
    x: jnp.ndarray,
    w: jnp.ndarray,
    strides: tuple = (1, 1),
    padding="SAME",
    dtype=jnp.bfloat16,
):
    """NHWC conv on ±1 (or raw first-layer) values: forward on the MXU in
    ``dtype`` with fp32 accumulation, backward as the fp32 conv VJP.

    The explicit VJP exists because JAX's transpose rule for a mixed-dtype
    conv (bf16 operands, fp32 preferred_element_type output) rejects the
    fp32 cotangent against the bf16 operands; computing the backward as the
    VJP of the equivalent fp32 conv sidesteps that while keeping the exact
    gradients the reference's autograd produces through conv2d on binarized
    values (models/binarized_modules.py:97-104, SURVEY §3.2). Exactness of
    the forward: ±1 operands are exactly representable in bf16 and the MXU
    accumulates in fp32, so dense-backend conv outputs are integers, exact
    for |dot| <= 2^24.
    """
    return _conv_fwd_impl(x, w, strides, padding, dtype)


def _bconv_fwd(x, w, strides, padding, dtype):
    return _conv_fwd_impl(x, w, strides, padding, dtype), (x, w)


def _bconv_bwd(strides, padding, dtype, res, g):
    x, w = res
    _, vjp = jax.vjp(
        lambda xx, ww: _conv_fwd_impl(xx, ww, strides, padding, jnp.float32),
        x.astype(jnp.float32),
        w.astype(jnp.float32),
    )
    gx, gw = vjp(g.astype(jnp.float32))
    return gx.astype(x.dtype), gw.astype(w.dtype)


binary_conv2d.defvjp(_bconv_fwd, _bconv_bwd)


def conv_patch_weight(wb: jnp.ndarray) -> jnp.ndarray:
    """(kh, kw, cin, F) conv kernel -> the (kh*kw*cin, F) GEMM matrix in
    ``jax.lax.conv_general_dilated_patches`` feature order (channel-major:
    patches flatten as (cin, kh, kw)).

    THE canonical ordering for the im2col binarized-conv path — shared by
    the training layer (models/layers.py BinarizedConv) and the frozen
    serving path (infer_conv.py), so the two cannot drift."""
    kh, kw, cin, f = wb.shape
    return jnp.transpose(wb, (2, 0, 1, 3)).reshape(kh * kw * cin, f)


def conv_padding_correction(
    tap_sums: jnp.ndarray,
    in_hw: tuple,
    strides: tuple,
    padding="SAME",
) -> jnp.ndarray:
    """Zero-padding correction for an im2col ±1 conv GEMM.

    Padded border taps enter the bitplane GEMM as -1 (pack_bits maps
    x > 0 to bit 1) instead of contributing nothing; the spurious
    subtraction per output position is ``sum_all(w) - sum_in_bounds(w)``.
    Only the per-tap channel sums matter, so ``tap_sums`` is the kernel
    summed over its input channels, shape (kh, kw, F) — which is also all
    a frozen artifact needs to ship (the dense (Ho, Wo, F) map rebuilds
    here, ~cin*Ho*Wo/(kh*kw) times smaller on disk). Returns
    (1, Ho, Wo, F); exactly zero in the interior. Shared by BinarizedConv
    and the frozen conv serving path."""
    ones = jnp.ones((1, *in_hw, 1), jnp.float32)
    valid = jax.lax.conv_general_dilated(
        ones,
        tap_sums[:, :, None, :].astype(jnp.float32),
        window_strides=tuple(strides),
        padding=padding if isinstance(padding, str)
        else tuple(tuple(p) for p in padding),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )  # (1, Ho, Wo, F): sum of w over in-bounds taps
    total = jnp.sum(tap_sums, axis=(0, 1))
    return total[None, None, None, :] - valid
