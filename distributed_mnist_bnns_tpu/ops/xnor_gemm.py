"""Binary (±1) matrix multiply with selectable TPU backends.

This is the performance core: the role cuDNN/ATen's fp32 GEMM plays for the
reference (nn.functional.linear on ±1 values, models/binarized_modules.py:80)
is played here by one of:

  * "xla"         — fp32 jnp.dot of the ±1 values (correctness oracle; what
                    the reference effectively computes).
  * "bf16"        — cast ±1 to bfloat16 and hit the MXU with fp32
                    accumulation. ±1 is exactly representable in bf16, so
                    this is bit-exact w.r.t. the fp32 oracle while running at
                    MXU bf16 rate. Usually the fastest path at MNIST sizes.
  * "xnor"        — int32 bitplane XNOR+popcount GEMM written in pure
                    jax.numpy (XLA-compiled; also the CPU-runnable oracle for
                    the Pallas kernel).
  * "pallas_xnor" — the hand-written Pallas TPU kernel (bitplanes in VMEM,
                    popcount on the VPU, fori_loop over packed-K).

All backends are exact (no approximation): a ±1 dot product is an integer
with |dot| <= K <= 2^24, representable in fp32/int32.

Gradients: `binary_matmul` carries a custom_vjp whose backward is the pair of
fp32 matmuls (g @ w^T, x^T @ g) on the ±1 operands — the same gradients the
reference's autograd computes through nn.functional.linear on binarized
values (SURVEY §3.2).
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp

from .bitpack import WORD_BITS, pack_bits

Backend = Literal["xla", "bf16", "xnor", "pallas_xnor"]

_DEFAULT_BACKEND: Backend = "bf16"


def set_default_backend(backend: Backend) -> None:
    global _DEFAULT_BACKEND
    if backend not in ("xla", "bf16", "xnor", "pallas_xnor"):
        raise ValueError(f"unknown backend {backend!r}")
    _DEFAULT_BACKEND = backend


def get_default_backend() -> Backend:
    return _DEFAULT_BACKEND


# ---------------------------------------------------------------------------
# XNOR-popcount GEMM — pure-jnp reference implementation
# ---------------------------------------------------------------------------


def _xnor_matmul_jnp(x_pm1: jnp.ndarray, w_pm1: jnp.ndarray) -> jnp.ndarray:
    """(M, K) @ (K, N) on ±1 values via bitplanes, in pure jax.numpy."""
    k = x_pm1.shape[-1]
    xp = pack_bits(x_pm1)                 # (M, KW) int32
    wp = pack_bits(w_pm1.T)               # (N, KW) int32
    xor = jnp.bitwise_xor(xp[:, None, :], wp[None, :, :])        # (M, N, KW)
    mismatches = jnp.sum(
        jax.lax.population_count(xor), axis=-1, dtype=jnp.int32
    )
    return (k - 2 * mismatches).astype(jnp.float32)


# ---------------------------------------------------------------------------
# XNOR-popcount GEMM — Pallas TPU kernel
# ---------------------------------------------------------------------------


def _xnor_kernel(
    x_ref, w_ref, o_ref, *, k_words: int, real_k: int, k_chunk: int = 8
):
    """One (bm, bn) output tile: o = real_k - 2 * sum_w popcount(x ^ w).

    x_ref: (bm, KW) int32 packed activations
    w_ref: (bn, KW) int32 packed weights (N-major, packed along K)

    The packed-K reduction runs on the VPU in chunks of ``k_chunk`` words:
    each iteration XOR+popcounts a (bm, bn, k_chunk) broadcast and reduces
    the chunk axis — fatter vector ops (and fewer loop trips) than a
    per-word loop, while keeping the temporary well under VMEM limits
    (bm*bn*k_chunk*4B = 512KB at 128x128x8).
    """
    x = x_ref[...]
    w = w_ref[...]
    bm, bn = o_ref.shape
    n_chunks = -(-k_words // k_chunk)

    def body(i, acc):
        start = i * k_chunk
        xw = jax.lax.dynamic_slice_in_dim(x, start, k_chunk, axis=1)
        ww = jax.lax.dynamic_slice_in_dim(w, start, k_chunk, axis=1)
        mism = jax.lax.population_count(
            jnp.bitwise_xor(xw[:, None, :], ww[None, :, :])  # (bm, bn, kc)
        )
        return acc + jnp.sum(mism, axis=-1)

    acc = jax.lax.fori_loop(
        0, n_chunks, body, jnp.zeros((bm, bn), jnp.int32)
    )
    o_ref[...] = (real_k - 2 * acc).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n", "interpret"))
def xnor_matmul(
    x_pm1: jnp.ndarray,
    w_pm1: jnp.ndarray,
    *,
    block_m: int = 256,
    block_n: int = 256,
    interpret: bool = False,
) -> jnp.ndarray:
    """(M, K) @ (K, N) on ±1 values via the Pallas XNOR-popcount kernel.

    Pads M and N up to block multiples (padding rows/cols are ±1 garbage and
    sliced off), packs K into int32 words zero-padded so the popcount formula
    stays exact (see bitpack.py docstring).
    """
    from jax.experimental import pallas as pl

    m, k = x_pm1.shape
    k2, n = w_pm1.shape
    assert k == k2, (x_pm1.shape, w_pm1.shape)

    bm = min(block_m, max(8, m))
    bn = min(block_n, max(128, n))
    mp = -(-m // bm) * bm
    np_ = -(-n // bn) * bn

    # Pad packed-K to a multiple of the kernel's chunk so every
    # dynamic_slice in the reduction is in-bounds; zero words pad *both*
    # operands (equal bits -> zero extra mismatches -> formula stays exact).
    xp = pack_bits(x_pm1, pad_words_to=8)    # (M, KW)
    wp = pack_bits(w_pm1.T, pad_words_to=8)  # (N, KW)
    kw = xp.shape[-1]
    if mp != m:
        xp = jnp.pad(xp, ((0, mp - m), (0, 0)))
    if np_ != n:
        wp = jnp.pad(wp, ((0, np_ - n), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_xnor_kernel, k_words=kw, real_k=k),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec((bm, kw), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, kw), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        interpret=interpret,
    )(xp, wp)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# Unified differentiable entry point
# ---------------------------------------------------------------------------


def _forward(x_pm1, w_pm1, backend, interpret):
    if backend == "xla":
        return jnp.dot(x_pm1, w_pm1, preferred_element_type=jnp.float32)
    if backend == "bf16":
        return jnp.dot(
            x_pm1.astype(jnp.bfloat16),
            w_pm1.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    if backend == "xnor":
        return _xnor_matmul_jnp(x_pm1, w_pm1)
    if backend == "pallas_xnor":
        return xnor_matmul(x_pm1, w_pm1, interpret=interpret)
    raise ValueError(f"unknown backend {backend!r}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def binary_matmul(
    x_pm1: jnp.ndarray,
    w_pm1: jnp.ndarray,
    backend: Backend | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Differentiable ±1 matmul: forward on the chosen backend, backward as
    bf16 MXU matmuls of the ±1 operands (exact, since operands are ±1 and
    cotangents are fp32 — accumulation is fp32)."""
    return _forward(x_pm1, w_pm1, backend or _DEFAULT_BACKEND, interpret)


def _bmm_fwd(x_pm1, w_pm1, backend, interpret):
    return _forward(x_pm1, w_pm1, backend or _DEFAULT_BACKEND, interpret), (
        x_pm1,
        w_pm1,
    )


def _bmm_bwd(backend, interpret, res, g):
    x_pm1, w_pm1 = res
    gx = jnp.dot(g, w_pm1.T.astype(g.dtype), preferred_element_type=jnp.float32)
    gw = jnp.dot(x_pm1.T.astype(g.dtype), g, preferred_element_type=jnp.float32)
    return gx.astype(x_pm1.dtype), gw.astype(w_pm1.dtype)


binary_matmul.defvjp(_bmm_fwd, _bmm_bwd)
