"""MoE routing math — dispatch/combine construction and the router's
load-balancing objective.

Pure-ops (no parallel/ or train/ dependencies) so both the model
families (models/moe.py) and the expert-parallel deployment
(parallel/expert_parallel.py) use one definition of routing; the latter
re-exports these names for its public API.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def top1_dispatch(
    gates: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 routing with capacity-bounded one-hot dispatch.

    gates: (T, E) router probabilities. Returns (dispatch, combine), both
    (T, E, C): dispatch is the 0/1 token->slot assignment (tokens beyond
    ``capacity`` per expert are dropped, in token order); combine is
    dispatch scaled by the chosen expert's gate probability.
    """
    t, e = gates.shape
    expert_idx = jnp.argmax(gates, axis=-1)                      # (T,)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=gates.dtype)    # (T, E)
    # 1-based arrival position of each token within its chosen expert.
    pos = jnp.cumsum(onehot, axis=0) * onehot                    # (T, E)
    keep = (pos > 0) & (pos <= capacity)
    slot = jnp.where(keep, pos - 1, 0).astype(jnp.int32)
    dispatch = (
        keep.astype(gates.dtype)[..., None]
        * jax.nn.one_hot(slot, capacity, dtype=gates.dtype)      # (T, E, C)
    )
    gate_val = jnp.sum(gates * onehot, axis=-1)                  # (T,)
    combine = gate_val[:, None, None] * dispatch
    return dispatch, combine


def topk_dispatch(
    gates: jnp.ndarray, capacity: int, k: int = 2
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k routing (GShard top-2 by default) with capacity bounds.

    Each token sends to its k highest-gate experts; combine weights are
    the chosen gates renormalized over the k choices. Expert slots fill
    choice-major (everyone's first choice before anyone's second), each
    choice in token order; tokens past ``capacity`` drop that choice.
    Returns (dispatch, combine), both (T, E, C)."""
    t, e = gates.shape
    if k < 1 or k > e:
        raise ValueError(f"top-k needs 1 <= k <= {e}, got {k}")
    remaining = gates
    chosen = []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(idx, e, dtype=gates.dtype)
        chosen.append((jnp.sum(gates * onehot, axis=-1), onehot))
        remaining = remaining - onehot * 2.0  # probs <= 1: never re-chosen
    denom = sum(gv for gv, _ in chosen) + 1e-9
    counts = jnp.zeros((e,), gates.dtype)  # kept slots used per expert
    dispatch = jnp.zeros((t, e, capacity), gates.dtype)
    combine = jnp.zeros((t, e, capacity), gates.dtype)
    for gv, onehot in chosen:
        pos = jnp.cumsum(onehot, axis=0) * onehot + counts[None, :] * onehot
        keep = (pos > 0) & (pos <= capacity)
        slot = jnp.where(keep, pos - 1, 0).astype(jnp.int32)
        d_j = (
            keep.astype(gates.dtype)[..., None]
            * jax.nn.one_hot(slot, capacity, dtype=gates.dtype)
        )
        dispatch = dispatch + d_j
        combine = combine + (gv / denom)[:, None, None] * d_j
        counts = counts + jnp.sum(keep.astype(gates.dtype) * onehot, axis=0)
    return dispatch, combine


def load_balance_loss(gates: jnp.ndarray) -> jnp.ndarray:
    """Switch-Transformer auxiliary load-balancing loss.

    ``E * sum_e f_e * p_e`` with f_e the fraction of tokens whose top-1
    choice is expert e and p_e the mean router probability of e; equals
    1.0 at perfect balance, grows as routing collapses. Differentiable
    through p (f's argmax is piecewise constant), which is what pushes
    the router toward balance."""
    t, e = gates.shape
    top1 = jax.nn.one_hot(jnp.argmax(gates, axis=-1), e, dtype=gates.dtype)
    f = jnp.mean(top1, axis=0)
    p = jnp.mean(gates, axis=0)
    return e * jnp.sum(f * p)
