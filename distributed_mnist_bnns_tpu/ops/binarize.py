"""Binarization / quantization primitives with straight-through-estimator (STE)
gradients, as jax.custom_vjp transforms.

Capability parity with the reference's ``Binarize``/``Quantize``
(reference: models/binarized_modules.py:11-15, 56-63), with the STE expressed
functionally instead of via the reference's weight.data-swap trick
(reference: mnist-dist2.py:131-137 restores fp32 masters before the optimizer
step so autograd's "identity through sign" gradient lands on the fp32 weights).

Design notes (TPU-first):
  * Pure functions of arrays — no in-place mutation (the reference binarizes
    caller activations in place, models/binarized_modules.py:76; a purely
    functional graph places ``binarize`` at the layer input, which reproduces
    the training dynamics without the aliasing hazard).
  * ``sign(0)`` maps to +1 here (the reference's torch ``.sign()`` maps 0 to
    0). Strict ±1 outputs are required for the bitplane XNOR-popcount backend
    to be exact; the measure-zero difference is irrelevant to training and is
    covered by a numerics test.
  * Everything is jit/vmap/grad-compatible and shape-polymorphic, so XLA can
    fuse the sign into neighbouring ops.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp

STEMode = Literal["identity", "hardtanh"]


def _sign_pm1(x: jnp.ndarray) -> jnp.ndarray:
    """sign() with outputs in {-1, +1} (0 -> +1), dtype preserved."""
    return jnp.where(x >= 0, jnp.ones_like(x), -jnp.ones_like(x))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def binarize_ste(x: jnp.ndarray, ste: STEMode = "identity") -> jnp.ndarray:
    """Deterministic sign binarization with an STE gradient.

    ste="identity": backward is the identity — exactly the gradient the
        reference training loop realizes for *weights* (autograd never sees
        the sign op because weight.data is swapped; mnist-dist2.py:131-137).
    ste="hardtanh": backward masks gradients where |x| > 1 — the standard
        BNN STE (Courbariaux et al.); in the reference this role is played
        by the Hardtanh activations placed before each binarized layer
        (mnist-dist2.py:51-74).
    """
    return _sign_pm1(x)


def _binarize_fwd(x, ste):
    return _sign_pm1(x), (x if ste == "hardtanh" else None)


def _binarize_bwd(ste, res, g):
    x = res
    if ste == "hardtanh":
        g = g * (jnp.abs(x) <= 1.0).astype(g.dtype)
    return (g,)


binarize_ste.defvjp(_binarize_fwd, _binarize_bwd)


def binarize(
    x: jnp.ndarray,
    quant_mode: str = "det",
    *,
    ste: STEMode = "identity",
    key: jax.Array | None = None,
) -> jnp.ndarray:
    """Binarize to ±1, deterministic or stochastic.

    Parity with reference ``Binarize(tensor, quant_mode)``
    (models/binarized_modules.py:11-15):
      det:   sign(x)
      stoch: shift to [0,1] via (x+1)/2, add U(-0.5, 0.5) noise, clamp to
             [0,1], round, map back to {-1,+1}.

    The stochastic path requires an explicit PRNG ``key`` (JAX is functional;
    the reference used torch's global RNG). Gradients for both paths are the
    STE gradient of ``binarize_ste``.
    """
    if quant_mode == "det":
        return binarize_ste(x, ste)
    if key is None:
        raise ValueError("stochastic binarize requires a PRNG key")
    noise = jax.random.uniform(key, x.shape, x.dtype, -0.5, 0.5)
    # Straight-through: forward uses the noisy rounding, backward is the STE.
    det = binarize_ste(x, ste)
    probs = jnp.clip((x + 1.0) / 2.0 + noise, 0.0, 1.0)
    stoch = jnp.round(probs) * 2.0 - 1.0
    return det + jax.lax.stop_gradient(stoch - det)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _quantize_ste(x: jnp.ndarray, num_bits: int) -> jnp.ndarray:
    scale = 2.0 ** (num_bits - 1)
    bound = scale
    xc = jnp.clip(x * scale, -bound, bound - 1)
    return jnp.round(xc) / scale


def _quantize_fwd(x, num_bits):
    return _quantize_ste(x, num_bits), None


def _quantize_bwd(num_bits, res, g):
    return (g,)


_quantize_ste.defvjp(_quantize_fwd, _quantize_bwd)


def quantize(
    x: jnp.ndarray,
    quant_mode: str = "det",
    num_bits: int = 8,
    *,
    key: jax.Array | None = None,
) -> jnp.ndarray:
    """k-bit fixed-point quantization with an identity-STE gradient.

    Parity with reference ``Quantize`` (models/binarized_modules.py:56-63):
    clamp to the signed 2^(b-1) range, scale-round-rescale. The reference's
    stochastic branch calls an undefined ``quant_fixed`` (dead/buggy,
    models/binarized_modules.py:62); here the stochastic path is implemented
    properly as additive-uniform-noise rounding.
    """
    if quant_mode == "det":
        return _quantize_ste(x, num_bits)
    if key is None:
        raise ValueError("stochastic quantize requires a PRNG key")
    scale = 2.0 ** (num_bits - 1)
    noise = jax.random.uniform(key, x.shape, x.dtype, -0.5, 0.5)
    det = _quantize_ste(x, num_bits)
    stoch = jnp.round(jnp.clip(x * scale + noise, -scale, scale - 1)) / scale
    return det + jax.lax.stop_gradient(stoch - det)
