"""Loss functions: hinge, squared ("sqrt") hinge, cross entropy.

Parity with the reference's HingeLoss / SqrtHingeLossFunction
(models/binarized_modules.py:20-54) and the CrossEntropyLoss used by every
training loop (e.g. mnist-dist2.py:90). The reference's SqrtHingeLossFunction
has a live pdb.set_trace() in its backward (models/binarized_modules.py:50),
making it unusable; here the same math is implemented as a custom_vjp with the
reference's handwritten gradient, minus the debugger.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def hinge_loss(output: jnp.ndarray, target_pm1: jnp.ndarray) -> jnp.ndarray:
    """Margin-1 hinge: mean(max(0, 1 - output * target)).

    ``target_pm1`` is ±1-coded (the reference's HingeLoss contract,
    models/binarized_modules.py:20-32).
    """
    return jnp.mean(jnp.maximum(0.0, 1.0 - output * target_pm1))


@jax.custom_vjp
def sqrt_hinge_loss(output: jnp.ndarray, target_pm1: jnp.ndarray) -> jnp.ndarray:
    """Squared hinge: mean over batch of sum(max(0, 1 - y*t)^2).

    Mirrors the forward of reference SqrtHingeLossFunction
    (models/binarized_modules.py:34-46): per-sample sum of squared hinge
    terms, averaged over the batch, with the reference's handwritten backward
    (minus its pdb.set_trace(), :50).
    """
    err = jnp.maximum(0.0, 1.0 - output * target_pm1)
    batch = output.shape[0] if output.ndim > 0 else 1
    return jnp.sum(err * err) / batch


def _sqrt_hinge_fwd(output, target_pm1):
    err = jnp.maximum(0.0, 1.0 - output * target_pm1)
    batch = output.shape[0] if output.ndim > 0 else 1
    return jnp.sum(err * err) / batch, (err, target_pm1, batch)


def _sqrt_hinge_bwd(res, g):
    err, target_pm1, batch = res
    # d/d_output of sum((1 - y*t)_+^2)/B = -2 * t * err / B
    grad_out = -2.0 * target_pm1 * err / batch * g
    return grad_out, jnp.zeros_like(target_pm1)


sqrt_hinge_loss.defvjp(_sqrt_hinge_fwd, _sqrt_hinge_bwd)


def make_loss(name: str, num_classes: int = 10, label_smoothing: float = 0.0):
    """Loss registry for the trainer: 'ce' (the reference training loops),
    'hinge' / 'sqrt_hinge' (the reference's HingeLoss / SqrtHingeLoss
    modules, models/binarized_modules.py:20-54, which take ±1-coded
    targets — integer labels are one-hot ±1 encoded here).

    ``label_smoothing`` (ce only) mixes the one-hot target with the
    uniform distribution — a per-sample mean loss, so the masked-eval and
    grad-accum exactness properties are preserved."""
    if label_smoothing and name != "ce":
        raise ValueError("label_smoothing only applies to the 'ce' loss")
    if name == "ce":
        if not label_smoothing:
            return cross_entropy_loss
        if not 0.0 < label_smoothing < 1.0:
            raise ValueError(
                f"label_smoothing must be in (0, 1), got {label_smoothing}"
            )

        def smoothed(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
            target = optax.smooth_labels(
                jax.nn.one_hot(labels, num_classes), label_smoothing
            )
            return optax.softmax_cross_entropy(logits, target).mean()

        return smoothed
    if name in ("hinge", "sqrt_hinge"):
        base = hinge_loss if name == "hinge" else sqrt_hinge_loss

        def loss(outputs: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
            target_pm1 = 2.0 * jax.nn.one_hot(labels, num_classes) - 1.0
            return base(outputs, target_pm1)

        return loss
    raise ValueError(f"unknown loss {name!r}; available: ce, hinge, sqrt_hinge")


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross entropy over integer labels.

    Equivalent of nn.CrossEntropyLoss (mnist-dist2.py:90). The reference's
    BNN MLP ends in LogSoftmax *and* is trained with CrossEntropyLoss (a
    double-log-softmax quirk, mnist-dist2.py:75,90,124 — harmless because
    log_softmax is shift-invariant and idempotent up to normalization); we
    accept either logits or log-probabilities for the same reason.
    """
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
