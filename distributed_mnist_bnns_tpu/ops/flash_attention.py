"""Flash attention as a Pallas TPU kernel.

No reference counterpart (the reference has no attention at all, SURVEY §5
"long-context: absent"); this is the on-chip half of the framework's
long-context story. parallel/ring_attention.py scales sequence length
*across* chips (K/V stream over ICI with online-softmax accumulation);
this kernel is the same online-softmax algorithm *within* a chip: Q blocks
stay resident in VMEM, K/V blocks stream through as the innermost
(sequential) grid dimension, and the running (max, denom, accumulator)
carry lives in VMEM scratch that persists across those grid steps — so the
(Lq, Lk) score matrix never materializes in HBM.

Exactness: same math as softmax(QK^T)V with fp32 accumulation; the only
difference from the naive oracle is reassociation of the exp/sum, the
standard flash rescaling.

Backward: custom_vjp with a K-chunked fp32 recompute driven by the
forward's saved (out, lse) — the flash-attention backward identity
  ds = p * (do.v - rowsum(do*o) + g_lse),  p = exp(s - lse)
evaluated one K block at a time under lax.scan, accumulating dq and
emitting per-block dk/dv. Peak memory is O(Lq * block) per step, never
the (Lq, Lk) score matrix — training memory stays linear in sequence
length, matching the forward (the long-context requirement the
flash+ring stack exists for).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite stand-in for -inf (keeps exp() NaN-free)


def _pick_block(n: int, cap: int, align: int) -> tuple[int, int]:
    """Choose a Mosaic-aligned block size for a length-n axis.

    Returns (block, padded_n): ``block`` is a multiple of ``align`` (the
    Mosaic tile granularity for that axis — 8 sublanes for the q axis, 128
    lanes for the k axis) and ``padded_n`` is the multiple of ``block`` the
    axis must be padded to. Never emits an unaligned block for awkward
    lengths (e.g. L=7 -> block 8 with one padded row, not block 7)."""
    if n % align == 0:
        for cand in (512, 256, 128, 64, 32, 16, 8):
            if cand <= cap and cand % align == 0 and n % cand == 0:
                return cand, n
    block = max(align, min(cap, -(-n // align) * align))
    return block, -(-n // block) * block


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, scale: float, causal: bool, n_kblocks: int, causal_offset: int,
    real_lk: int, mask_pad_k: bool
):
    """One (batch*head, q-block, k-block) grid step.

    Scratch (persists across the sequential k-block axis):
      m_ref  (bq, 1)  running row max
      l_ref  (bq, 1)  running softmax denominator
      acc_ref(bq, d)  running output numerator
    """
    from jax.experimental import pallas as pl

    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _reset():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0]  # (bq, d)
    k = k_ref[0]  # (bk, d)
    v = v_ref[0]  # (bk, d)
    bq, bk = q.shape[0], k.shape[0]

    # HIGHEST precision: on TPU the default fp32 matmul is a single bf16
    # MXU pass (~1e-3 relative error); HIGHEST keeps fp32 operands exact
    # and costs nothing for bf16 operands.
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ) * scale  # (bq, bk)
    if causal or mask_pad_k:
        k_pos = kk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if mask_pad_k:
        # Zero-padded key rows (alignment padding) must not attend.
        s = jnp.where(k_pos < real_lk, s, NEG_INF)
    if causal:
        # Bottom-right alignment for Lq != Lk (matching jnp.tril with
        # k = Lk - Lq): query row i attends keys [0, i + Lk - Lq].
        # Positions use the *real* lengths (padding sits at the end).
        q_pos = pl.program_id(1) * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0
        )
        s = jnp.where(k_pos <= q_pos + causal_offset, s, NEG_INF)

    m_prev = m_ref[...]
    l_prev = l_ref[...]
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    # Guard fully-masked blocks: with every score at NEG_INF, m_new stays
    # NEG_INF and exp(s - m_new) would be exp(0)=1; zero those explicitly.
    p = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
    corr = jnp.where(
        m_prev > NEG_INF / 2, jnp.exp(m_prev - m_new), 0.0
    )
    m_ref[...] = m_new
    l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )

    @pl.when(kk == n_kblocks - 1)
    def _finalize():
        l = l_ref[...]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = acc_ref[...] / safe_l
        # log-sum-exp of this device's scores per q row — what a ring-level
        # merge needs to combine per-shard results exactly.
        lse_ref[0] = jnp.where(
            l == 0.0, NEG_INF, m_ref[...] + jnp.log(safe_l)
        )


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_fwd_impl(
    q3: jnp.ndarray, k3: jnp.ndarray, v3: jnp.ndarray,
    *, causal: bool, block_q: int, block_k: int, interpret: bool
) -> jnp.ndarray:
    """(BH, L, D) flash attention."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q3.shape
    lk = k3.shape[1]
    scale = d**-0.5  # real head dim — padding must not change the scale

    # Mosaic-aligned blocks: q rows tile at 8 sublanes; k rows become the
    # lane axis of the (bq, bk) score tile, so they tile at 128 lanes; the
    # head dim is a lane axis of q/k/v tiles — pad it to 128. Padded keys
    # are masked to NEG_INF in-kernel; padded q rows/d columns are sliced
    # off after the call.
    bq, lq_p = _pick_block(lq, block_q, 8)
    bk, lk_p = _pick_block(lk, block_k, 128)
    d_p = -(-d // 128) * 128
    if (lq_p, lk_p, d_p) != (lq, lk, d):
        q3 = jnp.pad(q3, ((0, 0), (0, lq_p - lq), (0, d_p - d)))
        k3 = jnp.pad(k3, ((0, 0), (0, lk_p - lk), (0, d_p - d)))
        v3 = jnp.pad(v3, ((0, 0), (0, lk_p - lk), (0, d_p - d)))
    n_kblocks = lk_p // bk

    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, causal=causal, n_kblocks=n_kblocks,
            causal_offset=lk - lq, real_lk=lk, mask_pad_k=lk_p != lk,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lq_p, d_p), jnp.float32),
            jax.ShapeDtypeStruct((bh, lq_p, 1), jnp.float32),
        ),
        grid=(bh, lq_p // bq, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, bq, d_p), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, bk, d_p), lambda b, i, kk: (b, kk, 0)),
            pl.BlockSpec((1, bk, d_p), lambda b, i, kk: (b, kk, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, d_p), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, kk: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d_p), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    if (lq_p, d_p) != (lq, d):
        out = out[:, :lq, :d]
        lse = lse[:, :lq, :]
    return out, lse


def _oracle_with_lse(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    hi = jax.lax.Precision.HIGHEST  # match the kernel (exact fp32 on TPU)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, precision=hi) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        scores = jnp.where(mask, scores, NEG_INF)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)  # (B, H, Lq)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v, precision=hi)
    return out, lse.transpose(0, 2, 1)  # lse as (B, Lq, H)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas flash attention over (B, L, H, D) inputs.

    Forward streams K/V blocks through VMEM (no (L, L) materialization);
    backward differentiates the fp32 oracle. ``interpret=True`` runs the
    kernel in interpreter mode for CPU tests. Output dtype matches q.
    """
    out, _ = flash_attention_with_lse(q, k, v, causal, interpret)
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    interpret: bool = False,
):
    """Like flash_attention, additionally returning the per-row
    log-sum-exp (B, L, H) — the quantity a cross-device (ring) merge needs
    to combine per-shard attention results exactly. Both outputs stay
    fp32 so cross-shard accumulation keeps full precision (the ring merge
    casts once at the end; flash_attention casts to q.dtype itself).
    Differentiable: the VJP recomputes the fp32 oracle and propagates both
    cotangents, so downstream uses of the lse (e.g. the ring merge
    weights) get exact gradients."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if causal and lq > lk:
        # Bottom-right alignment leaves query rows < Lq-Lk attending to
        # zero keys — an ill-defined softmax (the kernel would emit zeros,
        # the oracle uniform attention); refuse rather than silently
        # diverge.
        raise ValueError(
            f"causal attention requires Lq <= Lk, got Lq={lq} Lk={lk}"
        )
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    o3, lse3 = _flash_fwd_impl(
        q3, k3, v3, causal=causal, block_q=512, block_k=512,
        interpret=interpret,
    )
    out = o3.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    lse = lse3.reshape(b, h, lq).transpose(0, 2, 1)
    return out, lse


def _fa_fwd(q, k, v, causal, interpret):
    out, lse = flash_attention_with_lse(q, k, v, causal, interpret)
    return (out, lse), (q, k, v, out, lse)


# K-block length of the chunked backward. Module-level so tests can force
# multiple chunks at small L; 512 matches the forward kernel's block cap.
_BWD_BLOCK_K = 512

# Block caps of the Pallas backward kernels (same role as the forward's
# block_q/block_k args). Module-level so tests can force multi-block
# grids at small L — the sequential reset/accumulate/finalize streaming
# is the core of both kernels and must be exercised, not just the
# single-block case.
_BWD_PALLAS_BLOCK_Q = 512
_BWD_PALLAS_BLOCK_K = 512

# Backward implementation: "pallas" (on-chip kernels, same blocked
# streaming as the forward) or "chunked" (lax.scan over K blocks in
# plain XLA). Both are linear-memory and tested equal to the oracle;
# pallas is the default hot path, chunked the dependable fallback for a
# platform that miscompiles the kernels. Selectable via the
# FLASH_BWD_IMPL env var, read at import — set it BEFORE any training
# step compiles (the choice is baked into the traced program; flipping
# the module global later does not invalidate jit caches).
import os as _os

_BWD_IMPL = _os.environ.get("FLASH_BWD_IMPL", "pallas").strip().lower()
if _BWD_IMPL not in ("pallas", "chunked"):
    raise ValueError(
        f"FLASH_BWD_IMPL={_BWD_IMPL!r} is not a flash backward "
        "implementation (have: pallas, chunked)"
    )


def _bwd_masks(
    s, lse_blk, q_pos, k_pos, *, causal, causal_offset, real_lq, real_lk
):
    """p = exp(s - lse) with every invalid (padded q row, padded k col,
    causally-masked, no-valid-key row) position forced to exactly 0."""
    invalid = (k_pos >= real_lk) | (q_pos >= real_lq)
    if causal:
        invalid = invalid | (k_pos > q_pos + causal_offset)
    invalid = invalid | (lse_blk < NEG_INF / 2)  # row had no valid keys
    return jnp.where(invalid, 0.0, jnp.exp(s - lse_blk))


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, coeff_ref, dq_ref, acc_ref,
    *, scale, causal, n_kblocks, causal_offset, real_lq, real_lk,
):
    """dq: grid (BH, q-block, k-block sequential). Streams K/V blocks
    against a resident q block, accumulating dq = sum_j ds @ k."""
    from jax.experimental import pallas as pl

    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _reset():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    lse_blk = lse_ref[0]      # (bq, 1)
    coeff = coeff_ref[0]      # (bq, 1) = g_lse - delta
    bq, bk = q.shape[0], k.shape[0]
    hi = jax.lax.Precision.HIGHEST
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hi,
    ) * scale
    q_pos = pl.program_id(1) * bq + jax.lax.broadcasted_iota(
        jnp.int32, (bq, bk), 0
    )
    k_pos = kk * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    p = _bwd_masks(
        s, lse_blk, q_pos, k_pos, causal=causal,
        causal_offset=causal_offset, real_lq=real_lq, real_lk=real_lk,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hi,
    )
    ds = p * (dp + coeff) * scale
    acc_ref[...] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hi,
    )

    @pl.when(kk == n_kblocks - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...]


def _flash_bwd_dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, coeff_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale, causal, n_qblocks, causal_offset, real_lq, real_lk,
):
    """dk/dv: grid (BH, k-block, q-block sequential). Streams Q/dO blocks
    against a resident K/V block: dv = sum_i p^T do, dk = sum_i ds^T q."""
    from jax.experimental import pallas as pl

    qq = pl.program_id(2)

    @pl.when(qq == 0)
    def _reset():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    k, v, q, do = k_ref[0], v_ref[0], q_ref[0], do_ref[0]
    lse_blk = lse_ref[0]
    coeff = coeff_ref[0]
    bq, bk = q.shape[0], k.shape[0]
    hi = jax.lax.Precision.HIGHEST
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hi,
    ) * scale
    q_pos = qq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = pl.program_id(1) * bk + jax.lax.broadcasted_iota(
        jnp.int32, (bq, bk), 1
    )
    p = _bwd_masks(
        s, lse_blk, q_pos, k_pos, causal=causal,
        causal_offset=causal_offset, real_lq=real_lq, real_lk=real_lk,
    )
    # dv += p^T @ do   (contract the q axis of both)
    dv_acc[...] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hi,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hi,
    )
    ds = p * (dp + coeff) * scale
    dk_acc[...] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hi,
    )

    @pl.when(qq == n_qblocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...]
        dv_ref[0] = dv_acc[...]


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_bwd_impl(
    q3, k3, v3, do3, lse3, coeff3,
    *, causal: bool, block_q: int, block_k: int, interpret: bool
):
    """(BH, L, D) flash backward: two Pallas kernels mirroring the
    forward's blocking (q rows tile at 8 sublanes, k rows at 128 lanes,
    head dim padded to 128; padding masked in-kernel)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q3.shape
    lk = k3.shape[1]
    scale = d**-0.5
    bq, lq_p = _pick_block(lq, block_q, 8)
    bk, lk_p = _pick_block(lk, block_k, 128)
    d_p = -(-d // 128) * 128
    if (lq_p, d_p) != (lq, d):
        q3 = jnp.pad(q3, ((0, 0), (0, lq_p - lq), (0, d_p - d)))
        do3 = jnp.pad(do3, ((0, 0), (0, lq_p - lq), (0, d_p - d)))
        lse3 = jnp.pad(lse3, ((0, 0), (0, lq_p - lq), (0, 0)))
        coeff3 = jnp.pad(coeff3, ((0, 0), (0, lq_p - lq), (0, 0)))
    if (lk_p, d_p) != (lk, d):
        k3 = jnp.pad(k3, ((0, 0), (0, lk_p - lk), (0, d_p - d)))
        v3 = jnp.pad(v3, ((0, 0), (0, lk_p - lk), (0, d_p - d)))
    n_qblocks, n_kblocks = lq_p // bq, lk_p // bk
    kw = dict(
        scale=scale, causal=causal, causal_offset=lk - lq,
        real_lq=lq, real_lk=lk,
    )

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, n_kblocks=n_kblocks, **kw
        ),
        out_shape=jax.ShapeDtypeStruct((bh, lq_p, d_p), jnp.float32),
        grid=(bh, n_qblocks, n_kblocks),
        in_specs=[
            pl.BlockSpec((1, bq, d_p), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, bk, d_p), lambda b, i, kk: (b, kk, 0)),
            pl.BlockSpec((1, bk, d_p), lambda b, i, kk: (b, kk, 0)),
            pl.BlockSpec((1, bq, d_p), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, kk: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d_p), lambda b, i, kk: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d_p), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, coeff3)

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, n_qblocks=n_qblocks, **kw
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lk_p, d_p), jnp.float32),
            jax.ShapeDtypeStruct((bh, lk_p, d_p), jnp.float32),
        ),
        grid=(bh, n_kblocks, n_qblocks),
        in_specs=[
            pl.BlockSpec((1, bk, d_p), lambda b, j, qq: (b, j, 0)),
            pl.BlockSpec((1, bk, d_p), lambda b, j, qq: (b, j, 0)),
            pl.BlockSpec((1, bq, d_p), lambda b, j, qq: (b, qq, 0)),
            pl.BlockSpec((1, bq, d_p), lambda b, j, qq: (b, qq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, qq: (b, qq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, qq: (b, qq, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bk, d_p), lambda b, j, qq: (b, j, 0)),
            pl.BlockSpec((1, bk, d_p), lambda b, j, qq: (b, j, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, d_p), jnp.float32),
            pltpu.VMEM((bk, d_p), jnp.float32),
        ],
        interpret=interpret,
    )(k3, v3, q3, do3, lse3, coeff3)

    if (lq_p, d_p) != (lq, d):
        dq = dq[:, :lq, :d]
    if (lk_p, d_p) != (lk, d):
        dk, dv = dk[:, :lk, :d], dv[:, :lk, :d]
    return dq, dk, dv


def _fa_bwd_pallas(causal, interpret, res, g):
    """Pallas-kernel flash backward: same math as the chunked path, on
    the same blocked streaming schedule the forward uses."""
    q, k, v, out, lse = res
    g_out, g_lse = g
    f32 = jnp.float32
    b, lq, h, d = q.shape
    lk = k.shape[1]

    def to3(x, l):  # (B, L, H, D) -> (BH, L, D) fp32
        return x.transpose(0, 2, 1, 3).reshape(b * h, l, d).astype(f32)

    q3, k3, v3 = to3(q, lq), to3(k, lk), to3(v, lk)
    do3, o3 = to3(g_out, lq), to3(out, lq)
    lse3 = lse.transpose(0, 2, 1).reshape(b * h, lq, 1).astype(f32)
    gl3 = g_lse.transpose(0, 2, 1).reshape(b * h, lq, 1).astype(f32)
    delta3 = jnp.sum(do3 * o3, axis=-1, keepdims=True)
    coeff3 = gl3 - delta3
    dq3, dk3, dv3 = _flash_bwd_impl(
        q3, k3, v3, do3, lse3, coeff3,
        causal=causal, block_q=_BWD_PALLAS_BLOCK_Q,
        block_k=_BWD_PALLAS_BLOCK_K, interpret=interpret,
    )

    def back(x3, l, dtype):
        return (
            x3.reshape(b, h, l, d).transpose(0, 2, 1, 3).astype(dtype)
        )

    return (
        back(dq3, lq, q.dtype), back(dk3, lk, k.dtype),
        back(dv3, lk, v.dtype),
    )


def _fa_bwd(causal, interpret, res, g):
    if _BWD_IMPL == "pallas":
        return _fa_bwd_pallas(causal, interpret, res, g)
    return _fa_bwd_chunked(causal, interpret, res, g)


def _fa_bwd_chunked(causal, interpret, res, g):
    """Memory-bounded flash backward from the saved (out, lse).

    With p_ij = exp(s_ij - lse_i) (softmax probabilities, never
    materialized whole) and delta_i = sum_d do_id * o_id:

        dv_j = sum_i p_ij do_i
        ds_ij = p_ij * (do_i . v_j - delta_i + g_lse_i) * scale
        dq_i  = sum_j ds_ij k_j          dk_j = sum_i ds_ij q_i

    (g_lse enters because lse is a second differentiable output:
    d lse_i / d s_ij = p_ij.) The j sums run one K block per lax.scan
    step: per-step live tensors are (Lq, block) — linear-in-L training
    memory, no (Lq, Lk) intermediate anywhere in the backward."""
    del interpret
    q, k, v, out, lse = res
    g_out, g_lse = g
    f32 = jnp.float32
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = d**-0.5
    hi = jax.lax.Precision.HIGHEST

    def bhld(x):  # (B, L, H, D) -> (B, H, L, D) fp32
        return x.transpose(0, 2, 1, 3).astype(f32)

    qt, kt, vt = bhld(q), bhld(k), bhld(v)
    do, o = bhld(g_out), bhld(out)
    lse_t = lse.transpose(0, 2, 1).astype(f32)     # (B, H, Lq)
    gl = g_lse.transpose(0, 2, 1).astype(f32)      # (B, H, Lq)
    delta = jnp.sum(do * o, axis=-1)               # (B, H, Lq)
    coeff = (gl - delta)[..., None]                # (B, H, Lq, 1)

    bk = min(_BWD_BLOCK_K, lk)
    lk_p = -(-lk // bk) * bk
    if lk_p != lk:  # padded keys are masked off via their positions
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, lk_p - lk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, lk_p - lk), (0, 0)))
    n_blocks = lk_p // bk
    # (B, H, n, bk, D) -> (n, B, H, bk, D): scan over the leading axis.
    kc = kt.reshape(b, h, n_blocks, bk, d).transpose(2, 0, 1, 3, 4)
    vc = vt.reshape(b, h, n_blocks, bk, d).transpose(2, 0, 1, 3, 4)
    q_pos = jnp.arange(lq)[:, None]                # (Lq, 1)

    def block(carry, xs):
        dq_acc, blk = carry
        k_blk, v_blk = xs                          # (B, H, bk, D)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, k_blk, precision=hi) * scale
        k_pos = blk * bk + jnp.arange(bk)[None, :]  # (1, bk)
        invalid = k_pos >= lk
        if causal:
            invalid = invalid | (k_pos > q_pos + (lk - lq))
        # Masked (or padding) keys contribute p=0; rows with no valid key
        # have lse=NEG_INF, which must not turn into exp(+inf).
        log_p = s - lse_t[..., None]
        p = jnp.where(
            invalid | (lse_t[..., None] < NEG_INF / 2), 0.0, jnp.exp(log_p)
        )
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, do, precision=hi)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, v_blk, precision=hi)
        ds = p * (dp + coeff) * scale
        dq_acc = dq_acc + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, k_blk, precision=hi
        )
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qt, precision=hi)
        return (dq_acc, blk + 1), (dk_blk, dv_blk)

    (dq, _), (dk_blocks, dv_blocks) = jax.lax.scan(
        block, (jnp.zeros_like(qt), jnp.int32(0)), (kc, vc)
    )
    dk = dk_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, lk_p, d)[:, :, :lk]
    dv = dv_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, lk_p, d)[:, :, :lk]

    def blhd(x, dtype):  # back to (B, L, H, D)
        return x.transpose(0, 2, 1, 3).astype(dtype)

    return blhd(dq, q.dtype), blhd(dk, k.dtype), blhd(dv, v.dtype)


flash_attention_with_lse.defvjp(_fa_fwd, _fa_bwd)
