from .augment import random_crop_flip
from .binarize import binarize, binarize_ste, quantize
from .losses import hinge_loss, sqrt_hinge_loss, cross_entropy_loss, make_loss
from .bitpack import pack_bits, pack_bits_mxu, unpack_bits, packed_dim
from .comm_compress import (
    CommPlan,
    compress_buckets,
    decompress_buckets,
    exchange,
    make_plan,
)
from .flash_attention import flash_attention
from .xnor_gemm import (
    xnor_matmul,
    xnor_matmul_packed,
    prepack_weights,
    binary_matmul,
    binary_conv2d,
    set_default_backend,
    get_default_backend,
)

__all__ = [
    "random_crop_flip",
    "binarize",
    "binarize_ste",
    "quantize",
    "hinge_loss",
    "sqrt_hinge_loss",
    "cross_entropy_loss",
    "make_loss",
    "pack_bits",
    "pack_bits_mxu",
    "unpack_bits",
    "packed_dim",
    "CommPlan",
    "compress_buckets",
    "decompress_buckets",
    "exchange",
    "make_plan",
    "xnor_matmul",
    "xnor_matmul_packed",
    "prepack_weights",
    "binary_matmul",
    "binary_conv2d",
    "flash_attention",
    "set_default_backend",
    "get_default_backend",
]
