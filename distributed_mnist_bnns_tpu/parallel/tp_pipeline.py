"""Three-axis DP x TP x PP composition: Megatron tensor parallelism
INSIDE each pipeline stage, data parallelism across replica rows.

The reference's parallelism never composed (its model-parallel demo was
a bare two-device layer split, mnist-distributed-BNNS2.py:193-213, and
its DP was DDP, mnist-dist2.py:93); this module is the TPU-native
composition of all three axes on one mesh, in the scaling-book style:
pick a ``(data, model, pipe)`` mesh, annotate shardings, let the
collectives ride ICI.

Each pipeline stage is a binarized two-matmul MLP block in the
column->row Megatron layout over ``model_axis``:

    h   = hardtanh(x @ sign(W1_col) + b1_col)     # local: no collective
    y   = psum(h @ sign(W2_row), model_axis) + b2 # one all-reduce/stage

W1 is column-parallel (each model-shard holds hidden/tp columns), W2
row-parallel (hidden/tp rows), so the ONLY model-axis collective is the
single psum of the row-parallel partials — the canonical Megatron
schedule. Weights are binarized via ``ops.binarize`` (STE custom_vjp),
so the composed program differentiates end-to-end like every other
layer in the framework. The stage chain runs through the GPipe ring of
``make_pipeline_fn`` (microbatches ppermute'd over ``pipe`` within
each (data, model) slice), and the batch dim is sharded over ``data``
(stage/TP weights replicated across rows, gradient all-reduce falling
out of the loss mean under jit/GSPMD — same contract as DP x PP).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.binarize import binarize


def init_tp_pipeline_params(
    key: jax.Array, n_stages: int, d_model: int, d_hidden: int
) -> dict:
    """Stage-major (dim 0 = stage) params for the TP-MLP stage chain.

    Full (unsharded) shapes — sharding happens at dispatch via
    ``tp_pipeline_param_specs``: w1 (S, d, h) col-parallel on h,
    b1 (S, h), w2 (S, h, d) row-parallel on h, b2 (S, d) replicated.
    """
    k1, k2 = jax.random.split(key)
    s = 1.0 / jnp.sqrt(d_model)
    return {
        "w1": jax.random.uniform(
            k1, (n_stages, d_model, d_hidden), minval=-s, maxval=s
        ),
        "b1": jnp.zeros((n_stages, d_hidden)),
        "w2": jax.random.uniform(
            k2, (n_stages, d_hidden, d_model), minval=-s, maxval=s
        ),
        "b2": jnp.zeros((n_stages, d_model)),
    }


def tp_pipeline_param_specs(
    axis: str = "pipe", model_axis: str = "model"
) -> dict:
    """Per-leaf shardings: dim 0 = pipeline stage, hidden dim = TP."""
    return {
        "w1": P(axis, None, model_axis),   # column-parallel
        "b1": P(axis, model_axis),
        "w2": P(axis, model_axis, None),   # row-parallel
        "b2": P(axis, None),               # replicated over model
    }


def make_tp_pipeline_fn(
    mesh: Mesh,
    *,
    axis: str = "pipe",
    model_axis: str = "model",
    batch_axis: str | None = "data",
    n_micro: int = 0,
    stage_remat: bool = False,
):
    """f(stage_params, x) -> y: the stage chain pipelined over ``axis``
    with Megatron TP over ``model_axis`` inside every stage and the
    batch sharded over ``batch_axis``. ``stage_params`` leaves are the
    FULL shapes of ``init_tp_pipeline_params``; shard_map slices them
    per ``tp_pipeline_param_specs``."""
    from .pipeline import make_pipeline_fn

    def stage_fn(params, x):
        # local column-parallel matmul: params["w1"] is (d, h/tp) here
        h = jnp.dot(x, binarize(params["w1"])) + params["b1"]
        h = jax.nn.hard_tanh(h)
        partial = jnp.dot(h, binarize(params["w2"]))
        # the one model-axis collective of the Megatron schedule
        return jax.lax.psum(partial, model_axis) + params["b2"]

    return make_pipeline_fn(
        mesh,
        stage_fn,
        axis=axis,
        n_micro=n_micro or mesh.shape[axis],
        batch_axis=batch_axis,
        stage_remat=stage_remat,
        param_specs=tp_pipeline_param_specs(axis, model_axis),
    )


def tp_pipeline_reference(stage_params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Single-device dense oracle (same binarize, unsharded matmuls)."""
    n_stages = stage_params["w1"].shape[0]
    for s in range(n_stages):
        h = jnp.dot(x, binarize(stage_params["w1"][s]))
        h = jax.nn.hard_tanh(h + stage_params["b1"][s])
        x = jnp.dot(h, binarize(stage_params["w2"][s])) + stage_params["b2"][s]
    return x


def latent_mask(stage_params: dict) -> dict:
    """Clamp mask for the latent fp32 masters: binarized weight leaves
    (w*) -> True, biases -> False. Derived from the params keys so a
    new leaf fails loudly in clamp_latent's tree map rather than
    silently drifting out of sync."""
    return {k: k.startswith("w") for k in stage_params}
