"""Ring attention — sequence/context parallelism over a mesh axis.

Not present in the reference (no attention/sequence models there, SURVEY
§5 "long-context: absent"); included because long-context scaling is a
first-class axis of this framework. The design is blockwise ring attention
(Liu et al.): the sequence is sharded over a mesh axis, each device keeps
its Q shard resident and streams K/V shards around the ring with
``lax.ppermute`` (ICI neighbor exchange), accumulating exact softmax
attention via the online (flash) max/sum rescaling — so the result is
bit-for-bit-close to full attention while sequence length scales linearly
with the number of devices.

Shapes: (batch, seq, heads, head_dim); the 'seq' axis shards dim 1.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map


def attention_reference(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *, causal: bool = False
) -> jnp.ndarray:
    """Full softmax attention oracle: (B, L, H, D) -> (B, L, H, D)."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_attn_update(q, k, v, m, l, acc, *, scale, mask=None):
    """One K/V block of online-softmax attention.

    m: running row max (B, H, Lq, 1); l: running denom; acc: running
    numerator (B, Lq, H, D)."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    # guard -inf (fully masked rows) -> exp(0)=1 on zero weights is avoided
    # by the final l division; replace -inf diffs with large negatives.
    p = jnp.exp(scores - m_new)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = (
        acc * jnp.moveaxis(correction, 1, 2)
        + jnp.einsum("bhqk,bkhd->bqhd", p, v)
    )
    return m_new, l_new, acc_new


def make_ring_attention(
    mesh: Mesh, *, axis: str = "seq", causal: bool = False,
    local: str = "dense", interpret: bool = False,
):
    """Build a jitted ring-attention fn over ``mesh``'s ``axis``.

    Returns f(q, k, v) taking globally-shaped arrays sharded on seq
    (placement handled by in_shardings), computing exact attention.
    With causal=True, block masking uses the global positions implied by
    each shard's ring offset.

    ``local`` picks the per-device block computation:
      * "dense" — einsum online-softmax update (always available);
      * "flash" — the Pallas flash kernel (ops/flash_attention.py): each
        ring step computes its K/V shard's attention entirely in VMEM and
        returns (out, lse); shards merge by log-sum-exp rescaling, which
        is algebraically the same online softmax at shard granularity.
        Causal supported: the diagonal ring step runs the causal kernel,
        earlier-position shards attend fully, later ones are skipped.
    """
    n_shards = mesh.shape[axis]
    if local == "flash":
        return _make_ring_flash(mesh, axis, n_shards, causal, interpret)
    if local != "dense":
        raise ValueError(f"unknown local={local!r} (have: dense, flash)")

    def local_fn(q, k, v):
        # per-device shapes: (B, Lloc, H, D)
        scale = q.shape[-1] ** -0.5
        my_idx = jax.lax.axis_index(axis)
        b, lq, h, d = q.shape
        m = jnp.full((b, h, lq, 1), -jnp.inf, q.dtype)
        l = jnp.zeros((b, h, lq, 1), q.dtype)
        acc = jnp.zeros_like(q)
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

        def body(step, carry):
            m, l, acc, k_cur, v_cur = carry
            src_idx = (my_idx - step) % n_shards  # whose K/V we hold now
            if causal:
                q_pos = my_idx * lq + jnp.arange(lq)[:, None]
                k_pos = src_idx * lq + jnp.arange(k_cur.shape[1])[None, :]
                mask = (k_pos <= q_pos)[None, None]
            else:
                mask = None
            m, l, acc = _block_attn_update(
                q, k_cur, v_cur, m, l, acc, scale=scale, mask=mask
            )
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return m, l, acc, k_nxt, v_nxt

        m, l, acc, _, _ = jax.lax.fori_loop(
            0, n_shards, body, (m, l, acc, k, v)
        )
        return acc / jnp.moveaxis(l, 1, 2)

    return _finalize_ring(local_fn, mesh, axis)


def _finalize_ring(local_fn, mesh: Mesh, axis: str):
    """shard_map + jit the per-device ring body, resharding inputs onto
    the seq layout first — a no-op for already-sharded arrays, and the
    reshard that lets callers holding single-device (committed) q/k/v —
    e.g. a model calling this mid-forward — use the ring directly.

    On a 2-D mesh with a 'data' axis (e.g. make_mesh(data=2) x seq=4),
    the batch dim additionally shards over 'data': each data-row runs its
    own independent K/V ring over ICI while batches split across rows —
    simultaneous DP x SP, the long-context scale-out layout."""
    batch_axis = next(
        (a for a in mesh.axis_names if a == "data" and a != axis), None
    )
    seq_sharded = P(batch_axis, axis, None, None)
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(seq_sharded,) * 3,
        out_specs=seq_sharded,
        check_vma=False,
    )
    sh = NamedSharding(mesh, seq_sharded)
    jitted = jax.jit(fn, in_shardings=(sh,) * 3, out_shardings=sh)

    def call(q, k, v):
        return jitted(
            jax.device_put(q, sh), jax.device_put(k, sh),
            jax.device_put(v, sh),
        )

    return call


def _make_ring_flash(
    mesh: Mesh, axis: str, n_shards: int, causal: bool, interpret: bool
):
    """Ring attention with the Pallas flash kernel as the local step.

    Each ring step computes full attention of the resident Q shard against
    the currently-held K/V shard on-chip (ops/flash_attention.py) and
    yields (out_i, lse_i); shards merge via the online log-sum-exp
    rescaling — exp weights are reassociated exactly as in flash itself,
    so the result equals full attention.

    Causal decomposes by ring step (equal shards, K/V from
    ``src = my_idx - step mod n``): step 0 is the diagonal block — causal
    flash with Lq == Lk; a later step is *fully visible* when the held
    shard came from a lower sequence position (``step <= my_idx``) and
    *fully masked* otherwise — a runtime ``lax.cond`` between a
    non-causal flash call and a no-op. The ring rotation itself stays
    unconditional (every device must participate in every ppermute)."""
    from ..ops.flash_attention import NEG_INF, flash_attention_with_lse

    def local_fn(q, k, v):
        b, lq, h, d = q.shape
        my_idx = jax.lax.axis_index(axis)
        m_run = jnp.full((b, lq, h), NEG_INF, jnp.float32)
        den = jnp.zeros((b, lq, h), jnp.float32)
        num = jnp.zeros((b, lq, h, d), jnp.float32)
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

        def merge(carry, o_i, lse_i):
            m_run, den, num = carry
            m_new = jnp.maximum(m_run, lse_i)
            w_old = jnp.where(
                m_run > NEG_INF / 2, jnp.exp(m_run - m_new), 0.0
            )
            w_new = jnp.where(
                lse_i > NEG_INF / 2, jnp.exp(lse_i - m_new), 0.0
            )
            return (
                m_new,
                den * w_old + w_new,
                num * w_old[..., None] + o_i * w_new[..., None],
            )

        carry = (m_run, den, num)
        k_cur, v_cur = k, v
        # Python loop: n_shards is static and small; `step` being static
        # lets the diagonal pick the causal flash variant at trace time.
        for step in range(n_shards):
            if not causal:
                o_i, lse_i = flash_attention_with_lse(
                    q, k_cur, v_cur, False, interpret
                )
                carry = merge(carry, o_i, lse_i)
            elif step == 0:
                o_i, lse_i = flash_attention_with_lse(
                    q, k_cur, v_cur, True, interpret
                )
                carry = merge(carry, o_i, lse_i)
            else:

                def attend(c, k_cur=k_cur, v_cur=v_cur):
                    o_i, lse_i = flash_attention_with_lse(
                        q, k_cur, v_cur, False, interpret
                    )
                    return merge(c, o_i, lse_i)

                carry = jax.lax.cond(
                    step <= my_idx, attend, lambda c: c, carry
                )
            if step + 1 < n_shards:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)

        _, den, num = carry
        out = num / jnp.where(den == 0.0, 1.0, den)[..., None]
        return out.astype(q.dtype)

    return _finalize_ring(local_fn, mesh, axis)
