"""Host collective: the inter-host gradient exchange over real TCP.

Why this exists: the CPU jax backend cannot run cross-process XLA
collectives ("Multiprocess computations aren't implemented on the CPU
backend"), so the multi-host elastic runtime — one OS process per
"host", each running single-process jax — moves the inter-host
1-bit exchange over a host-side transport instead. This is not just a
test shim: it is also the honest model of the source paper's setting
(commodity TCP between hosts, mnist change master.py's raw sockets),
and it is the seam where host LOSS becomes observable — a SIGKILLed
rank surfaces as an EOF/timeout on a socket, which no in-XLA collective
would ever report back to Python.

Topology: a star. Rank 0 is the conductor — every peer ships its
compressed planes up, rank 0 concatenates all ``hosts`` messages and
broadcasts the bundle back. (A ring would halve the conductor's fan-in,
but the star keeps loss detection trivial: every rank notices a dead
world within one step because every step touches the conductor.)

Failure contract — the donation footgun: the exchange runs inside the
jitted train step via ``jax.experimental.io_callback(ordered=True)``,
and the step donates its state buffers. Raising out of a callback
mid-dispatch would poison the donated state (the PR 8 lesson), so the
callback NEVER raises: on any socket error it marks the channel
``lost`` and returns shape-correct zeros. The trainer checks
``channel.lost`` at the next step boundary, discards the garbage step,
and vacates via the preempt path WITHOUT saving — the relaunch resumes
from the last digest-verified checkpoint generation, which is what
makes the post-shrink trajectory bitwise-equal to a fresh resume.

Lockstep: every rank must issue the same sequence of ``allgather``
calls with the same ``tag``; the conductor cross-checks tags and treats
a mismatch as divergence (mark lost — a diverged world must vacate, not
exchange garbage). The compressed transform below issues exactly one
allgather per step, tagged by a monotonic step counter.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

log = logging.getLogger(__name__)

# frame header: (rank, tag, payload_len)
_HDR = struct.Struct("!IIQ")
_LEN = struct.Struct("!Q")
_HELLO_TAG = 0xFFFFFFFF


class HostLostError(ConnectionError):
    """A peer host vanished mid-exchange (EOF/timeout/reset). Carries
    ``lost_ranks`` when the conductor could attribute the loss."""

    def __init__(self, message: str, lost_ranks: Optional[List[int]] = None):
        super().__init__(message)
        self.lost_ranks = list(lost_ranks or [])


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise HostLostError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


class HostChannel:
    """One rank's endpoint of the star-topology host collective.

    ``start()`` establishes the full-world mesh of connections (rank 0
    binds/listens/accepts; peers connect with jittered retries — the
    conductor races to bind, so a refused connect is the expected
    transient). ``allgather(payload, tag)`` is the one collective: every
    rank contributes a byte string, every rank receives all ``hosts``
    payloads in rank order. ``hosts == 1`` needs no sockets at all.

    Byte counters (``bytes_sent``/``bytes_received``) account the real
    framed traffic for the observability split; ``lost`` latches on the
    first failure (with ``lost_ranks`` when attributable) and every
    later call fails fast — a half-dead world must vacate, not limp.

    Thread safety: ``allgather`` is meant for one caller (the train
    step's ordered io_callback); ``mark_lost`` may race it from a
    monitor thread, hence the small lock around the latch.
    """

    def __init__(
        self,
        rank: int,
        hosts: int,
        port: int,
        *,
        host: str = "127.0.0.1",
        timeout_s: float = 60.0,
        connect_retries: int = 20,
        connect_backoff_s: float = 0.1,
    ):
        if hosts < 1 or not 0 <= rank < hosts:
            raise ValueError(f"rank {rank} out of range for {hosts} host(s)")
        self.rank = int(rank)
        self.hosts = int(hosts)
        self.port = int(port)
        self.host = host
        self.timeout_s = float(timeout_s)
        self.connect_retries = int(connect_retries)
        self.connect_backoff_s = float(connect_backoff_s)
        self.bytes_sent = 0
        self.bytes_received = 0
        self._lock = threading.Lock()
        self._lost = False
        self._lost_reason = ""
        self.lost_ranks: List[int] = []
        self._peers: Dict[int, socket.socket] = {}  # conductor: rank->sock
        self._up: Optional[socket.socket] = None    # peer: link to rank 0
        self._srv: Optional[socket.socket] = None
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "HostChannel":
        if self._started or self.hosts == 1:
            self._started = True
            return self
        if self.rank == 0:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((self.host, self.port))
            srv.listen(self.hosts)
            srv.settimeout(self.timeout_s)
            self._srv = srv
            try:
                while len(self._peers) < self.hosts - 1:
                    conn, _ = srv.accept()
                    conn.settimeout(self.timeout_s)
                    rank, tag, n = _HDR.unpack(
                        _recv_exact(conn, _HDR.size)
                    )
                    if tag != _HELLO_TAG or not 1 <= rank < self.hosts:
                        raise HostLostError(
                            f"bad hello (rank={rank}, tag={tag:#x}) — "
                            "stale peer from a previous generation?"
                        )
                    if rank in self._peers:
                        raise HostLostError(
                            f"rank {rank} connected twice (rank collision)"
                        )
                    self._peers[rank] = conn
            except (OSError, HostLostError) as e:
                self.mark_lost(f"world never formed: {e}")
                raise HostLostError(
                    f"conductor: only {len(self._peers) + 1}/{self.hosts} "
                    f"hosts joined within {self.timeout_s}s: {e}"
                ) from e
        else:
            from ..utils.transfer import _connect_with_retries

            try:
                self._up = _connect_with_retries(
                    self.host, self.port, timeout=self.timeout_s,
                    retries=self.connect_retries,
                    backoff_s=self.connect_backoff_s,
                )
                self._up.settimeout(self.timeout_s)
                self._up.sendall(_HDR.pack(self.rank, _HELLO_TAG, 0))
            except OSError as e:
                self.mark_lost(f"could not join world: {e}")
                raise HostLostError(
                    f"rank {self.rank}: conductor {self.host}:{self.port} "
                    f"unreachable: {e}"
                ) from e
        self._started = True
        log.info(
            "host collective up: rank %d/%d via %s:%d",
            self.rank, self.hosts, self.host, self.port,
        )
        return self

    def close(self) -> None:
        for s in [self._up, self._srv, *self._peers.values()]:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._peers.clear()
        self._up = None
        self._srv = None

    # -- loss latch --------------------------------------------------------

    @property
    def lost(self) -> bool:
        with self._lock:
            return self._lost

    @property
    def lost_reason(self) -> str:
        with self._lock:
            return self._lost_reason

    def mark_lost(self, reason: str, ranks: Optional[List[int]] = None):
        with self._lock:
            if not self._lost:
                self._lost = True
                self._lost_reason = reason
                self.lost_ranks = list(ranks or [])
                log.error(
                    "host collective lost (rank %d/%d): %s",
                    self.rank, self.hosts, reason,
                )

    # -- the collective ----------------------------------------------------

    def allgather(self, payload: bytes, tag: int = 0) -> List[bytes]:
        """Every rank contributes ``payload``; returns all ``hosts``
        payloads in rank order (identical list on every rank). Raises
        :class:`HostLostError` on any transport failure (after latching
        ``lost``) — callers inside a jitted step must wrap this (see
        module docstring)."""
        if self.hosts == 1:
            return [payload]
        if not self._started:
            raise RuntimeError("HostChannel.start() not called")
        if self.lost:
            raise HostLostError(f"world already lost: {self.lost_reason}")
        tag &= 0xFFFFFFFF
        try:
            if self.rank == 0:
                return self._conduct(payload, tag)
            return self._follow(payload, tag)
        except HostLostError:
            raise
        except OSError as e:
            self.mark_lost(f"{type(e).__name__}: {e}")
            raise HostLostError(
                f"rank {self.rank}: exchange failed: {e}"
            ) from e

    def _conduct(self, payload: bytes, tag: int) -> List[bytes]:
        parts: List[Optional[bytes]] = [None] * self.hosts
        parts[0] = payload
        for rank, sock in self._peers.items():
            try:
                r, t, n = _HDR.unpack(_recv_exact(sock, _HDR.size))
                if r != rank or t != tag:
                    raise HostLostError(
                        f"schedule divergence: rank {rank} sent "
                        f"(rank={r}, tag={t}), expected tag {tag}"
                    )
                parts[rank] = _recv_exact(sock, n)
                self.bytes_received += _HDR.size + n
            except (OSError, HostLostError) as e:
                self.mark_lost(
                    f"host {rank} lost mid-gather: {e}", ranks=[rank]
                )
                raise HostLostError(
                    f"conductor: host {rank} lost: {e}", lost_ranks=[rank]
                ) from e
        bundle = b"".join(
            _LEN.pack(len(p)) + p for p in parts  # type: ignore[arg-type]
        )
        hdr = _HDR.pack(0, tag, len(bundle))
        for rank, sock in self._peers.items():
            try:
                sock.sendall(hdr + bundle)
                self.bytes_sent += len(hdr) + len(bundle)
            except OSError as e:
                self.mark_lost(
                    f"host {rank} lost mid-broadcast: {e}", ranks=[rank]
                )
                raise HostLostError(
                    f"conductor: host {rank} lost: {e}", lost_ranks=[rank]
                ) from e
        return parts  # type: ignore[return-value]

    def _follow(self, payload: bytes, tag: int) -> List[bytes]:
        assert self._up is not None
        self._up.sendall(_HDR.pack(self.rank, tag, len(payload)) + payload)
        self.bytes_sent += _HDR.size + len(payload)
        r, t, n = _HDR.unpack(_recv_exact(self._up, _HDR.size))
        if r != 0 or t != tag:
            self.mark_lost(
                f"schedule divergence: conductor sent (rank={r}, tag={t}), "
                f"expected tag {tag}"
            )
            raise HostLostError("schedule divergence on broadcast")
        bundle = _recv_exact(self._up, n)
        self.bytes_received += _HDR.size + n
        parts, off = [], 0
        for _ in range(self.hosts):
            (m,) = _LEN.unpack(bundle[off:off + _LEN.size])
            off += _LEN.size
            parts.append(bundle[off:off + m])
            off += m
        if off != n:
            self.mark_lost(f"bundle framing off ({off} != {n})")
            raise HostLostError("corrupt broadcast bundle")
        return parts


def allgather_rows(
    channel: HostChannel, row: np.ndarray, *, tag: int = 0
) -> np.ndarray:
    """Stack every host's equally-shaped ``row`` into ``(hosts, *shape)``
    (rank order). The checkpoint-boundary EF-row sync: each rank's
    compress state holds only its own row; the primary needs the full
    matrix before saving so a resume at ANY host count can re-fold it
    (parallel/remesh). Raises HostLostError on transport failure — the
    caller is at a step boundary, outside jit, where raising is safe."""
    row = np.ascontiguousarray(row)
    parts = channel.allgather(row.tobytes(), tag=tag)
    out = np.stack([
        np.frombuffer(p, dtype=row.dtype).reshape(row.shape) for p in parts
    ])
    return out


# -- the host-side compressed gradient transform ----------------------------


def host_sign_compress(
    *,
    mode: str,
    channel: HostChannel,
    bucket_size: int = 1024,
    chunks: int = 4,
) -> Any:
    """1-bit inter-host gradient exchange as an optax transformation —
    the :func:`~..train.optim.sign_compress` contract carried over the
    host collective instead of an XLA axis.

    Single-phase topology: each host sign-compresses its (EF-corrected)
    full gradient into bucket planes + scales, the star allgather moves
    every host's compressed message, and each host decodes and combines
    all ``hosts`` contributions locally (mean of scale*sign for
    ``sign_ef``, Bernstein majority for ``sign``). There is no second
    compressed phase — the broadcast already happened — so only the
    worker-side error feedback exists (``ef_residual2`` stays zero, kept
    at the flat layout so parallel/remesh's fold/regrow rules apply
    unchanged across host counts).

    State layout: :class:`~..train.optim.SignCompressState` with the
    leading axis = ``hosts``. Each rank updates only its OWN row (the
    others stay zero in its copy); the trainer allgathers the rows at
    checkpoint boundaries (:func:`allgather_rows`) so the saved state is
    complete. The combine math runs identically on every rank from the
    identical gathered bytes, so updates — and therefore trajectories —
    are bitwise-equal across the world.

    Exchange-in-jit: the TCP roundtrip runs via ``io_callback``
    (ordered=True, exactly one per step). The callback NEVER raises
    (donation poison — module docstring): on failure it latches
    ``channel.lost`` and returns zeros; the trainer vacates at the next
    step boundary without saving.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ..ops.bitpack import unpack_bits
    from ..ops.comm_compress import (
        _signs,
        compress_buckets,
        decompress_buckets,
        make_plan,
        pad_flat,
        tree_size,
    )

    if mode not in ("sign", "sign_ef"):
        raise ValueError(
            f"unknown compression mode {mode!r} (have: sign, sign_ef)"
        )
    hosts, rank = channel.hosts, channel.rank

    def _plan(n: int):
        return make_plan(
            n, world=hosts, mode=mode, bucket_size=bucket_size,
            chunks=chunks,
        )

    step_counter = {"n": 0}  # lockstep tag: every rank steps in unison

    def init(params):
        from ..train.optim import SignCompressState  # lazy: import cycle

        if mode != "sign_ef":
            return optax.EmptyState()
        plan = _plan(tree_size(params))
        return SignCompressState(
            ef_residual=jnp.zeros((hosts, plan.padded), jnp.float32),
            ef_residual2=jnp.zeros((hosts, plan.seg), jnp.float32),
        )

    def update(updates, state, params=None):
        from ..train.optim import SignCompressState  # lazy: import cycle

        del params
        flat, unravel = jax.flatten_util.ravel_pytree(updates)
        plan = _plan(flat.size)
        flat = pad_flat(flat.astype(jnp.float32), plan)
        if mode == "sign_ef":
            corrected = flat + state.ef_residual[rank]
        else:
            corrected = flat
        total_nb, B = hosts * plan.nb, plan.bucket_size
        x = corrected.reshape(total_nb, B)
        planes, scale = compress_buckets(x)        # (total_nb, B/32), (total_nb,)
        sent = decompress_buckets(planes, scale, B).reshape(plan.padded)

        planes_nbytes = total_nb * plan.words * 4
        scale_nbytes = total_nb * 4

        def _xchg(planes_np: np.ndarray, scale_np: np.ndarray):
            zeros = (
                np.zeros((hosts, total_nb, plan.words), np.int32),
                np.zeros((hosts, total_nb), np.float32),
            )
            if channel.lost:
                return zeros
            tag = step_counter["n"]
            step_counter["n"] += 1
            try:
                payload = (
                    np.ascontiguousarray(planes_np).tobytes()
                    + np.ascontiguousarray(scale_np).tobytes()
                )
                parts = channel.allgather(payload, tag=tag)
                g_planes = np.empty(
                    (hosts, total_nb, plan.words), np.int32
                )
                g_scales = np.empty((hosts, total_nb), np.float32)
                for h, part in enumerate(parts):
                    if len(part) != planes_nbytes + scale_nbytes:
                        raise HostLostError(
                            f"host {h} message {len(part)}B, expected "
                            f"{planes_nbytes + scale_nbytes}B"
                        )
                    g_planes[h] = np.frombuffer(
                        part[:planes_nbytes], np.int32
                    ).reshape(total_nb, plan.words)
                    g_scales[h] = np.frombuffer(
                        part[planes_nbytes:], np.float32
                    )
                return g_planes, g_scales
            except Exception as e:  # NEVER raise mid-dispatch (donation)
                channel.mark_lost(f"{type(e).__name__}: {e}")
                return zeros

        g_planes, g_scales = jax.experimental.io_callback(
            _xchg,
            (
                jax.ShapeDtypeStruct((hosts, total_nb, plan.words),
                                     jnp.int32),
                jax.ShapeDtypeStruct((hosts, total_nb), jnp.float32),
            ),
            planes, scale,
            ordered=True,
        )
        if mode == "sign":
            votes = jnp.sum(unpack_bits(g_planes, B), axis=0)
            combined = _signs(votes) * jnp.mean(g_scales, axis=0)[..., None]
        else:
            contrib = decompress_buckets(g_planes, g_scales, B)
            combined = jnp.mean(contrib, axis=0)   # (total_nb, B)
        combined = combined.reshape(plan.padded)
        new_updates = unravel(combined[: plan.n_params])
        if mode != "sign_ef":
            return new_updates, state
        e1_new = (corrected - sent).at[plan.n_params:].set(0.0)
        return new_updates, SignCompressState(
            ef_residual=state.ef_residual.at[rank].set(e1_new),
            ef_residual2=state.ef_residual2,
        )

    return optax.GradientTransformation(init, update)
