"""Version-compat shims for JAX APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma``) across the 0.4.x -> 0.5+ line. Calling
``jax.shard_map`` directly raises ``AttributeError`` on the older
releases this repo still supports (the seed's 21 tier-1 failures on
jax 0.4.37 were exactly that), so every call site goes through this
shim instead — the lint rule ``JG006`` (analysis/lint) enforces it.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax


def shard_map(
    f: Callable,
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: Optional[bool] = None,
    **kwargs: Any,
) -> Callable:
    """``jax.shard_map`` where available, else the experimental API.

    ``check_vma`` maps onto the old API's ``check_rep``; ``None`` leaves
    whichever backend is active at its own default. Extra kwargs pass
    through untouched (callers pinning version-specific options own the
    compatibility of those)."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return native(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    # jg: disable=JG006 -- this IS the compat shim the rule points at
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    return legacy_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
