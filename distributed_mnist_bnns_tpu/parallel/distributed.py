"""Multi-host initialization — the TPU-native replacement for the
reference's env:// rendezvous + process-group setup
(MASTER_ADDR/MASTER_PORT + dist.init_process_group('gloo'|'nccl'),
mnist-dist2.py:41-43,83).

One JAX process per host; devices are auto-discovered after
jax.distributed.initialize connects every process to the coordinator.
All collectives thereafter are XLA collectives compiled onto ICI/DCN —
there is no hand-rolled transport (the reference's raw-TCP checkpoint
shipping, mnist change master.py:117-124, is subsumed by the checkpoint
component writing to shared storage; utils/checkpoint.py).

Hardened bootstrap (multi-host elastic runtime): the bare
``jax.distributed.initialize`` call hangs forever on an unreachable
coordinator and surfaces rank collisions as opaque RPC errors — on a
real fleet that is the difference between "host 3 restarted with a
stale rank file" and "the coordinator VM is gone", and the two need
opposite responses. So the wrapper here

  * **fails fast on config errors** (``check_multihost_config``): a
    rank outside ``[0, num_processes)`` or a nonsense port is a
    programming error that no amount of retrying fixes — ``ValueError``
    before any network I/O;
  * **bounds every attempt** with ``initialization_timeout_s`` (passed
    through to jax's own coordinator handshake deadline);
  * **classifies failures loudly** (``classify_init_error``):
    ``coordinator-unreachable`` (refused/unavailable — the coordinator
    process is not there), ``rank-collision`` (two processes claimed
    the same ``process_id`` — retrying REJOINS the collision, so this
    is fatal), ``timeout`` (the coordinator exists but the world never
    filled — a peer is missing);
  * **retries the retryable kinds** (unreachable/timeout/unknown) with
    the jittered exponential backoff of
    :class:`~..resilience.policy.RetryPolicy` — constant-delay retries
    from a fleet of restarting hosts synchronize into a thundering
    herd on the coordinator exactly when it is struggling;
  * raises :class:`MultihostInitError` carrying the classified
    ``kind`` once the budget is spent, and emits a ``multihost_init``
    event (attempts, outcome, kind) when given a telemetry.

``detect_multihost`` reads the ``JG_MH_*`` environment the elastic
supervisor (resilience/multihost.py) exports into each rank process —
the env:// analogue for the subprocess-per-host runtime where the
inter-host exchange travels over the host collective
(parallel/hostcomm.py) rather than XLA.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, Optional

import jax

log = logging.getLogger(__name__)

# Environment contract between the elastic multihost supervisor and its
# rank subprocesses (resilience/multihost.py exports, detect_multihost
# reads). RANK/HOSTS name the host-level world; PORT is the rank-0
# conductor port for the host collective; STORE is the shared directory
# (checkpoints + membership.json + events).
ENV_RANK = "JG_MH_RANK"
ENV_HOSTS = "JG_MH_HOSTS"
ENV_PORT = "JG_MH_PORT"
ENV_STORE = "JG_MH_STORE"

#: classification kinds (MultihostInitError.kind)
COORDINATOR_UNREACHABLE = "coordinator-unreachable"
RANK_COLLISION = "rank-collision"
TIMEOUT = "timeout"
UNKNOWN = "unknown"

# Substring → kind, matched case-insensitively against the failure
# message. jax.distributed surfaces grpc status strings; the patterns
# cover both the grpc spellings and the Python exception types' texts.
_UNREACHABLE_PATTERNS = (
    "connection refused", "unavailable", "failed to connect",
    "connection reset", "name or service not known", "unreachable",
)
_COLLISION_PATTERNS = (
    "already exists", "already_exists", "duplicate task",
    "duplicate process", "already connected", "task already",
)
_TIMEOUT_PATTERNS = (
    "deadline exceeded", "deadline_exceeded", "timed out", "timeout",
    "barrier timed out",
)


class MultihostInitError(RuntimeError):
    """Cluster bootstrap failed; ``kind`` carries the classification
    (coordinator-unreachable | rank-collision | timeout | unknown)."""

    def __init__(self, message: str, *, kind: str, attempts: int = 1):
        super().__init__(message)
        self.kind = kind
        self.attempts = attempts


def classify_init_error(exc: BaseException) -> str:
    """Map an initialize failure onto the loud kinds above.

    Exception types first (a raw ``ConnectionRefusedError`` needs no
    message sniffing), then message substrings — jax wraps the grpc
    status into ``RuntimeError`` text, so the string is usually all
    there is.
    """
    if isinstance(exc, ConnectionError):
        return COORDINATOR_UNREACHABLE
    if isinstance(exc, TimeoutError):
        return TIMEOUT
    msg = str(exc).lower()
    for pat in _COLLISION_PATTERNS:
        if pat in msg:
            return RANK_COLLISION
    for pat in _UNREACHABLE_PATTERNS:
        if pat in msg:
            return COORDINATOR_UNREACHABLE
    for pat in _TIMEOUT_PATTERNS:
        if pat in msg:
            return TIMEOUT
    return UNKNOWN


def check_multihost_config(
    coordinator_address: Optional[str],
    num_processes: Optional[int],
    process_id: Optional[int],
) -> None:
    """Fail-fast sanity checks before any network I/O (``ValueError``
    — classified fatal by RetryPolicy, so supervisors never burn their
    restart budget rejoining with a config that cannot work)."""
    if num_processes is not None and num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    if num_processes is not None and num_processes > 1:
        if coordinator_address is None:
            raise ValueError(
                f"num_processes={num_processes} needs a "
                "coordinator_address (host:port)"
            )
        if process_id is None:
            raise ValueError(
                f"num_processes={num_processes} needs an explicit "
                "process_id (this host's rank)"
            )
    if process_id is not None:
        if process_id < 0:
            raise ValueError(f"process_id must be >= 0, got {process_id}")
        if num_processes is not None and process_id >= num_processes:
            raise ValueError(
                f"process_id {process_id} out of range for "
                f"num_processes {num_processes} (ranks are "
                f"0..{num_processes - 1})"
            )
    if coordinator_address is not None:
        host, sep, port = coordinator_address.rpartition(":")
        if not sep or not host:
            raise ValueError(
                "coordinator_address must be 'host:port', got "
                f"{coordinator_address!r}"
            )
        try:
            port_n = int(port)
        except ValueError:
            raise ValueError(
                f"coordinator_address port {port!r} is not an integer"
            ) from None
        if not 1 <= port_n <= 65535:
            raise ValueError(
                f"coordinator_address port {port_n} out of range 1..65535"
            )


def detect_multihost(env: Optional[Dict[str, str]] = None) -> Optional[dict]:
    """Read the elastic supervisor's ``JG_MH_*`` rank environment.

    Returns ``{"rank", "hosts", "port", "store"}`` when this process
    was launched as a rank of a multihost world, else ``None``. Raises
    ``ValueError`` on a half-set or inconsistent environment — a rank
    that silently ran single-host would corrupt the shared checkpoint
    generations it shares with its peers.
    """
    env = os.environ if env is None else env
    rank_s = env.get(ENV_RANK)
    hosts_s = env.get(ENV_HOSTS)
    if rank_s is None and hosts_s is None:
        return None
    if rank_s is None or hosts_s is None:
        raise ValueError(
            f"half-set multihost env: {ENV_RANK}={rank_s!r} "
            f"{ENV_HOSTS}={hosts_s!r} (supervisor must export both)"
        )
    try:
        rank, hosts = int(rank_s), int(hosts_s)
    except ValueError:
        raise ValueError(
            f"non-integer multihost env: {ENV_RANK}={rank_s!r} "
            f"{ENV_HOSTS}={hosts_s!r}"
        ) from None
    if hosts < 1 or not 0 <= rank < hosts:
        raise ValueError(
            f"multihost env rank {rank} out of range for {hosts} host(s)"
        )
    port_s = env.get(ENV_PORT)
    info = {
        "rank": rank,
        "hosts": hosts,
        "port": int(port_s) if port_s is not None else None,
        "store": env.get(ENV_STORE),
    }
    if hosts > 1 and info["port"] is None:
        raise ValueError(
            f"{ENV_HOSTS}={hosts} needs {ENV_PORT} (rank-0 conductor "
            "port for the host collective)"
        )
    return info


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    initialization_timeout_s: float = 60.0,
    retries: int = 3,
    policy: Any = None,
    telemetry: Any = None,
    sleep: Callable[[float], None] = time.sleep,
    _initialize: Optional[Callable[..., None]] = None,
) -> dict:
    """Connect this process to a multi-host JAX cluster.

    Mirrors the reference CLI contract (-n nodes, -nr node_rank with a
    master address) but via jax.distributed: pass
    coordinator_address="host:port", num_processes=n_hosts,
    process_id=this_host_rank. With no arguments, auto-detects from the
    cluster environment (TPU pod metadata / SLURM) or stays
    single-process.

    Hardened per the module docstring: fail-fast config validation,
    per-attempt ``initialization_timeout_s``, classified failures
    (:class:`MultihostInitError` with ``kind``), jittered-backoff
    retries for the retryable kinds only. ``_initialize`` injects the
    underlying initialize for tests (defaults to
    ``jax.distributed.initialize``); ``policy`` injects the backoff
    shape (defaults to a seeded-from-rank RetryPolicy so a restarting
    fleet decorrelates); ``sleep`` injects the clock.

    Returns a summary dict {process_id, num_processes, local_devices,
    global_devices} for logging.
    """
    check_multihost_config(coordinator_address, num_processes, process_id)
    attempts = 0
    if coordinator_address is not None or num_processes not in (None, 1):
        if policy is None:
            from ..resilience.policy import RetryPolicy

            # seed from the rank: every host restarts at once after a
            # coordinator bounce, identical jitter re-herds them
            policy = RetryPolicy(
                max_restarts=retries,
                base_backoff_s=0.5,
                max_backoff_s=15.0,
                seed=process_id,
            )
        init = (
            _initialize if _initialize is not None
            else jax.distributed.initialize
        )
        last_kind = UNKNOWN
        last_exc: Optional[BaseException] = None
        while True:
            attempts += 1
            try:
                init(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    initialization_timeout=int(initialization_timeout_s),
                )
                break
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                last_exc, last_kind = e, classify_init_error(e)
                desc = (
                    f"jax.distributed.initialize attempt {attempts} "
                    f"failed [{last_kind}] (coordinator "
                    f"{coordinator_address}, rank {process_id}/"
                    f"{num_processes}): {type(e).__name__}: {e}"
                )
                if last_kind == RANK_COLLISION:
                    # rejoining with the same rank hits the same
                    # collision; the supervisor must resolve ranks
                    _emit_init_event(
                        telemetry, ok=False, kind=last_kind,
                        attempts=attempts, coordinator=coordinator_address,
                        process_id=process_id, num_processes=num_processes,
                    )
                    raise MultihostInitError(
                        desc, kind=last_kind, attempts=attempts
                    ) from e
                if attempts > retries:
                    _emit_init_event(
                        telemetry, ok=False, kind=last_kind,
                        attempts=attempts, coordinator=coordinator_address,
                        process_id=process_id, num_processes=num_processes,
                    )
                    raise MultihostInitError(
                        f"{desc} — budget of {retries} retr(ies) spent",
                        kind=last_kind, attempts=attempts,
                    ) from e
                delay = policy.backoff(attempts)
                log.warning("%s; retrying in %.2fs", desc, delay)
                sleep(delay)
    info = {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
    _emit_init_event(
        telemetry, ok=True, kind="ok", attempts=max(attempts, 1),
        coordinator=coordinator_address, process_id=process_id,
        num_processes=num_processes,
    )
    if jax.process_index() == 0:
        log.info("distributed runtime: %s", info)
    return info


def _emit_init_event(
    telemetry: Any, *, ok: bool, kind: str, attempts: int,
    coordinator: Optional[str], process_id: Optional[int],
    num_processes: Optional[int],
) -> None:
    if telemetry is None:
        return
    try:
        telemetry.emit(
            "multihost_init", ok=ok, init_kind=kind, attempts=attempts,
            coordinator=coordinator, process_id=process_id,
            num_processes=num_processes,
        )
    except Exception:  # telemetry must never mask the init outcome
        log.exception("multihost_init event emit failed")
