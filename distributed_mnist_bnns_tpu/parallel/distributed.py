"""Multi-host initialization — the TPU-native replacement for the
reference's env:// rendezvous + process-group setup
(MASTER_ADDR/MASTER_PORT + dist.init_process_group('gloo'|'nccl'),
mnist-dist2.py:41-43,83).

One JAX process per host; devices are auto-discovered after
jax.distributed.initialize connects every process to the coordinator.
All collectives thereafter are XLA collectives compiled onto ICI/DCN —
there is no hand-rolled transport (the reference's raw-TCP checkpoint
shipping, mnist change master.py:117-124, is subsumed by the checkpoint
component writing to shared storage; utils/checkpoint.py)."""

from __future__ import annotations

import logging

import jax

log = logging.getLogger(__name__)


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Connect this process to a multi-host JAX cluster.

    Mirrors the reference CLI contract (-n nodes, -nr node_rank with a
    master address) but via jax.distributed: pass
    coordinator_address="host:port", num_processes=n_hosts,
    process_id=this_host_rank. With no arguments, auto-detects from the
    cluster environment (TPU pod metadata / SLURM) or stays single-process.

    Returns a summary dict {process_id, num_processes, local_devices,
    global_devices} for logging.
    """
    if coordinator_address is not None or num_processes not in (None, 1):
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    info = {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
    if jax.process_index() == 0:
        log.info("distributed runtime: %s", info)
    return info
