"""Pipeline parallelism — GPipe-style microbatched stage pipeline over a
mesh axis, built on shard_map + lax.ppermute.

The reference has no pipeline parallelism (its 2-device split is naive
layer placement with no micro-batching, SURVEY §2.3 "PP: absent"); this
module exceeds parity. Semantics: a homogeneous chain of ``n_stages``
stage functions (stage s owns its own parameter slice, sharded over the
'pipe' axis), fed ``n_micro`` microbatches. Every device runs the same
SPMD program; at each schedule tick it processes the activation it holds
and hands the result to its ring neighbor (``ppermute`` over ICI). The
bubble is the standard (n_stages - 1) ticks at fill and drain:
total ticks = n_micro + n_stages - 1.

Exactness: the pipelined result equals applying the stages sequentially —
covered by tests/test_pipeline.py against a single-device loop.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import default_registry
from .compat import shard_map


def make_pipeline_fn(
    mesh: Mesh,
    stage_fn: Callable[..., jnp.ndarray],
    *,
    axis: str = "pipe",
    n_micro: int = 4,
    batch_axis: str | None = None,
    stage_takes_rng: bool = False,
    stage_remat: bool = False,
    param_specs=None,
    seed: int = 0,
):
    """Build f(stage_params, x[, rng]) -> y running the stage chain as a
    pipeline.

    stage_params: pytree whose leaves have leading dim n_stages (stage-major,
    sharded over ``axis``). stage_fn(params_for_one_stage, x) -> x' must be
    shape-preserving (homogeneous pipeline).
    x: (B, ...) with B divisible by n_micro; replicated in, replicated out.

    ``batch_axis``: name of a second mesh axis to shard the batch dim of
    ``x`` over — DP x PP composition on a 2-D (batch_axis, axis) mesh.
    Each data-parallel replica row runs its own independent pipeline over
    its batch shard (stage params replicated across rows, so the
    ppermute ring only connects devices within a row); the local batch
    B/dp must itself be divisible by ``n_micro``. Gradient all-reduce
    over ``batch_axis`` is NOT this function's job — it falls out of the
    loss mean over the globally-sharded output under jit/GSPMD, exactly
    as in plain DP.

    ``stage_takes_rng``: stage_fn is ``(params, x, rng) -> x'`` and the
    returned callable is ``f(stage_params, x, rng)``. Each (stage,
    microbatch) cell receives an independent key —
    ``fold_in(fold_in(rng, microbatch), stage)`` — that depends only on
    its schedule-invariant coordinates, never on the tick: the draw a
    cell makes is identical whatever schedule executes it (the property
    that makes dropout well-defined under pipelining; see
    tests/test_pipeline.py's rng-matched sequential oracle). Under
    DP x PP the ``batch_axis`` row index is folded in first, so each
    data replica draws independent masks for its batch shard (the same
    decorrelation the step body's grad-accum fold_in enforces).

    ``param_specs``: optional pytree of PartitionSpecs (matching the
    stage_params structure) for leaves that are sharded over MORE than
    the stage axis — e.g. Megatron-TP stage weights also sharded over a
    'model' mesh axis (see tp_pipeline.py). Every spec's dim 0 must
    still be ``axis`` (the stage dim); defaults to ``P(axis)`` on every
    leaf. The stage_fn is then responsible for the model-axis
    collectives (psum of row-parallel partials) — inside shard_map the
    axis name is in scope.

    ``stage_remat``: wrap each stage execution in ``jax.checkpoint`` so
    reverse-mode AD stores only the stage's *input* per tick and
    recomputes its internals in the backward pipeline. This bounds
    activation memory to O(ticks x microbatch), independent of stage
    depth — the 1F1B-class memory footprint (see PERF.md §pipeline):
    with XLA's static schedule, fwd-all-then-bwd-reversed has the same
    bubble as tick-interleaved 1F1B, so memory is the only axis left,
    and checkpointing the stage recovers it without a manual vjp
    schedule."""
    n_stages = mesh.shape[axis]

    run_stage = stage_fn
    if not stage_takes_rng:
        run_stage = lambda params, x, rng: stage_fn(params, x)  # noqa: E731
    if stage_remat:
        run_stage = jax.checkpoint(run_stage)

    def local_fn(stage_params, x, rng):
        # stage_params leaves arrive as (1, ...) slices -> squeeze stage dim.
        params = jax.tree.map(lambda p: p[0], stage_params)
        idx = jax.lax.axis_index(axis)
        b = x.shape[0]
        mb = b // n_micro
        micro = x.reshape(n_micro, mb, *x.shape[1:])
        total_ticks = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        buf = jnp.zeros_like(micro[0])
        outputs = jnp.zeros_like(micro)

        def tick(t, carry):
            buf, outputs = carry
            # stage 0 injects microbatch t (clamped; masked by validity)
            inject = micro[jnp.clip(t, 0, n_micro - 1)]
            x_in = jnp.where(idx == 0, inject, buf)
            # device s at tick t is working on microbatch (t - s)
            mb_idx = t - idx
            valid = (mb_idx >= 0) & (mb_idx < n_micro)
            row_rng = (
                jax.random.fold_in(rng, jax.lax.axis_index(batch_axis))
                if batch_axis else rng
            )
            cell_rng = jax.random.fold_in(
                jax.random.fold_in(
                    row_rng, jnp.clip(mb_idx, 0, n_micro - 1)
                ),
                idx,
            )
            y = run_stage(params, x_in, cell_rng)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            # last stage banks its finished microbatch
            is_last = idx == n_stages - 1
            outputs = jax.lax.cond(
                valid & is_last,
                lambda o: o.at[jnp.clip(mb_idx, 0, n_micro - 1)].set(y),
                lambda o: o,
                outputs,
            )
            buf = jax.lax.ppermute(y, axis, perm)
            return buf, outputs

        _, outputs = jax.lax.fori_loop(0, total_ticks, tick, (buf, outputs))
        # replicate the last stage's collected outputs to every device
        outputs = jax.lax.psum(
            jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis,
        )
        return outputs.reshape(b, *x.shape[1:])

    if param_specs is not None:
        for spec in jax.tree.leaves(
                param_specs, is_leaf=lambda s: isinstance(s, P)):
            if not spec or spec[0] != axis:
                # local_fn squeezes dim 0 as the per-stage slice; any
                # other leading placement silently runs stage-0 weights
                # on every device
                raise ValueError(
                    f"param_specs leaf {spec} must have the stage axis "
                    f"{axis!r} at dim 0"
                )
    x_spec = P(batch_axis) if batch_axis else P()
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            param_specs if param_specs is not None else P(axis),
            x_spec, P(),
        ),
        out_specs=x_spec,
        check_vma=False,
    )
    # Pipeline-shape telemetry: the schedule's static geometry as gauges
    # (the analytic bubble is the number a profiler trace should confirm)
    # plus a per-call counter. Direct callers count dispatches; embedded
    # in an outer jit (the trainer's pipelined apply) the counter ticks
    # per TRACE — a growing count across same-shape steps is the retrace
    # signal. Each call is also a named profiler region.
    reg = default_registry()
    reg.gauge("pipeline_stages", "GPipe stage count").set(
        n_stages, axis=axis
    )
    reg.gauge("pipeline_microbatches", "microbatches per step").set(
        n_micro, axis=axis
    )
    reg.gauge(
        "pipeline_bubble_fraction", "analytic GPipe bubble fraction"
    ).set(pipeline_bubble_fraction(n_stages, n_micro), axis=axis)
    calls = reg.counter(
        "pipeline_calls_total",
        "pipeline invocations (dispatches, or traces under an outer jit)",
    )

    if stage_takes_rng:
        jitted = jax.jit(fn)
    else:
        # The shard_map signature is uniform (params, x, rng); stages
        # that take no rng get a key derived from ``seed`` that they
        # never consume.
        _dummy = jax.random.PRNGKey(seed)
        jitted = jax.jit(lambda p, x: fn(p, x, _dummy))

    @functools.wraps(jitted)
    def instrumented(*args, **kwargs):
        calls.inc(axis=axis)
        with jax.profiler.TraceAnnotation("pipeline_dispatch"):
            return jitted(*args, **kwargs)

    return instrumented


def sequential_reference(
    stage_params: Any, x: jnp.ndarray, stage_fn: Callable
) -> jnp.ndarray:
    """Oracle: apply the stage chain sequentially on one device."""
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    for s in range(n_stages):
        params = jax.tree.map(lambda p: p[s], stage_params)
        x = stage_fn(params, x)
    return x


def sequential_reference_rng(
    stage_params: Any,
    x: jnp.ndarray,
    stage_fn: Callable,
    rng: jax.Array,
    n_micro: int,
) -> jnp.ndarray:
    """Single-device oracle for the rng-plumbed pipeline: runs every
    (stage, microbatch) cell with the SAME key derivation the schedule
    uses — ``fold_in(fold_in(rng, microbatch), stage)`` — so a pipelined
    run with dropout/stochastic masks must match it exactly (the
    schedule-invariance contract of make_pipeline_fn)."""
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    b = x.shape[0]
    micro = x.reshape(n_micro, b // n_micro, *x.shape[1:])
    outs = []
    for m in range(n_micro):
        h = micro[m]
        for s in range(n_stages):
            params = jax.tree.map(lambda p, s=s: p[s], stage_params)
            cell_rng = jax.random.fold_in(jax.random.fold_in(rng, m), s)
            h = stage_fn(params, h, cell_rng)
        outs.append(h)
    return jnp.concatenate(outs).reshape(b, *x.shape[1:])


def pipeline_bubble_fraction(n_stages: int, n_micro: int) -> float:
    """Analytic GPipe bubble fraction of the tick schedule:
    ``(S - 1) / (M + S - 1)`` — each of fill and drain idles S-1 ticks
    per M work ticks, in forward and (mirrored) in the autodiff-reversed
    backward, so the fraction holds for the full train step. Under XLA's
    static schedule this equals tick-interleaved 1F1B's bubble (1F1B's
    win is in-flight activation memory, recovered here by
    ``stage_remat`` — see PERF.md §pipeline)."""
    return (n_stages - 1) / (n_micro + n_stages - 1)
