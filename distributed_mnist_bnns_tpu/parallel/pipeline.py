"""Pipeline parallelism — GPipe-style microbatched stage pipeline over a
mesh axis, built on shard_map + lax.ppermute.

The reference has no pipeline parallelism (its 2-device split is naive
layer placement with no micro-batching, SURVEY §2.3 "PP: absent"); this
module exceeds parity. Semantics: a homogeneous chain of ``n_stages``
stage functions (stage s owns its own parameter slice, sharded over the
'pipe' axis), fed ``n_micro`` microbatches. Every device runs the same
SPMD program; at each schedule tick it processes the activation it holds
and hands the result to its ring neighbor (``ppermute`` over ICI). The
bubble is the standard (n_stages - 1) ticks at fill and drain:
total ticks = n_micro + n_stages - 1.

Exactness: the pipelined result equals applying the stages sequentially —
covered by tests/test_pipeline.py against a single-device loop.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_pipeline_fn(
    mesh: Mesh,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    *,
    axis: str = "pipe",
    n_micro: int = 4,
    batch_axis: str | None = None,
):
    """Build f(stage_params, x) -> y running the stage chain as a pipeline.

    stage_params: pytree whose leaves have leading dim n_stages (stage-major,
    sharded over ``axis``). stage_fn(params_for_one_stage, x) -> x' must be
    shape-preserving (homogeneous pipeline).
    x: (B, ...) with B divisible by n_micro; replicated in, replicated out.

    ``batch_axis``: name of a second mesh axis to shard the batch dim of
    ``x`` over — DP x PP composition on a 2-D (batch_axis, axis) mesh.
    Each data-parallel replica row runs its own independent pipeline over
    its batch shard (stage params replicated across rows, so the
    ppermute ring only connects devices within a row); the local batch
    B/dp must itself be divisible by ``n_micro``. Gradient all-reduce
    over ``batch_axis`` is NOT this function's job — it falls out of the
    loss mean over the globally-sharded output under jit/GSPMD, exactly
    as in plain DP."""
    n_stages = mesh.shape[axis]

    def local_fn(stage_params, x):
        # stage_params leaves arrive as (1, ...) slices -> squeeze stage dim.
        params = jax.tree.map(lambda p: p[0], stage_params)
        idx = jax.lax.axis_index(axis)
        b = x.shape[0]
        mb = b // n_micro
        micro = x.reshape(n_micro, mb, *x.shape[1:])
        total_ticks = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        buf = jnp.zeros_like(micro[0])
        outputs = jnp.zeros_like(micro)

        def tick(t, carry):
            buf, outputs = carry
            # stage 0 injects microbatch t (clamped; masked by validity)
            inject = micro[jnp.clip(t, 0, n_micro - 1)]
            x_in = jnp.where(idx == 0, inject, buf)
            y = stage_fn(params, x_in)
            # device s at tick t is working on microbatch (t - s)
            mb_idx = t - idx
            valid = (mb_idx >= 0) & (mb_idx < n_micro)
            y = jnp.where(valid, y, jnp.zeros_like(y))
            # last stage banks its finished microbatch
            is_last = idx == n_stages - 1
            outputs = jax.lax.cond(
                valid & is_last,
                lambda o: o.at[jnp.clip(mb_idx, 0, n_micro - 1)].set(y),
                lambda o: o,
                outputs,
            )
            buf = jax.lax.ppermute(y, axis, perm)
            return buf, outputs

        _, outputs = jax.lax.fori_loop(0, total_ticks, tick, (buf, outputs))
        # replicate the last stage's collected outputs to every device
        outputs = jax.lax.psum(
            jnp.where(idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis,
        )
        return outputs.reshape(b, *x.shape[1:])

    x_spec = P(batch_axis) if batch_axis else P()
    fn = jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    return jax.jit(fn)


def sequential_reference(
    stage_params: Any, x: jnp.ndarray, stage_fn: Callable
) -> jnp.ndarray:
    """Oracle: apply the stage chain sequentially on one device."""
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    for s in range(n_stages):
        params = jax.tree.map(lambda p: p[s], stage_params)
        x = stage_fn(params, x)
    return x
