"""Data parallelism — the TPU-native replacement for
DistributedDataParallel + DistributedSampler (mnist-dist2.py:93,100-102).

Two equivalent formulations are provided:

  * ``make_dp_train_step`` — GSPMD: jit the single-device train step with
    batch inputs sharded over the 'data' mesh axis and state replicated;
    XLA inserts the gradient all-reduce over ICI automatically (the role of
    DDP's backward hooks). BatchNorm reductions happen over the *global*
    batch (sync-BN semantics — a strict improvement over the reference's
    per-replica stats; the shard_map variant below keeps per-replica
    normalization for exact DDP parity).

  * ``make_shardmap_dp_train_step`` — explicit SPMD: shard_map over the
    mesh; each device computes local grads on its batch shard, then
    ``lax.pmean`` over 'data' (the literal all-reduce DDP performs,
    visible in the program rather than hidden in hooks). BatchNorm
    normalizes with per-replica statistics exactly like torch DDP, and the
    running stats are pmean'd so the replicated state stays consistent
    (rank-0-saves semantics without divergent replicas).

Both take the same (state, images, labels, rng) signature as the
single-device step, so the Trainer/benchmarks can swap them in freely.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import default_registry
from ..ops.losses import cross_entropy_loss
from ..train.trainer import TrainState, clamp_latent, make_step_body
from .compat import shard_map

# Host-side placement cost per step (device_put dispatch / multi-process
# global-array assembly) — the piece of DP step time the device profiler
# cannot see. Feeds the obs registry so the `telemetry` snapshot shows
# when input placement, not compute, is the bottleneck.
_place_hist = default_registry().histogram(
    "host_placement_seconds",
    "host-side batch placement (shard/replicate/assemble) per call",
)


def _assemble_global(tree: Any, sharding: NamedSharding) -> Any:
    """Build global jax.Arrays from per-process local data. Each process
    contributes the rows its own data pipeline produced (batch_iterator's
    host_id-strided shard); jax stitches them into one global array laid
    out per ``sharding`` without any cross-host copy of the data itself."""
    t0 = time.perf_counter()
    out = jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        tree,
    )
    _place_hist.observe(time.perf_counter() - t0, path="assemble_global")
    return out


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Place every leaf replicated over the mesh.

    Multi-process: every host must hold identical values (true for state
    built from the same seed, the reference's implicit DDP contract —
    mnist-dist2.py:85-93); device_put cannot address remote devices, so the
    global array is assembled from the per-process copies instead."""
    sharding = NamedSharding(mesh, P())
    if jax.process_count() > 1:
        return _assemble_global(tree, sharding)
    return jax.device_put(tree, sharding)


def shard_batch(
    tree: Any, mesh: Mesh, axis: str = "data", *, batch_dim: int = 0
) -> Any:
    """Shard the batch dim of every leaf over the given mesh axis —
    the per-rank slicing DistributedSampler does host-side, expressed as a
    device placement. ``batch_dim=1`` places (S, B, ...) scan chunks
    (steps replicated, per-step batch sharded — make_train_scan's layout).

    Single-process: a plain device_put with a sharded layout. Multi-process:
    each host's array is only its *local* shard of the global batch
    (batch_iterator feeds per-host shards, mirroring DistributedSampler,
    mnist-dist2.py:100-102), so the global array must be assembled with
    make_array_from_process_local_data — a device_put onto the global
    sharding would mis-assemble (or fail on non-addressable devices)."""
    sharding = NamedSharding(mesh, P(*([None] * batch_dim), axis))
    if jax.process_count() > 1:
        return _assemble_global(tree, sharding)
    t0 = time.perf_counter()
    out = jax.device_put(tree, sharding)
    # device_put is async: this is the host dispatch cost, the part that
    # serializes against the python loop.
    _place_hist.observe(time.perf_counter() - t0, path="shard_batch")
    return out


def make_dp_train_step(
    clamp_mask: Any,
    mesh: Mesh,
    *,
    loss_fn: Callable = cross_entropy_loss,
    donate: bool = True,
    remat: bool = False,
    grad_accum: int = 1,
    augment: bool = False,
) -> Callable:
    """GSPMD data-parallel train step (grad all-reduce inserted by XLA).

    The body is the single-device step body (train/trainer.py
    make_step_body); the DP semantics live entirely in the shardings below
    — XLA turns the batch-sharded loss/grad reductions into ICI
    all-reduces, the role of DDP's backward hooks."""
    train_step = make_step_body(
        clamp_mask, loss_fn=loss_fn, remat=remat, grad_accum=grad_accum,
        augment=augment,
    )
    repl = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P("data"))
    return jax.jit(
        train_step,
        in_shardings=(repl, data_sh, data_sh, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_shardmap_dp_train_step(
    clamp_mask: Any,
    mesh: Mesh,
    *,
    loss_fn: Callable = cross_entropy_loss,
    axis: str = "data",
) -> Callable:
    """Explicit shard_map data-parallel step: local grads + lax.pmean —
    DDP's backward-hook all-reduce made visible (mnist-dist2.py:93,130)."""

    def local_step(state, images, labels, rng):
        local_rng = jax.random.fold_in(
            jax.random.fold_in(rng, state.step),
            jax.lax.axis_index(axis),  # decorrelate rngs across replicas
        )
        dropout_rng, binarize_rng = jax.random.split(local_rng)

        def compute_loss(params):
            outs, mutated = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                images,
                train=True,
                rngs={"dropout": dropout_rng, "binarize": binarize_rng},
                mutable=["batch_stats"],
            )
            return loss_fn(outs, labels), (outs, mutated.get("batch_stats", {}))

        (loss, (outs, new_bs)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        # The DDP all-reduce, explicit:
        grads = jax.lax.pmean(grads, axis)
        loss = jax.lax.pmean(loss, axis)
        acc = jax.lax.pmean(
            (jnp.argmax(outs, -1) == labels).mean() * 100.0, axis
        )
        # Keep replicated running stats consistent across replicas (the
        # reference leaves them divergent and saves rank 0's copy).
        new_bs = jax.lax.pmean(new_bs, axis) if new_bs else new_bs
        updates, new_opt_state = state.tx.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_params = clamp_latent(new_params, clamp_mask)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_bs if new_bs else state.batch_stats,
            opt_state=new_opt_state,
        )
        return new_state, {"loss": loss, "accuracy": acc}

    shmapped = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0,))


def _make_compressed_train_step(
    clamp_mask: Any,
    mesh: Mesh,
    state: "TrainState",
    *,
    loss_fn: Callable,
    axis: str,
    remat: bool,
    grad_accum: int,
    augment: bool,
    scan_steps: int,
) -> Callable:
    """Shared implementation of the compressed-DP and compressed-FSDP
    train dispatches (the two differ only in what lives inside
    ``state.tx`` and therefore in the state-spec tree
    ``compressed_state_specs`` derives)."""
    body = make_step_body(
        clamp_mask, loss_fn=loss_fn, remat=remat, grad_accum=grad_accum,
        augment=augment,
    )

    def compressed_train_step(state, images, labels, rng):
        # Decorrelate per-replica dropout/binarization noise; the body
        # additionally folds in state.step (same scheme as the
        # shard_map DP step above).
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        new_state, metrics = body(state, images, labels, rng)
        metrics = jax.lax.pmean(metrics, axis)
        bs = new_state.batch_stats
        if bs:
            # Per-replica normalization like torch DDP, replicated
            # running stats kept consistent (see make_shardmap_dp_
            # train_step).
            new_state = new_state.replace(
                batch_stats=jax.lax.pmean(bs, axis)
            )
        return new_state, metrics

    from .fsdp import compressed_state_specs

    state_specs = compressed_state_specs(state, axis)
    if scan_steps > 1:
        # The fused multi-step dispatch (make_train_scan) composed with
        # the compressed exchange: the scan must live INSIDE the
        # shard_map so the exchange's all_to_all/all_gather run per
        # iteration over the mapped axis. The exchange transform is
        # pure (no Python-level bucket state), so every iteration keeps
        # the per-chunk pack/exchange overlap; inputs are (S, B, ...)
        # chunks sharded P(None, axis).
        def compressed_train_scan_step(state, images, labels, rng):
            def scan_body(st, xs):
                st, m = compressed_train_step(st, xs[0], xs[1], rng)
                return st, m

            state, ms = jax.lax.scan(scan_body, state, (images, labels))
            return state, jax.tree.map(jnp.mean, ms)

        shmapped = shard_map(
            compressed_train_scan_step,
            mesh=mesh,
            in_specs=(state_specs, P(None, axis), P(None, axis), P()),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
    else:
        shmapped = shard_map(
            compressed_train_step,
            mesh=mesh,
            in_specs=(state_specs, P(axis), P(axis), P()),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
    return jax.jit(shmapped, donate_argnums=(0,))


def make_compressed_dp_train_step(
    clamp_mask: Any,
    mesh: Mesh,
    state: "TrainState",
    *,
    loss_fn: Callable = cross_entropy_loss,
    axis: str = "data",
    remat: bool = False,
    grad_accum: int = 1,
    augment: bool = False,
    scan_steps: int = 1,
) -> Callable:
    """Data-parallel train step with a 1-bit compressed gradient
    exchange (ops/comm_compress, PERF.md "Gradient comms").

    The body is the standard single-device step body — the DP all-reduce
    lives INSIDE ``state.tx``: the ``sign_compress`` transformation
    (train/optim.py) compresses each worker's local gradient to sign
    bitplanes + per-bucket scales and runs the two-phase
    all_to_all/all_gather exchange over ``axis``, so no ``pmean`` of
    gradients appears here (adding one would both double-reduce and
    defeat the compression). Metrics and BatchNorm running stats still
    take the plain fp32 pmean — they are O(1) and O(channels), not
    O(params).

    ``state`` is the template whose opt_state carries the EF residual
    buffers; their leading world axis is sharded over ``axis``
    (parallel/fsdp.compressed_state_specs), everything else replicated.

    ``scan_steps > 1`` fuses S steps into one lax.scan dispatch inside
    the shard_map (signature then takes (S, B, ...) chunks, metrics
    averaged over the S steps — make_train_scan semantics).
    """
    return _make_compressed_train_step(
        clamp_mask, mesh, state, loss_fn=loss_fn, axis=axis, remat=remat,
        grad_accum=grad_accum, augment=augment, scan_steps=scan_steps,
    )


def make_compressed_hier_train_step(
    clamp_mask: Any,
    mesh: Mesh,
    state: "TrainState",
    *,
    loss_fn: Callable = cross_entropy_loss,
    host_axis: str = "data",
    local_axis: str = "local",
    remat: bool = False,
    grad_accum: int = 1,
    augment: bool = False,
    scan_steps: int = 1,
) -> Callable:
    """Two-level hierarchical compressed-DP train step
    (ops/comm_compress.hier_exchange): batch sharded over BOTH mesh
    axes (hosts x local devices), gradients fp32-pmean'd over
    ``local_axis`` inside ``state.tx`` (``sign_compress(...,
    local_axis_name=...)``) — the in-host ring reduce on the fast
    interconnect — then 1-bit exchanged over ``host_axis`` only, the
    slow link. No gradient collective appears in this body (same
    contract as the flat compressed step).

    ``state`` is the template whose opt_state carries the per-HOST EF
    residual rows: leading axis = hosts, sharded over ``host_axis``,
    replicated over ``local_axis`` (every device on a host computes the
    identical post-pmean residual, so replication is consistent).
    ``scan_steps > 1`` fuses S steps into one scanned dispatch like the
    flat variants.
    """
    body = make_step_body(
        clamp_mask, loss_fn=loss_fn, remat=remat, grad_accum=grad_accum,
        augment=augment,
    )
    axes = (host_axis, local_axis)
    local_n = mesh.shape[local_axis]

    def hier_train_step(state, images, labels, rng):
        # Decorrelate per-DEVICE noise over the flattened (host, local)
        # index; the body additionally folds in state.step.
        dev = (
            jax.lax.axis_index(host_axis) * local_n
            + jax.lax.axis_index(local_axis)
        )
        rng = jax.random.fold_in(rng, dev)
        new_state, metrics = body(state, images, labels, rng)
        metrics = jax.lax.pmean(metrics, axes)
        bs = new_state.batch_stats
        if bs:
            new_state = new_state.replace(
                batch_stats=jax.lax.pmean(bs, axes)
            )
        return new_state, metrics

    from .fsdp import compressed_state_specs

    state_specs = compressed_state_specs(state, host_axis)
    if scan_steps > 1:

        def hier_train_scan_step(state, images, labels, rng):
            def scan_body(st, xs):
                st, m = hier_train_step(st, xs[0], xs[1], rng)
                return st, m

            state, ms = jax.lax.scan(scan_body, state, (images, labels))
            return state, jax.tree.map(jnp.mean, ms)

        shmapped = shard_map(
            hier_train_scan_step,
            mesh=mesh,
            in_specs=(state_specs, P(None, axes), P(None, axes), P()),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
    else:
        shmapped = shard_map(
            hier_train_step,
            mesh=mesh,
            in_specs=(state_specs, P(axes), P(axes), P()),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
    return jax.jit(shmapped, donate_argnums=(0,))


def make_compressed_fsdp_train_step(
    clamp_mask: Any,
    mesh: Mesh,
    state: "TrainState",
    *,
    loss_fn: Callable = cross_entropy_loss,
    axis: str = "data",
    remat: bool = False,
    grad_accum: int = 1,
    augment: bool = False,
    scan_steps: int = 1,
) -> Callable:
    """FSDP/ZeRO train step over the 1-bit compressed exchange
    (ops/comm_compress + train/optim.sign_compress_fsdp; PERF.md
    "Gradient comms — compressed FSDP").

    Same shard_map shape as the compressed-DP step — the ZeRO-ness
    lives inside ``state.tx``: ``sign_compress_fsdp`` reduce-scatters
    1-bit gradients to segment owners, runs the BASE optimizer on the
    owner's (1, seg) moment rows (optimizer state sharded 1/N over
    ``axis``, laid out by ``compressed_state_specs``), and broadcasts
    the 1-bit update delta in place of the fp32 param all-gather.
    Params stay replicated across workers (each device needs them for
    fwd/bwd anyway) and bitwise consistent, because every worker
    applies the identical decoded delta; the FSDP memory saving is the
    sharded optimizer state + EF residuals — see PERF.md for the
    ZeRO-1-vs-ZeRO-3 trade against the fp32 GSPMD FSDP path.

    ``state`` is the template whose opt_state carries the
    FsdpCompressState (EF residuals + flat-segment base-optimizer
    rows); ``scan_steps > 1`` fuses S steps into one scanned dispatch
    exactly like the DP variant.
    """
    return _make_compressed_train_step(
        clamp_mask, mesh, state, loss_fn=loss_fn, axis=axis, remat=remat,
        grad_accum=grad_accum, augment=augment, scan_steps=scan_steps,
    )
