"""Expert parallelism — Mixture-of-Experts dispatch over a mesh axis.

Not present in the reference (no MoE; its models are dense MLPs/CNNs,
SURVEY §2.2/§5); included because expert parallelism is a first-class
scaling axis of this framework, alongside dp/tp/pp/sp.

Design (GShard-style, TPU-first): tokens and experts are both sharded over
the ``expert`` mesh axis. Each device routes its local tokens with top-1
gating into capacity-bounded slots, builds one-hot dispatch/combine tensors,
and exchanges token blocks with the expert owners via two
``lax.all_to_all`` collectives (ICI neighbor exchange) — the canonical
einsum-dispatch formulation, so the whole thing stays static-shaped and
MXU-friendly under jit:

    dispatch (T, E, C) @ tokens (T, D) -> (E, C, D)
    all_to_all: group by owner -> each owner holds (E_local, n*C, D)
    vmapped expert_fn per local expert
    all_to_all back -> (E, C, D_out), combine (T, E, C) -> (T, D_out)

Routing is computed *per token shard* with per-shard capacity, which is the
semantics ``moe_reference`` mirrors exactly (including token dropping), so
the expert-parallel path can be tested for equality against the dense
oracle on the virtual CPU mesh.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.binarize import binarize
from ..ops.routing import (  # canonical defs: ops/routing.py (re-exported)
    load_balance_loss,
    top1_dispatch,
    topk_dispatch,
)
from ..ops.xnor_gemm import binary_matmul
from .compat import shard_map

__all__ = [
    "top1_dispatch",
    "topk_dispatch",
    "load_balance_loss",
    "binarized_expert",
    "init_expert_params",
    "moe_reference",
    "make_expert_parallel_moe",
]


def binarized_expert(params: Any, x: jnp.ndarray) -> jnp.ndarray:
    """One BNN expert: sign(x) @ sign(W) + b — the BinarizeLinear math
    (reference models/binarized_modules.py:68-85) as an MoE expert body.

    params: {"w": (D, D_out) fp32 latent, "b": (D_out,)}; x: (S, D).
    """
    y = binary_matmul(binarize(x), binarize(params["w"]))
    return y + params["b"]


def init_expert_params(
    key: jax.Array, num_experts: int, d_in: int, d_out: int
) -> dict:
    """Stacked per-expert latent params, leading dim = experts (the dim the
    ``expert`` mesh axis shards)."""
    kw, _ = jax.random.split(key)
    scale = d_in ** -0.5
    return {
        "w": jax.random.uniform(
            kw, (num_experts, d_in, d_out), minval=-scale, maxval=scale
        ),
        "b": jnp.zeros((num_experts, d_out), jnp.float32),
    }


def moe_reference(
    expert_params: Any,
    gate_w: jnp.ndarray,
    x: jnp.ndarray,
    *,
    capacity: int,
    expert_fn: Callable[[Any, jnp.ndarray], jnp.ndarray] = binarized_expert,
    n_shards: int = 1,
    k: int = 1,
) -> jnp.ndarray:
    """Dense single-device MoE oracle with per-shard routing.

    Routing runs independently per token shard (vmapped), with per-shard
    ``capacity`` — exactly the semantics of the expert-parallel path, so
    outputs match it including which tokens get dropped. ``k=1`` keeps
    the original top-1 combine (raw gate scaling); ``k>=2`` uses the
    GShard top-k dispatch (renormalized combine weights)."""
    t, d = x.shape
    assert t % n_shards == 0, (t, n_shards)
    xs = x.reshape(n_shards, t // n_shards, d)

    def route(x_s):
        gates = jax.nn.softmax(x_s @ gate_w)
        if k == 1:
            return top1_dispatch(gates, capacity)
        return topk_dispatch(gates, capacity, k)

    dispatch, combine = jax.vmap(route)(xs)                  # (S, Tl, E, C)
    ex_in = jnp.einsum("stec,std->escd", dispatch, xs)       # (E, S, C, D)
    e = ex_in.shape[0]
    ex_out = jax.vmap(expert_fn)(
        expert_params, ex_in.reshape(e, n_shards * capacity, d)
    )                                                        # (E, S*C, Do)
    ex_out = ex_out.reshape(e, n_shards, capacity, -1)
    out = jnp.einsum("stec,escd->std", combine, ex_out)
    return out.reshape(t, -1)


def make_expert_parallel_moe(
    mesh: Mesh,
    *,
    axis: str = "expert",
    capacity: int,
    expert_fn: Callable[[Any, jnp.ndarray], jnp.ndarray] = binarized_expert,
    k: int = 1,
) -> Callable:
    """Build a jitted expert-parallel MoE over ``mesh``'s ``axis``.

    Returns f(expert_params, gate_w, x): expert_params leaves are stacked
    (E, ...) and sharded on the leading dim; x is (T, D) sharded on tokens;
    gate_w (D, E) is replicated. The axis size must divide both E and T.
    ``k`` selects top-1 (original combine) or GShard top-k routing.
    """
    n = mesh.shape[axis]

    def local_fn(params_local, gate_w, x_local):
        # Per-device: params (E_local, ...), x (T_local, D).
        gates = jax.nn.softmax(x_local @ gate_w)             # (Tl, E)
        if k == 1:
            dispatch, combine = top1_dispatch(gates, capacity)
        else:
            dispatch, combine = topk_dispatch(gates, capacity, k)
        ex_in = jnp.einsum("tec,td->ecd", dispatch, x_local)  # (E, C, D)
        # Scatter expert groups to their owners; gather my experts' slices
        # from every source device: (E, C, D) -> (E_local, n*C, D).
        ex_in = jax.lax.all_to_all(
            ex_in, axis, split_axis=0, concat_axis=1, tiled=True
        )
        ex_out = jax.vmap(expert_fn)(params_local, ex_in)     # (El, n*C, Do)
        # Return each source device its tokens' results: -> (E, C, Do).
        ex_out = jax.lax.all_to_all(
            ex_out, axis, split_axis=1, concat_axis=0, tiled=True
        )
        return jnp.einsum("tec,ecd->td", combine, ex_out)

    params_spec = P(axis)   # leading (expert) dim sharded on every leaf
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(params_spec, P(), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    fn = jax.jit(fn)

    def moe(expert_params, gate_w, x):
        e = jax.tree.leaves(expert_params)[0].shape[0]
        t = x.shape[0]
        if e % n or t % n:
            raise ValueError(
                f"expert axis {axis!r} of size {n} must divide both the "
                f"expert count ({e}) and the token count ({t})"
            )
        return fn(expert_params, gate_w, x)

    return moe
