"""Model parallelism — declarative layer/tensor sharding over the 'model'
mesh axis.

The reference's model parallelism is a 2-way layer *placement* demo:
``Net(dev0, dev1)`` pins bn1/bn3 to dev0 and bn2/fc4 to dev1, with
activations implicitly shipped between devices each forward
(mnist-distributed-BNNS2.py:32-46,193-213). The TPU-native generalization
is sharding annotations: instead of placing whole layers on devices, the
big MLP kernels are sharded over the 'model' axis in Megatron
column/row pairs and XLA inserts the (ICI) collectives:

  fc1 kernel (784, H1)   -> P(None, 'model')   column-parallel
  fc2 kernel (H1, H2)    -> P('model', None)   row-parallel (psum output)
  fc3 kernel (H2, H3)    -> P(None, 'model')   column-parallel
  head kernel (H3, 10)   -> P('model', None)   row-parallel

Feature-wise layers (BatchNorm scale/bias, binarized-layer biases) follow
the activation sharding of the layer they modulate.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.trainer import TrainState

# Megatron-style roles a module can play in a TP layout:
#   col     — column-parallel GEMM: kernel P(None, axis), bias P(axis)
#             (output features sharded; no collective on the forward)
#   row     — row-parallel GEMM: kernel P(axis, None), bias P(None)
#             (contracting dim sharded; XLA inserts the psum)
#   feat    — feature-wise layer (BatchNorm/LayerNorm/bias-only) whose
#             features follow a column-parallel producer: all P(axis)
#   repl    — replicated: all P()
#   expert_stack — stacked per-expert params (E, ...): leading dim
#             sharded (expert parallelism; XLA partitions the dispatch
#             einsums and inserts the all-to-alls)
_ROLES = ("col", "row", "feat", "repl", "expert_stack")


def tp_rules_by_path(
    params: Any,
    table: Dict[str, str],
    axis: str = "model",
    *,
    strict: bool = True,
) -> Any:
    """PartitionSpec tree from an explicit {module-path-pattern: role}
    table (roles above). Patterns are fnmatch globs over the
    '/'-joined module path (leaf name excluded), first match wins in
    table order.

    Matching is by *path name*, never by auto-name index arithmetic: a
    model edit that inserts or renames a layer makes the lookup fail
    loudly (strict=True) instead of silently sharding the wrong
    layers. strict=False replicates unmatched modules instead."""
    for role in table.values():
        if role not in _ROLES:
            raise ValueError(f"unknown TP role {role!r} (have {_ROLES})")

    def path_match(mod_path: str, pattern: str) -> bool:
        # Segment-wise: '*' must not cross '/' (a bare fnmatch would let
        # 'TransformerBlock_*/BinarizedDense_0' swallow a NEWLY NESTED
        # '.../RotaryAttention_0/BinarizedDense_0', silently sharding a
        # module the table never named — the failure mode strict mode
        # exists to prevent).
        segs, pats = mod_path.split("/"), pattern.split("/")
        return len(segs) == len(pats) and all(
            fnmatch.fnmatch(s, p) for s, p in zip(segs, pats)
        )

    def spec_for(path, leaf) -> P:
        keys = [getattr(p, "key", "") for p in path if hasattr(p, "key")]
        mod_path = "/".join(keys[:-1])
        kind = keys[-1] if keys else ""
        role = next(
            (r for pat, r in table.items() if path_match(mod_path, pat)),
            None,
        )
        if role is None:
            if strict:
                raise KeyError(
                    f"no TP rule matches module path {mod_path!r} "
                    "(pass strict=False to replicate unmatched modules)"
                )
            return P()
        if role == "col":
            return P(None, axis) if kind == "kernel" else P(axis)
        if role == "row":
            return P(axis, None) if kind == "kernel" else P(None)
        if role == "feat":
            return P(axis)
        if role == "expert_stack":
            return P(axis)  # leading (expert) dim; trailing dims whole
        return P()

    flat = jax.tree_util.tree_flatten_with_path(params)
    specs = [spec_for(path, leaf) for path, leaf in flat[0]]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), specs
    )


# The flagship BnnMLP's layout (mnist-dist2.py:46-76 topology): fc1/fc3
# column-parallel, fc2 and the fp32 head row-parallel; each BatchNorm
# follows its producing GEMM's output sharding. Explicit names — an
# inserted layer breaks the lookup loudly rather than flipping parities.
BNN_MLP_TP_TABLE: Dict[str, str] = {
    "BinarizedDense_0": "col",
    "BatchNorm_0": "feat",
    "BinarizedDense_1": "row",
    "BatchNorm_1": "repl",
    "BinarizedDense_2": "col",
    "BatchNorm_2": "feat",
    "Dense_0": "row",
}

# The k-bit QNN twin has the same topology under QuantizedDense names.
QNN_MLP_TP_TABLE: Dict[str, str] = {
    "QuantizedDense_0": "col",
    "BatchNorm_0": "feat",
    "QuantizedDense_1": "row",
    "BatchNorm_1": "repl",
    "QuantizedDense_2": "col",
    "BatchNorm_2": "feat",
    "Dense_0": "row",
}

# Binarized ViT/LM blocks (models/transformer.py): Megatron attention
# (q/k/v column-parallel over heads, out-projection row-parallel) and
# MLP (up column, down row). Embeddings, LayerNorms, pos embeds and the
# fp32 head are replicated — they are a tiny parameter fraction and the
# residual stream stays replicated between blocks.
BNN_VIT_TP_TABLE: Dict[str, str] = {
    "TransformerBlock_*/BinarizedSelfAttention_0/BinarizedDense_0": "col",
    "TransformerBlock_*/BinarizedSelfAttention_0/BinarizedDense_1": "col",
    "TransformerBlock_*/BinarizedSelfAttention_0/BinarizedDense_2": "col",
    "TransformerBlock_*/BinarizedSelfAttention_0/BinarizedDense_3": "row",
    "TransformerBlock_*/BinarizedDense_0": "col",
    "TransformerBlock_*/BinarizedDense_1": "row",
    "TransformerBlock_*/ln_*": "repl",
    "BinarizedDense_0": "repl",   # patch embedding
    "tok_embed": "repl",
    "ln_head": "repl",
    "head": "repl",
    "": "repl",                   # top-level raw params (pos_embed)
}


# The MoE family: EXPERT parallelism through the same mesh axis — the
# GShard formulation is sharding annotations on the dispatch einsums, so
# sharding the stacked expert bank's leading (expert) dim is all it
# takes; XLA inserts the token all-to-alls. Everything else (router,
# dense layers, BNs) is small and stays replicated.
BNN_MOE_TP_TABLE: Dict[str, str] = {
    "BinarizedExperts_0": "expert_stack",   # leading dim = experts
    "BinarizedDense_0": "repl",
    "BinarizedDense_1": "repl",
    "BatchNorm_0": "repl",
    "BatchNorm_1": "repl",
    "router": "repl",
}


def tp_rules_for(model_name: str, params: Any, axis: str = "model") -> Any:
    """The TP layout for a registry model family, by path-name table."""
    if model_name.startswith("qnn"):
        return tp_rules_by_path(params, QNN_MLP_TP_TABLE, axis)
    if model_name.startswith("bnn-mlp"):
        return tp_rules_by_path(params, BNN_MLP_TP_TABLE, axis)
    if "vit" in model_name:
        return tp_rules_by_path(params, BNN_VIT_TP_TABLE, axis)
    if "moe" in model_name:
        return tp_rules_by_path(params, BNN_MOE_TP_TABLE, axis)
    # fp32-mlp-large deliberately not matched: its all-Dense topology
    # (Dense_0..3) would collide with the head rule and mis-shard.
    raise ValueError(
        f"no TP rule table for model {model_name!r} "
        "(have: the BNN-MLP/QNN, ViT and MoE families)"
    )


def bnn_mlp_tp_rules(params: Any, axis: str = "model") -> Any:
    """PartitionSpec tree for a BnnMLP params pytree (tensor
    parallelism) — the explicit-name table, see BNN_MLP_TP_TABLE."""
    return tp_rules_by_path(params, BNN_MLP_TP_TABLE, axis)


def tp_state_shardings(
    mesh: Mesh, state: TrainState, param_specs: Any
) -> TrainState:
    """The TP run's TrainState-of-NamedShardings: params per the rule
    table, everything else replicated. Shared by the per-step jit
    (``make_tp_train_step``) and the multi-step scan dispatch
    (train.make_train_scan's ``state_shardings``), so the two dispatch
    modes cannot drift in layout."""
    repl = NamedSharding(mesh, P())
    return TrainState(
        step=repl,
        params=jax.tree.map(
            lambda spec: NamedSharding(mesh, spec), param_specs
        ),
        batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
        opt_state=jax.tree.map(lambda _: repl, state.opt_state),
        apply_fn=state.apply_fn,
        tx=state.tx,
    )


def make_tp_train_step(
    base_train_step: Callable,
    mesh: Mesh,
    state: TrainState,
    param_specs: Any,
    *,
    data_axis: str = "data",
    donate: bool = True,
) -> tuple[Callable, TrainState]:
    """Jit a train step with tensor-parallel params + data-parallel batch.

    ``param_specs`` shards state.params; optimizer moments and batch stats
    stay replicated (XLA reshards on the fly where the update touches
    sharded params). Returns (jitted_step, state placed onto the mesh) —
    the combined dp x mp configuration, the superset of the reference's
    DDP (data axis) and its 2-device layer-split demo (model axis).
    ``donate`` releases the incoming state's buffers to the update (the
    functional-update training pattern; pass False to keep stepping the
    same placed state repeatedly, e.g. ablations)."""
    repl = NamedSharding(mesh, P())
    st_sh = tp_state_shardings(mesh, state, param_specs)
    placed = jax.device_put(state, st_sh)
    data_sh = NamedSharding(mesh, P(data_axis))
    step = jax.jit(
        base_train_step,
        in_shardings=(st_sh, data_sh, data_sh, repl),
        out_shardings=(st_sh, repl),
        donate_argnums=(0,) if donate else (),
    )
    return step, placed
