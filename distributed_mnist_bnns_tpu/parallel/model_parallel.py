"""Model parallelism — declarative layer/tensor sharding over the 'model'
mesh axis.

The reference's model parallelism is a 2-way layer *placement* demo:
``Net(dev0, dev1)`` pins bn1/bn3 to dev0 and bn2/fc4 to dev1, with
activations implicitly shipped between devices each forward
(mnist-distributed-BNNS2.py:32-46,193-213). The TPU-native generalization
is sharding annotations: instead of placing whole layers on devices, the
big MLP kernels are sharded over the 'model' axis in Megatron
column/row pairs and XLA inserts the (ICI) collectives:

  fc1 kernel (784, H1)   -> P(None, 'model')   column-parallel
  fc2 kernel (H1, H2)    -> P('model', None)   row-parallel (psum output)
  fc3 kernel (H2, H3)    -> P(None, 'model')   column-parallel
  head kernel (H3, 10)   -> P('model', None)   row-parallel

Feature-wise layers (BatchNorm scale/bias, binarized-layer biases) follow
the activation sharding of the layer they modulate.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.trainer import TrainState


def bnn_mlp_tp_rules(params: Any, axis: str = "model") -> Any:
    """PartitionSpec tree for a BnnMLP params pytree (tensor parallelism).

    Alternates column/row parallel binarized layers; the fp32 head is
    row-parallel. BatchNorm & bias specs follow the producing layer's
    output sharding (sharded after column-parallel, replicated after
    row-parallel)."""

    def spec_for(path, leaf) -> P:
        keys = [getattr(p, "key", "") for p in path]
        name = next((k for k in keys if "_" in k), "")
        kind = keys[-1] if keys else ""
        if name.startswith("BinarizedDense"):
            idx = int(name.split("_")[-1])
            col = idx % 2 == 0  # fc1/fc3 column-parallel, fc2 row-parallel
            if kind == "kernel":
                return P(None, axis) if col else P(axis, None)
            return P(axis) if col else P(None)  # bias
        if name.startswith("Dense"):  # fp32 head: row-parallel
            return P(axis, None) if kind == "kernel" else P(None)
        if name.startswith("BatchNorm"):
            idx = int(name.split("_")[-1])
            after_col = idx % 2 == 0  # bn1/bn3 follow column-parallel layers
            return P(axis) if after_col else P(None)
        return P()

    flat = jax.tree_util.tree_flatten_with_path(params)
    specs = [spec_for(path, leaf) for path, leaf in flat[0]]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), specs
    )


def make_tp_train_step(
    base_train_step: Callable,
    mesh: Mesh,
    state: TrainState,
    param_specs: Any,
    *,
    data_axis: str = "data",
) -> tuple[Callable, TrainState]:
    """Jit a train step with tensor-parallel params + data-parallel batch.

    ``param_specs`` shards state.params; optimizer moments and batch stats
    stay replicated (XLA reshards on the fly where the update touches
    sharded params). Returns (jitted_step, state placed onto the mesh) —
    the combined dp x mp configuration, the superset of the reference's
    DDP (data axis) and its 2-device layer-split demo (model axis)."""
    repl = NamedSharding(mesh, P())
    st_sh = TrainState(
        step=repl,
        params=jax.tree.map(lambda spec: NamedSharding(mesh, spec), param_specs),
        batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
        opt_state=jax.tree.map(lambda _: repl, state.opt_state),
        apply_fn=state.apply_fn,
        tx=state.tx,
    )
    placed = jax.device_put(state, st_sh)
    data_sh = NamedSharding(mesh, P(data_axis))
    step = jax.jit(
        base_train_step,
        in_shardings=(st_sh, data_sh, data_sh, repl),
        out_shardings=(st_sh, repl),
    )
    return step, placed
