"""Pipeline-parallel transformer models — the model-level layer over
parallel/pipeline.py's GPipe schedule.

``make_pipeline_fn`` pipelines any homogeneous stage chain; this module
stages the actual ``TransformerBlock`` stack of the registry's
transformer families (BinarizedTransformer / BinarizedLM,
models/transformer.py) through it, so pipeline parallelism is a
*trainable Trainer configuration* (``--pp N``), not a library primitive.

The reference's only model parallelism is a 2-device layer placement
with no microbatching (mnist-distributed-BNNS2.py:32-46); this is the
TPU-native superset: stage s owns ``depth/N`` consecutive blocks
(parameters sharded over the 'pipe' mesh axis), microbatches stream
through the ring schedule, embeddings/head stay replicated (they are a
tiny fraction of parameters and their compute is one tick of the
pipeline).

Parameter layout: a pipelined state stores
``{"blocks": stage-major stacked block params, "rest": everything
else}``; ``split_block_params`` / ``merge_block_params`` convert to and
from the sequential layout (checkpoint interchange + the equality tests
in tests/test_pipeline_model.py).

Dropout (round 5): trains pipelined. Each (block, microbatch) cell
draws an independent mask from a schedule-invariant key —
``fold_in(fold_in(step_rng, microbatch), stage)`` then per-block in-stage
fold (``_make_stage_fn``) — so the masks are deterministic given the step
rng regardless of which schedule executes the cells. The draws differ
from the sequential ``model.apply`` stream (flax folds rngs by module
path, which stage-stacking erases); the contract is distributional
equivalence + schedule invariance, pinned against an rng-matched
sequential oracle in tests.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.layers import BinarizedDense
from ..models.transformer import (
    BinarizedLM,
    BinarizedTransformer,
    TransformerBlock,
)
from .pipeline import make_pipeline_fn

_BLOCK = "TransformerBlock_"


def split_block_params(params: Dict) -> Tuple[Any, Dict, List[str]]:
    """Sequential params -> (stage-major stacked blocks, rest, names).

    The stacked pytree's leaves get a new leading ``depth`` axis in block
    order; ``rest`` holds embeddings / final norm / head."""
    names = sorted(
        (k for k in params if k.startswith(_BLOCK)),
        key=lambda k: int(k.rsplit("_", 1)[1]),
    )
    if not names:
        raise ValueError("params contain no TransformerBlock_* submodules")
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs), *(params[n] for n in names)
    )
    rest = {k: v for k, v in params.items() if k not in set(names)}
    return stacked, rest, names


def merge_block_params(stacked: Any, rest: Dict, names: List[str]) -> Dict:
    """Inverse of ``split_block_params``."""
    out = dict(rest)
    for i, n in enumerate(names):
        out[n] = jax.tree.map(lambda x, i=i: x[i], stacked)
    return out


def _block_module(model) -> TransformerBlock:
    """The stage's block module, rebuilt from the parent model's knobs."""
    return TransformerBlock(
        model.embed_dim,
        model.num_heads,
        mlp_ratio=model.mlp_ratio,
        dropout=model.dropout,
        attention=model.attention,
        attention_fn=model.attention_fn,
        causal=isinstance(model, BinarizedLM),
        ste=model.ste,
        stochastic=model.stochastic,
        scale=model.scale,
        backend=model.backend,
        binarized=model.binarized,
        binarized_attention=model.binarized_attention,
    )


def _make_stage_fn(
    model, blocks_per_stage: int, *, train: bool = False
) -> Callable:
    """stage params (blocks_per_stage, ...) -> apply that many blocks.

    The train variant is ``(p_group, x, rng) -> x`` where ``rng`` is the
    pipeline's per-(stage, microbatch) cell key (make_pipeline_fn):
    block ``i`` of the stage folds it by its in-stage index, so every
    (block, microbatch) pair draws an independent, schedule-invariant
    dropout/stochastic-binarize mask. The draws intentionally do NOT
    reproduce the sequential ``model.apply`` stream (flax folds by
    module path, which pipelining erases) — the contract is
    distributional equivalence plus schedule-invariance, pinned by the
    rng-matched sequential oracle in tests/test_pipeline_model.py."""
    block = _block_module(model)
    needs_rng = bool(model.dropout) or bool(model.stochastic)

    if not (train and needs_rng):

        def stage_fn(p_group, x):
            def body(carry, p_one):
                return block.apply({"params": p_one}, carry), None

            x, _ = jax.lax.scan(body, x, p_group)
            return x

        return stage_fn

    def stage_fn_train(p_group, x, rng):
        def body(carry, xs):
            p_one, i = xs
            d_rng, b_rng = jax.random.split(jax.random.fold_in(rng, i))
            rngs = {}
            if model.dropout:
                rngs["dropout"] = d_rng
            if model.stochastic:
                rngs["binarize"] = b_rng
            y = block.apply(
                {"params": p_one}, carry, train=True, rngs=rngs
            )
            return y, None

        x, _ = jax.lax.scan(
            body, x, (p_group, jnp.arange(blocks_per_stage))
        )
        return x

    return stage_fn_train


def _vit_embed(model: BinarizedTransformer, rest: Dict, x: jnp.ndarray):
    """Patchify + binarized patch embedding + pos embed — the pre-block
    part of BinarizedTransformer.__call__ (models/transformer.py)."""
    b, h, w, c = x.shape
    p = model.patch_size
    nh, nw = h // p, w // p
    x = x.reshape(b, nh, p, nw, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, nh * nw, p * p * c)
    embed = BinarizedDense(
        model.embed_dim, binarize_input=False, ste=model.ste,
        backend=model.backend,
    )
    x = embed.apply({"params": rest["BinarizedDense_0"]}, x)
    return x + rest["pos_embed"]


def _vit_head(model: BinarizedTransformer, rest: Dict, x: jnp.ndarray):
    x = nn.LayerNorm().apply({"params": rest["ln_head"]}, x).mean(axis=1)
    x = nn.Dense(model.num_classes).apply({"params": rest["head"]}, x)
    return nn.log_softmax(x)


def _lm_embed(model: BinarizedLM, rest: Dict, tokens: jnp.ndarray):
    t = tokens.shape[1]
    x = nn.Embed(model.vocab, model.embed_dim).apply(
        {"params": rest["tok_embed"]}, tokens
    )
    return x + rest["pos_embed"][:, :t]


def _lm_head(model: BinarizedLM, rest: Dict, x: jnp.ndarray):
    x = nn.LayerNorm().apply({"params": rest["ln_head"]}, x)
    return nn.log_softmax(
        nn.Dense(model.vocab).apply({"params": rest["head"]}, x)
    )


def make_pipelined_apply(
    model,
    mesh: Mesh,
    depth: int,
    *,
    axis: str = "pipe",
    n_micro: int = 0,
    batch_axis: str | None = None,
    stage_remat: bool = False,
) -> Callable:
    """Build an ``apply_fn(variables, x, train=..., rngs=..., mutable=...)``
    running the model's block stack as a GPipe pipeline over ``axis``.

    Drop-in for ``model.apply`` in the trainer's step body (same call
    contract: returns ``(out, {})`` when ``mutable`` is non-empty). The
    variables' params must be in the pipelined layout
    ``{"blocks": stacked, "rest": rest}`` (see ``pipeline_params``).
    ``n_micro=0`` defaults to the stage count.

    ``batch_axis``: second mesh axis for DP x PP — the batch dim is
    sharded over it through the pipeline (see make_pipeline_fn); the
    embed/head/loss stages outside the shard_map ride the same sharding
    under jit/GSPMD.

    Dropout (and stochastic binarization) train pipelined: ``train=True``
    routes through a second pipeline program whose stages draw
    per-(block, microbatch) schedule-invariant masks from the step's
    ``rngs`` (see ``_make_stage_fn``); ``train=False`` (eval) runs the
    deterministic program.

    ``stage_remat``: checkpoint each stage execution — 1F1B-class
    activation memory (make_pipeline_fn docstring / PERF.md)."""
    n_stages = mesh.shape[axis]
    dp_size = mesh.shape[batch_axis] if batch_axis else 1
    if depth % n_stages:
        raise ValueError(
            f"model depth {depth} not divisible by pipeline stages "
            f"{n_stages}"
        )
    blocks_per_stage = depth // n_stages
    n_micro = n_micro or n_stages
    if isinstance(model, BinarizedTransformer):
        embed, head = _vit_embed, _vit_head
    elif isinstance(model, BinarizedLM):
        embed, head = _lm_embed, _lm_head
    else:
        raise ValueError(
            "pipeline parallelism supports the transformer families "
            f"(BinarizedTransformer / BinarizedLM), got {type(model).__name__}"
        )
    pipe_eval = make_pipeline_fn(
        mesh, _make_stage_fn(model, blocks_per_stage),
        axis=axis, n_micro=n_micro, batch_axis=batch_axis,
        stage_remat=stage_remat,
    )
    train_needs_rng = bool(model.dropout) or bool(model.stochastic)
    pipe_train = (
        make_pipeline_fn(
            mesh, _make_stage_fn(model, blocks_per_stage, train=True),
            axis=axis, n_micro=n_micro, batch_axis=batch_axis,
            stage_takes_rng=True, stage_remat=stage_remat,
        )
        if train_needs_rng
        else pipe_eval
    )

    def apply_fn(variables, x, train=False, rngs=None, mutable=()):
        params = variables["params"]
        stacked, rest = params["blocks"], params["rest"]
        # (depth, ...) -> (n_stages, blocks_per_stage, ...): stage-major
        # leading axis for the shard_map's P(axis) in_spec.
        grouped = jax.tree.map(
            lambda p: p.reshape(
                n_stages, blocks_per_stage, *p.shape[1:]
            ),
            stacked,
        )
        # The schedule needs each DP shard's local batch divisible by
        # n_micro; pad the global batch to a (dp * n_micro) multiple
        # (statically, the batch dim is a trace-time constant) and slice
        # back — partial final eval batches just ride a slightly padded
        # pipeline.
        b = x.shape[0]
        pad = (-b) % (n_micro * dp_size)
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad, *x.shape[1:]), x.dtype)]
            )
        h = embed(model, rest, x)
        if train and train_needs_rng:
            # The cell keys derive from one base stream: the 'dropout'
            # key for dropout models, else the 'binarize' key (stages
            # split per-purpose keys from the cell key — _make_stage_fn).
            need = "dropout" if model.dropout else "binarize"
            if not rngs or need not in rngs:
                raise ValueError(
                    "pipelined train step with dropout/stochastic "
                    f"binarization needs rngs={{'{need}': key}}"
                )
            h = pipe_train(grouped, h, rngs[need])
        else:
            h = pipe_eval(grouped, h)
        out = head(model, rest, h)[:b]
        if mutable:
            return out, {}
        return out

    return apply_fn


def pipeline_params(params: Dict) -> Dict:
    """Sequential params dict -> pipelined layout {"blocks", "rest"}."""
    stacked, rest, _ = split_block_params(params)
    return {"blocks": stacked, "rest": rest}


def sequential_params(pipelined: Dict, depth: int) -> Dict:
    """Pipelined layout -> sequential params dict (checkpoint export)."""
    names = [f"{_BLOCK}{i}" for i in range(depth)]
    return merge_block_params(pipelined["blocks"], pipelined["rest"], names)


def pipelined_state_shardings(state, mesh: Mesh, *, axis: str = "pipe"):
    """TrainState-of-NamedShardings for a pipelined run: block params
    (and their optimizer moments) sharded stage-major over ``axis``, the
    rest replicated. Shared by the initial placement
    (``place_pipelined_state``) and the multi-step scan dispatch
    (train.make_train_scan's ``state_shardings``)."""
    repl = NamedSharding(mesh, P())
    blocks_sh = NamedSharding(mesh, P(axis))

    def spec_like(tree):
        def leaf_spec(path, _):
            keys = [getattr(p, "key", None) for p in path]
            return blocks_sh if "blocks" in keys else repl

        flat = jax.tree_util.tree_flatten_with_path(tree)
        specs = [leaf_spec(path, leaf) for path, leaf in flat[0]]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(tree), specs
        )

    return state.replace(
        step=repl,
        params=spec_like(state.params),
        batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
        opt_state=spec_like(state.opt_state),
    )


def place_pipelined_state(state, mesh: Mesh, *, axis: str = "pipe"):
    """device_put a pipelined TrainState onto the mesh: block params (and
    their optimizer moments) sharded stage-major over ``axis``, the rest
    replicated — each stage's weights and Adam moments live only on the
    devices that run it (ZeRO-style memory scaling along the pipeline)."""
    return jax.device_put(
        state, pipelined_state_shardings(state, mesh, axis=axis)
    )
