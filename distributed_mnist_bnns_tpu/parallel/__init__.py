from .fsdp import (
    fsdp_shardings,
    make_fsdp_train_step,
    shard_state_fsdp,
)
from .mesh import make_hybrid_mesh, make_mesh
from .distributed import initialize_multihost
from .data_parallel import (
    make_dp_train_step,
    make_shardmap_dp_train_step,
    shard_batch,
    replicate,
)
from .model_parallel import bnn_mlp_tp_rules, make_tp_train_step
from .ring_attention import attention_reference, make_ring_attention
from .pipeline import make_pipeline_fn, sequential_reference
from .expert_parallel import (
    init_expert_params,
    make_expert_parallel_moe,
    moe_reference,
)

__all__ = [
    "make_mesh",
    "make_hybrid_mesh",
    "fsdp_shardings",
    "make_fsdp_train_step",
    "shard_state_fsdp",
    "initialize_multihost",
    "make_dp_train_step",
    "make_shardmap_dp_train_step",
    "shard_batch",
    "replicate",
    "bnn_mlp_tp_rules",
    "make_tp_train_step",
    "attention_reference",
    "make_ring_attention",
    "make_pipeline_fn",
    "sequential_reference",
    "init_expert_params",
    "make_expert_parallel_moe",
    "moe_reference",
]
