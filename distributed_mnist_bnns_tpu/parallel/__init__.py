from .compat import shard_map
from .fsdp import (
    compressed_state_shardings,
    compressed_state_specs,
    fsdp_shardings,
    fsdp_state_shardings,
    make_fsdp_train_step,
    place_compressed_state,
    shard_state_fsdp,
)
from .mesh import make_hybrid_mesh, make_mesh
from .remesh import (
    fold_worker_rows,
    mesh_topology,
    refold_segment_rows,
    remesh_compress_state,
)
from .distributed import initialize_multihost
from .data_parallel import (
    make_compressed_dp_train_step,
    make_compressed_fsdp_train_step,
    make_dp_train_step,
    make_shardmap_dp_train_step,
    shard_batch,
    replicate,
)
from .model_parallel import (
    bnn_mlp_tp_rules,
    make_tp_train_step,
    tp_rules_by_path,
    tp_rules_for,
)
from .ring_attention import attention_reference, make_ring_attention
from .pipeline import (
    make_pipeline_fn,
    pipeline_bubble_fraction,
    sequential_reference,
    sequential_reference_rng,
)
from .tp_pipeline import (
    init_tp_pipeline_params,
    make_tp_pipeline_fn,
    tp_pipeline_param_specs,
    tp_pipeline_reference,
)
from .pipeline_model import (
    make_pipelined_apply,
    pipelined_state_shardings,
    merge_block_params,
    pipeline_params,
    place_pipelined_state,
    sequential_params,
    split_block_params,
)
from .expert_parallel import (
    init_expert_params,
    load_balance_loss,
    make_expert_parallel_moe,
    moe_reference,
    top1_dispatch,
    topk_dispatch,
)

__all__ = [
    "shard_map",
    "make_mesh",
    "make_hybrid_mesh",
    "fold_worker_rows",
    "mesh_topology",
    "refold_segment_rows",
    "remesh_compress_state",
    "compressed_state_shardings",
    "compressed_state_specs",
    "fsdp_shardings",
    "fsdp_state_shardings",
    "make_fsdp_train_step",
    "place_compressed_state",
    "shard_state_fsdp",
    "initialize_multihost",
    "make_compressed_dp_train_step",
    "make_compressed_fsdp_train_step",
    "make_dp_train_step",
    "make_shardmap_dp_train_step",
    "shard_batch",
    "replicate",
    "bnn_mlp_tp_rules",
    "make_tp_train_step",
    "tp_rules_by_path",
    "tp_rules_for",
    "attention_reference",
    "make_ring_attention",
    "make_pipeline_fn",
    "pipeline_bubble_fraction",
    "init_tp_pipeline_params",
    "make_tp_pipeline_fn",
    "tp_pipeline_param_specs",
    "tp_pipeline_reference",
    "sequential_reference",
    "sequential_reference_rng",
    "make_pipelined_apply",
    "pipeline_params",
    "sequential_params",
    "split_block_params",
    "merge_block_params",
    "pipelined_state_shardings",
    "place_pipelined_state",
    "init_expert_params",
    "make_expert_parallel_moe",
    "moe_reference",
    "top1_dispatch",
    "topk_dispatch",
    "load_balance_loss",
]
