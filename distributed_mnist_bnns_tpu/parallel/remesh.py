"""Elastic re-placement of training state across data-parallel worlds.

A preempted worker used to cost the whole job: the mesh is sized at
launch, and every ``(world, ...)``-shaped buffer the 1-bit gradient
exchange keeps in optimizer state (ops/comm_compress, PERF.md "Gradient
comms") is laid out for exactly that world. This module is the state
half of elastic membership (resilience/elastic, RESILIENCE.md "Elastic
membership"): given a checkpoint written at world ``W_old`` and a run
rebuilt at world ``W_new``, it re-places every compression-state row
onto the new topology so training continues instead of restarting.

Two distinct row semantics, two distinct re-placements:

* **per-worker rows** (``ef_residual`` — one private error-feedback
  residual per worker over the padded flat gradient): the exchange
  combines worker contributions by MEAN, and a shrink re-shards the
  batch so new worker *j*'s gradient stream is the mean of the old
  workers it absorbed — the contribution-preserving re-placement is the
  groupwise MEAN of adjacent rows (``mean_j e'_j == mean_i e_i``: no
  error mass enters or leaves through the combine). A regrow re-splits
  by copying each row to its successors, preserving the mean the same
  way. (:func:`fold_worker_rows`)
* **per-segment-owner rows** (``ef_residual2``, and the base
  optimizer's moments inside ``FsdpCompressState.inner`` — row *j*
  covers parameter positions ``[j*seg, (j+1)*seg)``): flattened, these
  rows are ONE vector indexed by padded parameter position, so the
  re-placement is position-preserving — flatten, copy, reshape to the
  new ``(world, seg)`` layout. Every parameter keeps exactly its own
  adam moments / owner residual; a world-8 → world-4 shrink folds
  adjacent segment-row PAIRS into one row, a regrow re-splits them.
  (:func:`refold_segment_rows`)

Width changes (the plans' ``padded``/``seg`` differ across worlds) copy
the overlapping prefix; positions at/after ``n_params`` are zero by the
transforms' invariant (they zero the pad tails every step), so nothing
real is truncated. All functions are host-side NumPy on the restored
host arrays — the jitted step's pinned ``in_shardings`` place the
re-folded state onto the new mesh on the first dispatch.

Proven by tests/test_elastic.py: NumPy oracles for both fold rules, and
bitwise equality of the post-shrink trajectory against a fresh world-N
run resumed from the same checkpoint generation. The step that runs
immediately AFTER a remesh is additionally lockstep-checked: the
``remesh_fold_regrow`` program in ``analysis/spmd.py`` re-places
exchange state across worlds (8→2, 8→4, 4→8) and verifies every
process of the new world issues the identical collective schedule (CI
``spmd-lockstep`` — a fold that desynced one process's schedule would
hang a real multi-host fleet at the first post-resize exchange).
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

import jax
import numpy as np

from ..ops.comm_compress import CommPlan, make_plan

log = logging.getLogger(__name__)


def mesh_topology(mesh) -> Tuple[int, dict]:
    """``(data-parallel world size, {axis: size})`` for a mesh
    (``None`` → ``(1, {})``) — the fields checkpoint meta and the
    resume/restart/remesh events record so post-incident forensics can
    see whether a restore changed topology."""
    if mesh is None:
        return 1, {}
    shape = {str(k): int(v) for k, v in mesh.shape.items()}
    return int(shape.get("data", 1)), shape


def fold_worker_rows(
    rows: np.ndarray, new_world: int, new_width: int
) -> np.ndarray:
    """Re-place per-WORKER residual rows ``(old_world, old_width)`` →
    ``(new_world, new_width)``.

    Shrink (``old_world % new_world == 0``): groupwise mean of adjacent
    rows — new worker *j* absorbs old workers ``[g*j, g*(j+1))``, the
    same contiguous re-sharding the batch axis undergoes. Grow
    (``new_world % old_world == 0``): each row is copied to its ``g``
    successors. Anything else has no contiguous worker mapping and
    raises. See the module docstring for why MEAN/copy is the
    contribution-preserving choice under the exchange's mean combine.
    """
    rows = np.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(f"worker rows must be 2-D, got {rows.shape}")
    old_world, old_width = rows.shape
    if old_world == new_world:
        folded = rows
    elif old_world % new_world == 0:
        g = old_world // new_world
        folded = rows.reshape(new_world, g, old_width).mean(axis=1)
    elif new_world % old_world == 0:
        g = new_world // old_world
        folded = np.repeat(rows, g, axis=0)
    else:
        raise ValueError(
            f"cannot re-place worker rows from world {old_world} to "
            f"{new_world}: one world size must divide the other"
        )
    out = np.zeros((new_world, new_width), rows.dtype)
    m = min(old_width, new_width)
    out[:, :m] = folded[:, :m]
    return out


def refold_segment_rows(
    rows: np.ndarray, new_world: int, new_seg: int
) -> np.ndarray:
    """Re-place per-SEGMENT-OWNER rows ``(old_world, old_seg)`` →
    ``(new_world, new_seg)`` position-preservingly: row *j* covers
    parameter positions ``[j*seg, (j+1)*seg)`` of the flattened params,
    so the rows concatenate to one position-indexed vector that is
    simply re-cut at the new segment boundaries (world-8 → world-4
    folds adjacent row pairs; regrow re-splits them). The tail at/after
    ``n_params`` is zero by the transforms' invariant."""
    rows = np.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(f"segment rows must be 2-D, got {rows.shape}")
    flat = rows.reshape(-1)
    out = np.zeros(new_world * new_seg, rows.dtype)
    m = min(flat.size, out.size)
    out[:m] = flat[:m]
    return out.reshape(new_world, new_seg)


def _old_plan(plan: CommPlan, old_world: int) -> CommPlan:
    """The checkpoint-side plan: same gradient, same knobs, old world."""
    return make_plan(
        plan.n_params, world=old_world, mode=plan.mode,
        bucket_size=plan.bucket_size, chunks=plan.chunks,
        layout=plan.layout,
    )


def _check_ef_widths(name: str, node, old: CommPlan) -> None:
    """The restored node must BE a world-``old.world`` layout of this
    plan — fold math on foreign shapes would quietly produce garbage."""
    ef = np.asarray(node.ef_residual)
    ef2 = np.asarray(node.ef_residual2)
    ok = (
        ef.ndim == 2 and ef2.ndim == 2
        and ef.shape[0] == old.world and ef2.shape[0] == old.world
        and ef.shape[1] in (0, old.padded)
        and ef2.shape[1] in (0, old.seg)
    )
    if not ok:
        raise ValueError(
            f"{name} rows {ef.shape}/{ef2.shape} do not match the "
            f"world-{old.world} plan (padded={old.padded}, "
            f"seg={old.seg}) — checkpoint from a different model/"
            "bucket configuration, not just a different world"
        )


def remesh_compress_state(
    opt_state: Any, plan: CommPlan
) -> Tuple[Any, int]:
    """Re-place every 1-bit-exchange compression node in ``opt_state``
    (restored from a checkpoint at a different world size, as host
    arrays) onto ``plan``'s world. Returns ``(new_opt_state,
    nodes_replaced)``; nodes already at ``plan.world`` pass through
    untouched, so the call is idempotent. Zero-width EF rows (the
    stateless ``sign`` mode) stay zero-width."""
    from ..train.optim import (  # local import (parallel <-> train cycle)
        FsdpCompressState,
        SignCompressState,
    )

    replaced = 0

    def fold(node):
        nonlocal replaced
        if not isinstance(node, (SignCompressState, FsdpCompressState)):
            return node
        old_world = int(np.asarray(node.ef_residual).shape[0])
        if old_world == plan.world:
            return node
        old = _old_plan(plan, old_world)
        name = type(node).__name__
        _check_ef_widths(name, node, old)
        ef_w = plan.padded if np.asarray(node.ef_residual).shape[1] else 0
        ef2_w = plan.seg if np.asarray(node.ef_residual2).shape[1] else 0
        ef = fold_worker_rows(node.ef_residual, plan.world, ef_w)
        ef2 = refold_segment_rows(node.ef_residual2, plan.world, ef2_w)
        replaced += 1
        log.info(
            "remesh: re-placed %s world %d -> %d (seg %d -> %d)",
            name, old_world, plan.world, old.seg, plan.seg,
        )
        if isinstance(node, SignCompressState):
            return SignCompressState(ef_residual=ef, ef_residual2=ef2)

        def fold_inner(leaf):
            arr = np.asarray(leaf)
            if arr.shape == (old_world, old.seg):
                return refold_segment_rows(arr, plan.world, plan.seg)
            if arr.ndim == 0 or arr.shape == (plan.world, plan.seg):
                return leaf
            raise ValueError(
                f"unexpected base-optimizer state leaf {arr.shape} in "
                f"{name}.inner (want ({old_world}, {old.seg}) segment "
                "rows or a scalar) — cannot re-place"
            )

        return FsdpCompressState(
            ef_residual=ef, ef_residual2=ef2,
            inner=jax.tree.map(fold_inner, node.inner),
        )

    new_state = jax.tree.map(
        fold, opt_state,
        is_leaf=lambda n: isinstance(
            n, (SignCompressState, FsdpCompressState)
        ),
    )
    return new_state, replaced
