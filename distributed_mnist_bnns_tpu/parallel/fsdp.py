"""FSDP / ZeRO-style fully sharded data parallelism.

Beyond-parity capability (the reference's only memory strategy is "fit on
one GPU"): parameters, gradients, and optimizer state are *sharded* over
the 'data' mesh axis instead of replicated, so per-device memory for
state scales as 1/N while the training math stays identical to plain DP.

TPU-native formulation: no hand-written gather/scatter — each param leaf
gets a PartitionSpec sharding its largest divisible axis over 'data', the
jitted step runs with those shardings pinned on inputs and outputs, and
GSPMD materializes the ZeRO-3 schedule itself (all-gather params for
fwd/bwd, reduce-scatter grads, sharded optimizer update) on ICI. This is
the standard JAX FSDP recipe: sharding annotations in, collective
schedule out.

Exactness: tested equal to the single-device step (tests/test_fsdp.py).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.trainer import TrainState


def fsdp_spec(leaf: Any, n_shards: int, axis: str = "data") -> P:
    """PartitionSpec sharding the leaf's largest n_shards-divisible axis;
    replicated if no axis divides (small biases, scalars)."""
    shape = getattr(leaf, "shape", ())
    if not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % n_shards == 0 and shape[i] >= n_shards:
            spec = [None] * len(shape)
            spec[i] = axis
            return P(*spec)
    return P()


def fsdp_shardings(tree: Any, mesh: Mesh, axis: str = "data") -> Any:
    """NamedSharding tree: every array leaf sharded per fsdp_spec."""
    n = mesh.shape[axis]
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, fsdp_spec(leaf, n, axis)), tree
    )


def _place_fsdp_leaf(leaf: Any, sh: NamedSharding, axis: str):
    """Place one leaf on its FSDP sharding.

    Single-process: device_put. Multi-process: device_put cannot address
    remote devices; every host holds the identical full value (the DDP
    same-seed contract), so make_array_from_callback hands each local
    device exactly the slice the sharding assigns it — correct for any
    layout, no hand-rolled chunk arithmetic."""
    del axis
    if jax.process_count() == 1:
        return jax.device_put(leaf, sh)
    leaf = np.asarray(leaf)
    return jax.make_array_from_callback(
        leaf.shape, sh, lambda idx: leaf[idx]
    )


def shard_state_fsdp(state: TrainState, mesh: Mesh, axis: str = "data"
                     ) -> TrainState:
    """Place params/opt_state/batch_stats on their FSDP shardings (step
    counter replicated). Works multi-process: each host contributes the
    slice its devices own from the identically-initialized full state
    (the DDP same-seed contract, mnist-dist2.py:85-93)."""
    put = lambda tree: jax.tree.map(
        lambda leaf, sh: _place_fsdp_leaf(leaf, sh, axis),
        tree, fsdp_shardings(tree, mesh, axis),
    )
    return state.replace(
        step=_place_fsdp_leaf(
            state.step, NamedSharding(mesh, P()), axis
        ),
        params=put(state.params),
        batch_stats=put(state.batch_stats),
        opt_state=put(state.opt_state),
    )


def fsdp_state_shardings(
    state: TrainState, mesh: Mesh, axis: str = "data"
) -> TrainState:
    """The TrainState-of-NamedShardings for an FSDP layout (step counter
    replicated, everything else per fsdp_spec) — shared by the per-step
    wrapper below and the multi-step scan dispatch
    (train/trainer.make_train_scan(state_shardings=...))."""
    return TrainState(
        step=NamedSharding(mesh, P()),
        params=fsdp_shardings(state.params, mesh, axis),
        batch_stats=fsdp_shardings(state.batch_stats, mesh, axis),
        opt_state=fsdp_shardings(state.opt_state, mesh, axis),
        apply_fn=state.apply_fn,
        tx=state.tx,
    )


def make_fsdp_train_step(
    base_step: Callable,
    mesh: Mesh,
    state: TrainState,
    *,
    axis: str = "data",
) -> Callable:
    """Wrap a (state, images, labels, rng) train step with FSDP shardings.

    ``base_step`` is the unjitted-or-jitted single-device step (e.g.
    make_train_step(..., donate=False)); the returned step expects a state
    already placed via shard_state_fsdp and batch inputs sharded on
    ``axis``. Output state shardings are pinned to the input shardings so
    the optimizer update itself runs sharded (ZeRO's key property) rather
    than being all-gathered back.
    """
    state_sh = fsdp_state_shardings(state, mesh, axis)
    data_sh = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    metrics_sh = repl

    return jax.jit(
        base_step,
        in_shardings=(state_sh, data_sh, data_sh, repl),
        out_shardings=(state_sh, metrics_sh),
        donate_argnums=(0,),
    )


# -- compressed-DP / compressed-FSDP state layout ---------------------------
#
# The 1-bit gradient exchange (ops/comm_compress, PERF.md "Gradient
# comms") keeps per-worker error-feedback residuals in optimizer state
# with a leading ``world`` axis. Sharding that axis over 'data' is the
# ZeRO move this module exists for: the buffers checkpoint as ordinary
# global arrays (bitwise save/restore) while each device materializes
# only its own worker's row — one fp32 residual, the cost of a momentum
# buffer, instead of N of them. The compressed-FSDP layout
# (train/optim.sign_compress_fsdp) extends the same rule to the BASE
# optimizer's state: its moments live in (world, seg) flat-segment rows
# inside FsdpCompressState.inner, so adam's mu/nu cost 1/N per device —
# ZeRO's optimizer-state sharding, expressed as the same leading-axis
# PartitionSpec.


def compressed_state_specs(state: Any, axis: str = "data") -> Any:
    """TrainState-of-PartitionSpecs for the compressed shard_map steps
    (DP and FSDP layouts): everything replicated except the compression
    state, whose leading world axis is sharded over ``axis`` (each
    worker sees its own (1, ...) slice inside the shard_map body).
    For FsdpCompressState that covers the wrapped base optimizer's
    (world, seg) moment rows too; its scalar leaves (step counts) stay
    replicated."""
    from ..train.optim import (  # local import (cycle)
        FsdpCompressState,
        SignCompressState,
    )

    def mark(node):
        if isinstance(node, SignCompressState):
            return SignCompressState(
                ef_residual=P(axis), ef_residual2=P(axis)
            )
        if isinstance(node, FsdpCompressState):
            return FsdpCompressState(
                ef_residual=P(axis),
                ef_residual2=P(axis),
                inner=jax.tree.map(
                    lambda leaf: (
                        P(axis) if getattr(leaf, "ndim", 0) >= 1 else P()
                    ),
                    node.inner,
                ),
            )
        return jax.tree.map(lambda _: P(), node)

    opt_specs = jax.tree.map(
        mark, state.opt_state,
        is_leaf=lambda n: isinstance(
            n, (SignCompressState, FsdpCompressState)
        ),
    )
    repl = jax.tree.map(lambda _: P(), state)
    return repl.replace(opt_state=opt_specs)


def compressed_state_shardings(
    state: Any, mesh: Mesh, axis: str = "data"
) -> Any:
    """NamedSharding tree matching ``compressed_state_specs``."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        compressed_state_specs(state, axis),
        is_leaf=lambda n: isinstance(n, P),
    )


def place_compressed_state(
    state: Any, mesh: Mesh, axis: str = "data"
) -> Any:
    """Place a host/replicated TrainState onto the compressed-DP layout
    (residual rows to their owning devices, everything else replicated).
    Multi-process-safe via the same callback placement as FSDP."""
    return jax.tree.map(
        lambda leaf, sh: _place_fsdp_leaf(leaf, sh, axis),
        state, compressed_state_shardings(state, mesh, axis),
    )


def fsdp_memory_fraction(params: Any, mesh: Mesh, axis: str = "data"
                         ) -> float:
    """Fraction of replicated-param bytes each device holds under FSDP
    (1/N in the limit; > 1/N when small leaves stay replicated)."""
    n = mesh.shape[axis]
    total, local = 0, 0
    for leaf in jax.tree.leaves(params):
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        total += size
        local += size // n if fsdp_spec(leaf, n, axis) != P() else size
    return local / max(total, 1)
