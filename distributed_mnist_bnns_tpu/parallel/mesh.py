"""Device-mesh construction.

The reference's "mesh" is rank arithmetic: world_size = gpus*nodes,
rank = nr*gpus + gpu (mnist-dist2.py:40,82). TPU-native, the same role is
played by a jax.sharding.Mesh whose axes name the parallelism dimensions;
collectives then ride ICI within a slice and DCN across slices, placed by
XLA from sharding annotations rather than hand-written NCCL/Gloo calls
(SURVEY §2.3)."""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    data: int | None = None,
    model: int = 1,
    *,
    axis_names: Sequence[str] = ("data", "model"),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a (data x model) mesh over the available devices.

    data=None uses every remaining device for data parallelism — the
    analogue of the reference's world_size = gpus * nodes.
    """
    devs = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    need = data * model
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.asarray(devs[:need]).reshape(data, model)
    return Mesh(grid, axis_names=tuple(axis_names))
