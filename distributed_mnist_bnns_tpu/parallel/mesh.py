"""Device-mesh construction.

The reference's "mesh" is rank arithmetic: world_size = gpus*nodes,
rank = nr*gpus + gpu (mnist-dist2.py:40,82). TPU-native, the same role is
played by a jax.sharding.Mesh whose axes name the parallelism dimensions;
collectives then ride ICI within a slice and DCN across slices, placed by
XLA from sharding annotations rather than hand-written NCCL/Gloo calls
(SURVEY §2.3)."""

from __future__ import annotations

import logging
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger(__name__)


def make_mesh(
    data: int | None = None,
    model: int = 1,
    *,
    axis_names: Sequence[str] = ("data", "model"),
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a (data x model) mesh over the available devices.

    data=None uses every remaining device for data parallelism — the
    analogue of the reference's world_size = gpus * nodes.
    """
    devs = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    need = data * model
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    grid = np.asarray(devs[:need]).reshape(data, model)
    return Mesh(grid, axis_names=tuple(axis_names))


def make_hybrid_mesh(
    ici_axes: dict[str, int],
    *,
    dcn_axis: str = "replica",
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build an (n_slices, *ici_shape) mesh whose leading axis crosses the
    DCN boundary and whose trailing axes stay within a slice's ICI.

    Multi-slice layout rule (the scaling-book recipe): put the
    bandwidth-hungry shardings (tp/sp/ep) on ICI axes and the
    gradient-all-reduce (dp) on the slower DCN axis — gradients are summed
    once per step, activations move constantly. Grouping devices by
    ``slice_index`` makes XLA place each trailing-axis collective entirely
    on ICI; only the leading axis's psum crosses DCN.

    On hardware without slice topology (CPU simulation, single slice),
    devices are grouped by process index instead (equivalent for the
    one-process-per-host layout), falling back to equal chunks.

    ``ici_axes`` maps axis name -> size, e.g. {"data": 2, "model": 2};
    n_slices is inferred as device_count / prod(ici_sizes).
    """
    devs = list(devices if devices is not None else jax.devices())
    ici = 1
    for v in ici_axes.values():
        ici *= v
    if len(devs) % ici:
        raise ValueError(
            f"{len(devs)} devices not divisible by ICI shape {ici_axes}"
        )
    n_slices = len(devs) // ici
    ordered = _group_devices_by_slice(devs, n_slices, ici)
    grid = np.asarray(ordered).reshape(n_slices, *ici_axes.values())
    return Mesh(grid, axis_names=(dcn_axis, *ici_axes.keys()))


def _group_devices_by_slice(devs, n_slices: int, ici: int) -> list:
    """Order devices slice-major so a reshape to (n_slices, ici) puts
    each DCN group in one row: grouped by ``slice_index`` (real
    multi-slice topology), falling back to ``process_index``
    (one-process-per-host layouts), falling back to contiguous chunks
    with a warning when neither matches the requested shape."""

    def group_key(d):
        idx = getattr(d, "slice_index", None)
        if idx is not None:
            return idx
        return getattr(d, "process_index", 0)

    keys = sorted({group_key(d) for d in devs})
    if len(keys) == n_slices and all(
        sum(1 for d in devs if group_key(d) == k) == ici for k in keys
    ):
        return [d for k in keys for d in devs if group_key(d) == k]
    if n_slices > 1:  # no usable topology info — contiguous equal chunks
        log.warning(
            "make_hybrid_mesh: device slice/process grouping does not "
            "match %d slices of %d devices; falling back to contiguous "
            "chunks. On real multi-slice hardware this can place ICI "
            "axes across the DCN boundary — verify the mesh layout.",
            n_slices, ici,
        )
    return devs
