"""Packed-bitplane serving for the binarized conv families (bnn-cnn,
xnor-resnet18 and the bottleneck xnor-resnet50) — the conv extension of
infer.py's MLP freeze.

Same deployment story (infer.py module doc): after training, the fp32
latent masters are dead weight; hidden conv kernels pack to 1 bit per
parameter and every hidden GEMM runs on the bitplane XNOR kernel. The
conv-specific pieces:

  * **im2col packed GEMM** — a frozen BinarizedConv becomes patch
    extraction (``conv_general_dilated_patches``, the same lowering the
    training path uses, models/layers.py:236-244) followed by
    ``xnor_matmul_packed`` on the pre-packed (kh*kw*cin, F) bitplane
    matrix.
  * **SAME-padding correction** — zero border taps enter the ±1 GEMM as
    -1; the batch-independent correction (ops.conv_padding_correction,
    the same helper the training layer uses) is rebuilt at load from the
    shipped (kh, kw, F) per-tap channel sums for the declared input
    resolution — the runtime never needs the unpacked kernel, and the
    artifact stays dominated by the 1-bit weights.
  * **BN -> threshold after convs** — wherever the next consumer
    sign()-binarizes, ``binarize(hardtanh?(BN(y)))`` folds to the
    per-channel threshold compare of infer._bn_sign_fn; max-pooling
    commutes with the fold (sign and hardtanh are monotone, so
    ``sign(pool(hardtanh(bn(y)))) == pool(sign_thresh(y))``), so pooled
    hidden activations are ±1 bits end to end.
  * **fp32 first/last layers** — the stem conv, residual-shortcut 1x1
    convs, the final BN/relu (resnet) or BN/hardtanh (cnn) block and the
    classifier head stay full precision, exactly like the live model.

Frozen conv artifacts are resolution-specific (the padding corrections
bake in Ho x Wo); the apply fn checks and reports a shape mismatch.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .infer import _bn_affine_fn, _bn_sign_epilogue, _bn_sign_fn
from .models.bnn_cnn import BinarizedCNN
from .models.resnet import XnorResNet
from .ops.binarize import binarize_ste
from .ops.xnor_gemm import (
    conv_padding_correction,
    conv_patch_weight,
    prepack_weights,
    xnor_matmul_packed,
    xnor_matmul_packed_sign,
)

_HI = jax.lax.Precision.HIGHEST


def _out_hw(hw, strides):
    """SAME-padding output resolution."""
    return tuple(-(-d // s) for d, s in zip(hw, strides))


def _freeze_conv(
    kernel_latent: jnp.ndarray,
    bias: jnp.ndarray,
    in_hw: Tuple[int, int],
    strides: Tuple[int, int],
) -> Dict[str, Any]:
    """Freeze one hidden BinarizedConv: packed bitplanes (canonical
    im2col ordering, ops.conv_patch_weight — the same helper the training
    layer uses) plus the (kh, kw, F) per-tap channel sums from which the
    dense SAME-padding correction is rebuilt at load
    (ops.conv_padding_correction) — shipping the sums instead of the
    (Ho, Wo, F) map keeps the artifact dominated by the 1-bit weights."""
    kh, kw, in_ch, features = kernel_latent.shape
    wb = binarize_ste(kernel_latent)
    wp, k, n = prepack_weights(conv_patch_weight(wb))
    return {
        "wp": wp, "k": int(k), "n": int(n), "bias": bias,
        "kh": kh, "kw": kw, "strides": list(strides),
        "in_hw": list(in_hw),
        "tap_sums": jnp.sum(wb, axis=2),  # (kh, kw, F)
    }


def _packed_conv_fn(layer: Dict[str, Any], interpret: bool) -> Callable:
    wp = jnp.asarray(layer["wp"])
    bias = jnp.asarray(layer["bias"])
    k, n = int(layer["k"]), int(layer["n"])
    kh, kw = int(layer["kh"]), int(layer["kw"])
    strides = tuple(int(s) for s in layer["strides"])
    in_hw = tuple(int(d) for d in layer["in_hw"])
    corr = conv_padding_correction(
        jnp.asarray(layer["tap_sums"], jnp.float32), in_hw, strides, "SAME"
    )

    def fn(bits: jnp.ndarray) -> jnp.ndarray:
        if tuple(bits.shape[1:3]) != in_hw:
            raise ValueError(
                f"frozen conv was packed for {in_hw} inputs, got "
                f"{tuple(bits.shape[1:3])} (the padding correction is "
                "resolution-specific; re-freeze for this input size)"
            )
        patches = jax.lax.conv_general_dilated_patches(
            bits, filter_shape=(kh, kw), window_strides=strides,
            padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        nb, ho, wo, _ = patches.shape
        y = xnor_matmul_packed(
            patches.reshape(-1, k), wp, k, n, interpret=interpret
        ).reshape(nb, ho, wo, n)
        return y + corr + bias

    return fn


def _packed_conv1x1_sign_fn(
    layer: Dict[str, Any], avec, tvec, interpret: bool
) -> Callable:
    """Fused 1x1/stride-1 conv + next-BN threshold: a 1x1 SAME conv has
    no padding taps (corr == 0) and its im2col patches ARE the input, so
    the whole BN->sign->NEXT-layer handoff collapses into the packed
    GEMM's sign epilogue (ops.xnor_matmul_packed_sign) — the (B, H, W, F)
    fp32 pre-activation never round-trips HBM. Only built when the
    conv's sole consumer is the next pair's sign (block interiors)."""
    wp = jnp.asarray(layer["wp"])
    bias = jnp.asarray(layer["bias"])
    k, n = int(layer["k"]), int(layer["n"])
    in_hw = tuple(int(d) for d in layer["in_hw"])

    def fn(bits: jnp.ndarray) -> jnp.ndarray:
        if tuple(bits.shape[1:3]) != in_hw:
            raise ValueError(
                f"frozen conv was packed for {in_hw} inputs, got "
                f"{tuple(bits.shape[1:3])} (re-freeze for this size)"
            )
        nb, ho, wo, _ = bits.shape
        return xnor_matmul_packed_sign(
            bits.reshape(-1, k), wp, k, n, avec, tvec, bias,
            interpret=interpret,
        ).reshape(nb, ho, wo, n)

    return fn


def _fp32_conv_fn(kernel, bias, strides=(1, 1)):
    w = jnp.asarray(kernel, jnp.float32)
    b = jnp.asarray(bias, jnp.float32) if bias is not None else None

    def fn(x):
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32, precision=_HI,
        )
        return y if b is None else y + b

    return fn


def _maxpool_bits(x):
    """2x2/2 max-pool of ±1 maps (any +1 in the window wins)."""
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def _bn_pack(params, stats):
    return {"params": dict(params), "stats": dict(stats)}


# ---------------------------------------------------------------------------
# bnn-cnn


def _freeze_cnn_tensors(
    model: BinarizedCNN, variables: Dict, input_shape
) -> Dict[str, Any]:
    if model.stochastic:
        raise ValueError(
            "stochastic binarization is train-time; freeze the "
            "deterministic eval path"
        )
    if getattr(model, "scale", False):
        raise ValueError(
            "XNOR-Net alpha scaling (scale=True) is not folded by the "
            "packed freeze; freeze an unscaled model"
        )
    params, stats = variables["params"], variables["batch_stats"]
    h, w, c = input_shape
    hw1 = _out_hw((h, w), (1, 1))          # conv1 SAME/1
    hw_pool1 = (hw1[0] // 2, hw1[1] // 2)  # 2x2 pool
    frozen = {
        "family": "bnn-cnn",
        "arch": {"input_shape": list(input_shape)},
        # fp32 first layer: raw pixels x ±1 kernel as a real conv
        "conv1_w": binarize_ste(params["BinarizedConv_0"]["kernel"]),
        "conv1_b": params["BinarizedConv_0"]["bias"],
        "bn0": _bn_pack(params["BatchNorm_0"], stats["BatchNorm_0"]),
        "conv2": _freeze_conv(
            params["BinarizedConv_1"]["kernel"],
            params["BinarizedConv_1"]["bias"], hw_pool1, (1, 1),
        ),
        "bn1": _bn_pack(params["BatchNorm_1"], stats["BatchNorm_1"]),
        "bn2": _bn_pack(params["BatchNorm_2"], stats["BatchNorm_2"]),
        "head_w": params["Dense_0"]["kernel"],
        "head_b": params["Dense_0"]["bias"],
    }
    dense_w = binarize_ste(params["BinarizedDense_0"]["kernel"])
    wp, k, n = prepack_weights(dense_w)
    frozen["dense"] = {
        "wp": wp, "k": int(k), "n": int(n),
        "bias": params["BinarizedDense_0"]["bias"],
    }
    latent = sum(
        int(params[m]["kernel"].size) * 4
        for m in ("BinarizedConv_0", "BinarizedConv_1", "BinarizedDense_0")
    )
    packed = (
        int(frozen["conv1_w"].size) * 4
        + int(frozen["conv2"]["wp"].size) * 4
        + int(wp.size) * 4
    )
    frozen["info"] = {
        "family": "bnn-cnn",
        "latent_fp32_weight_bytes": latent,
        "frozen_weight_bytes": packed,
        "compression": round(latent / packed, 2),
        "packed_layers": ["BinarizedConv_1", "BinarizedDense_0"],
    }
    return frozen


def _build_cnn_apply(frozen: Dict[str, Any], interpret: bool) -> Callable:
    ishape = tuple(int(d) for d in frozen["arch"]["input_shape"])
    conv1 = _fp32_conv_fn(
        jnp.asarray(frozen["conv1_w"], jnp.float32), frozen["conv1_b"]
    )
    sign0 = _bn_sign_fn(frozen["bn0"]["params"], frozen["bn0"]["stats"])
    conv2 = _packed_conv_fn(frozen["conv2"], interpret)
    sign1 = _bn_sign_fn(frozen["bn1"]["params"], frozen["bn1"]["stats"])
    d = frozen["dense"]
    dwp, dk, dn = jnp.asarray(d["wp"]), int(d["k"]), int(d["n"])
    db = jnp.asarray(d["bias"])
    affine2 = _bn_affine_fn(frozen["bn2"]["params"], frozen["bn2"]["stats"])
    wh, bh = jnp.asarray(frozen["head_w"]), jnp.asarray(frozen["head_b"])

    def apply_fn(images: jnp.ndarray) -> jnp.ndarray:
        x = images.astype(jnp.float32)
        if x.ndim == 2:
            x = x.reshape(x.shape[0], *ishape)
        elif tuple(x.shape[1:]) != ishape:
            raise ValueError(
                f"frozen cnn expects {ishape} inputs, got "
                f"{tuple(x.shape[1:])} (the packed convs bake in this "
                "resolution; re-freeze for a different input size)"
            )
        y = conv1(x)
        bits = _maxpool_bits(sign0(y))
        y = conv2(bits)
        bits = _maxpool_bits(sign1(y))
        bits = bits.reshape(bits.shape[0], -1)
        y = xnor_matmul_packed(bits, dwp, dk, dn, interpret=interpret) + db
        h = jnp.clip(affine2(y), -1.0, 1.0)
        logits = jnp.dot(h, wh, preferred_element_type=jnp.float32) + bh
        return jax.nn.log_softmax(logits)

    return jax.jit(apply_fn)


# ---------------------------------------------------------------------------
# xnor-resnet (basic AND bottleneck blocks, CIFAR or ImageNet stem)


def _freeze_resnet_tensors(
    model: XnorResNet, variables: Dict, input_shape
) -> Dict[str, Any]:
    """Freeze basic-block (resnet18/CIFAR stem) AND bottleneck
    (resnet50/ImageNet stem) XNOR-ResNets: per block, each
    BN->sign->BinarizedConv pair folds to threshold + packed im2col
    GEMM; the residual stream, the fp32 stem (+maxpool for the ImageNet
    stem) and projection shortcuts stay full precision."""
    if model.scale:
        raise ValueError(
            "XNOR-Net alpha scaling (scale=True) rescales each conv's "
            "output by mean|W_latent| before bias — the packed freeze "
            "does not fold it and would serve wrong logits silently; "
            "freeze an unscaled model"
        )
    params, stats = variables["params"], variables["batch_stats"]
    h, w, _ = input_shape
    block_name = (
        "XnorBottleneckBlock_{}" if model.bottleneck else "XnorBasicBlock_{}"
    )
    if model.cifar_stem:
        hw = (h, w)
    else:  # 7x7/2 stem + 3x3/2 SAME maxpool (models/resnet.py:112-116)
        hw = _out_hw(_out_hw((h, w), (2, 2)), (2, 2))
    blocks = []
    latent = 0
    packed_bytes = 0
    bi = 0
    for stage, n_blocks in enumerate(model.stage_sizes):
        for b in range(n_blocks):
            strides = 2 if stage > 0 and b == 0 else 1
            name = block_name.format(bi)
            bp, bs = params[name], stats[name]
            out_hw = _out_hw(hw, (strides, strides))
            # (conv strides, conv input hw) per BN->sign->conv pair:
            # basic = [3x3 strided, 3x3]; bottleneck = [1x1, 3x3
            # strided, 1x1] (models/resnet.py:44-51, 76-86).
            if model.bottleneck:
                plan = [((1, 1), hw), ((strides, strides), hw),
                        ((1, 1), out_hw)]
            else:
                plan = [((strides, strides), hw), ((1, 1), out_hw)]
            convs = []
            for ci, (cs, c_hw) in enumerate(plan):
                cp = bp[f"BinarizedConv_{ci}"]
                convs.append({
                    "bn": _bn_pack(
                        bp[f"BatchNorm_{ci}"], bs[f"BatchNorm_{ci}"]
                    ),
                    "conv": _freeze_conv(
                        cp["kernel"], cp["bias"], c_hw, cs
                    ),
                })
                latent += int(cp["kernel"].size) * 4
                packed_bytes += int(convs[-1]["conv"]["wp"].size) * 4
            blk = {"convs": convs, "strides": strides}
            if "Conv_0" in bp:  # fp32 projection shortcut
                blk["shortcut_w"] = bp["Conv_0"]["kernel"]
            blocks.append(blk)
            hw = out_hw
            bi += 1
    frozen = {
        "family": "xnor-resnet",
        "arch": {
            "input_shape": list(input_shape),
            "stage_sizes": list(model.stage_sizes),
            "cifar_stem": bool(model.cifar_stem),
        },
        "stem_w": params["Conv_0"]["kernel"],  # fp32 stem
        "blocks": blocks,
        "bn_final": _bn_pack(params["BatchNorm_0"], stats["BatchNorm_0"]),
        "head_w": params["Dense_0"]["kernel"],
        "head_b": params["Dense_0"]["bias"],
    }
    n_convs = 3 if model.bottleneck else 2
    frozen["info"] = {
        "family": "xnor-resnet",
        "latent_fp32_weight_bytes": latent,
        "frozen_weight_bytes": packed_bytes,
        "compression": round(latent / max(packed_bytes, 1), 2),
        "packed_layers": [
            f"{block_name.format(i)}/BinarizedConv_{j}"
            for i in range(bi) for j in range(n_convs)
        ],
    }
    return frozen


def _resnet_block_pairs(convs: list, interpret: bool) -> list:
    """(sign_fn | None, conv_fn) pairs for one block's BN->sign->conv
    chain. Fuses a block-interior 1x1/stride-1 conv with the NEXT pair's
    BN threshold: its output's only consumer is that sign, and a 1x1
    SAME conv has corr == 0, so the packed GEMM emits the next layer's
    ±1 bits directly (bottleneck blocks: conv0; basic blocks have no
    1x1). A ``None`` sign marks a pair whose input bits already carry
    the threshold (the previous conv fused it)."""
    pairs = []
    skip_sign = False
    for idx, c in enumerate(convs):
        sign = (
            None if skip_sign
            else _bn_sign_fn(c["bn"]["params"], c["bn"]["stats"])
        )
        skip_sign = False
        layer = c["conv"]
        if (
            idx + 1 < len(convs)
            and int(layer["kh"]) == 1 and int(layer["kw"]) == 1
            and tuple(int(x) for x in layer["strides"]) == (1, 1)
        ):
            nxt = convs[idx + 1]["bn"]
            a, t = _bn_sign_epilogue(nxt["params"], nxt["stats"])
            pairs.append(
                (sign, _packed_conv1x1_sign_fn(layer, a, t, interpret))
            )
            skip_sign = True
        else:
            pairs.append((sign, _packed_conv_fn(layer, interpret)))
    return pairs


def _build_resnet_apply(frozen: Dict[str, Any], interpret: bool) -> Callable:
    arch = frozen["arch"]
    ishape = tuple(int(d) for d in arch["input_shape"])
    cifar_stem = bool(arch["cifar_stem"])
    stem = _fp32_conv_fn(
        frozen["stem_w"], None, (1, 1) if cifar_stem else (2, 2)
    )
    blocks = []
    for blk in frozen["blocks"]:
        if "convs" not in blk:
            raise ValueError(
                "stale xnor-resnet artifact schema (pre-bottleneck "
                "per-block layout); re-export the checkpoint with "
                "`cli export`"
            )
        strides = int(blk["strides"])
        blocks.append({
            "convs": _resnet_block_pairs(blk["convs"], interpret),
            "shortcut": (
                _fp32_conv_fn(
                    blk["shortcut_w"], None, (strides, strides)
                )
                if "shortcut_w" in blk else None
            ),
        })
    affine_final = _bn_affine_fn(
        frozen["bn_final"]["params"], frozen["bn_final"]["stats"]
    )
    wh, bh = jnp.asarray(frozen["head_w"]), jnp.asarray(frozen["head_b"])

    def apply_fn(images: jnp.ndarray) -> jnp.ndarray:
        x = images.astype(jnp.float32)
        if tuple(x.shape[1:]) != ishape:
            raise ValueError(
                f"frozen resnet expects {ishape} inputs, got "
                f"{tuple(x.shape[1:])}"
            )
        x = stem(x)
        if not cifar_stem:  # ImageNet stem: 3x3/2 SAME max-pool
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max,
                (1, 3, 3, 1), (1, 2, 2, 1), "SAME",
            )
        for blk in blocks:
            y = x
            for sign, conv in blk["convs"]:
                y = conv(sign(y) if sign is not None else y)
            shortcut = x if blk["shortcut"] is None else blk["shortcut"](x)
            x = y + shortcut
        x = jax.nn.relu(affine_final(x)).mean(axis=(1, 2))
        logits = jnp.dot(x, wh, preferred_element_type=jnp.float32) + bh
        return logits

    return jax.jit(apply_fn)


# ---------------------------------------------------------------------------
# public API (family dispatch lives in infer.py)


def freeze_bnn_cnn(
    model: BinarizedCNN, variables: Dict, *,
    input_shape=(28, 28, 1), interpret: bool = False,
) -> Tuple[Callable, Dict[str, Any]]:
    """Freeze a trained BinarizedCNN into packed inference; matches
    ``model.apply(variables, x, train=False)`` up to threshold ties."""
    frozen = _freeze_cnn_tensors(model, variables, input_shape)
    return _build_cnn_apply(frozen, interpret), frozen["info"]


def freeze_xnor_resnet(
    model: XnorResNet, variables: Dict, *,
    input_shape=(32, 32, 3), interpret: bool = False,
) -> Tuple[Callable, Dict[str, Any]]:
    """Freeze a trained XnorResNet — basic-block (resnet18, CIFAR stem)
    or bottleneck (resnet50, ImageNet stem) — into packed inference.
    Output is raw logits, matching the live model. For resnet50 pass
    the training resolution (e.g. input_shape=(224, 224, 3))."""
    frozen = _freeze_resnet_tensors(model, variables, input_shape)
    return _build_resnet_apply(frozen, interpret), frozen["info"]
