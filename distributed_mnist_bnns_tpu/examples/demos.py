"""The three distributed demos — TPU-native counterparts of the reference's
tutorial trio in mnist-distributed-BNNS2.py (run by its __main__,
:258-260), using synthetic inputs exactly like the reference does
(torch.randn there, jax.random.normal here):

  demo_basic          (ref :216-233)  DDP wrap + one fwd/bwd/step
                      -> GSPMD data-parallel train step over the mesh.
  demo_checkpoint     (ref :152-191)  rank-0 save, barrier, map_location
                      load, train, rank-0 delete
                      -> save_checkpoint/load_checkpoint (single-writer +
                      barrier live in utils/checkpoint.py) + one DP step.
  demo_model_parallel (ref :193-213)  Net(dev0, dev1) layer placement in DDP
                      -> tensor-parallel sharding over the 'model' mesh axis
                      combined with the 'data' axis (make_tp_train_step).

Run: python -m distributed_mnist_bnns_tpu.examples.demos
(on CPU, set XLA_FLAGS=--xla_force_host_platform_device_count=8 to get a
virtual 8-device mesh, the test-time stand-in for a TPU slice).
"""

from __future__ import annotations

import logging
import os
import tempfile

import jax
import jax.numpy as jnp
import optax

from ..models import bnn_mlp_small, latent_clamp_mask
from ..parallel import (
    bnn_mlp_tp_rules,
    make_dp_train_step,
    make_mesh,
    make_tp_train_step,
    replicate,
    shard_batch,
)
from ..train import make_train_step
from ..train.trainer import TrainState
from ..utils.checkpoint import load_checkpoint, save_checkpoint

log = logging.getLogger(__name__)


def _toy_state(lr=0.01, seed=0):
    model = bnn_mlp_small(backend="xla")
    x = jnp.zeros((1, 784))
    init_rng, dropout_rng = jax.random.split(jax.random.PRNGKey(seed))
    variables = model.init(
        {"params": init_rng, "dropout": dropout_rng},
        x,
        train=True,
    )
    tx = optax.adam(lr)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=tx.init(variables["params"]),
        apply_fn=model.apply,
        tx=tx,
    )
    return state, latent_clamp_mask(variables["params"])


def _toy_batch(n=64, seed=0):
    # distinct streams for data/labels, both derived from the one seed
    kx, ky = jax.random.split(jax.random.fold_in(jax.random.PRNGKey(seed), 1))
    x = jax.random.normal(kx, (n, 784))
    y = jax.random.randint(ky, (n,), 0, 10)
    return x, y


def demo_basic(seed: int = 0) -> float:
    """One data-parallel train step on synthetic data (ref demo_basic)."""
    state, mask = _toy_state(seed=seed)
    mesh = make_mesh()
    step = make_dp_train_step(mask, mesh, donate=False)
    x, y = _toy_batch(seed=seed)
    state = replicate(state, mesh)
    _, metrics = step(
        state, shard_batch(x, mesh), shard_batch(y, mesh),
        replicate(jax.random.PRNGKey(seed), mesh),
    )
    loss = float(metrics["loss"])
    log.info("demo_basic: loss=%.4f over mesh %s", loss, mesh.devices.shape)
    return loss


def demo_checkpoint(ckpt_dir: str | None = None, seed: int = 0) -> float:
    """Save (single-writer + barrier), restore, then train a step —
    the DDP-correct checkpoint pattern (ref demo_checkpoint)."""
    state, mask = _toy_state(seed=seed)
    with tempfile.TemporaryDirectory() as tmp:
        path = ckpt_dir or os.path.join(tmp, "ck")
        save_checkpoint(state, path, epoch=0)
        restored = load_checkpoint(state, path)
        mesh = make_mesh()
        step = make_dp_train_step(mask, mesh, donate=False)
        x, y = _toy_batch(seed=seed)
        restored = replicate(restored, mesh)
        _, metrics = step(
            restored, shard_batch(x, mesh), shard_batch(y, mesh),
            replicate(jax.random.PRNGKey(seed), mesh),
        )
    loss = float(metrics["loss"])
    log.info("demo_checkpoint: post-restore loss=%.4f", loss)
    return loss


def demo_model_parallel(seed: int = 0) -> float:
    """Train step with params sharded over the 'model' axis (the
    declarative version of Net(dev0, dev1); ref demo_model_parallel)."""
    n = jax.device_count()
    model_par = 2 if n % 2 == 0 and n >= 2 else 1
    mesh = make_mesh(data=n // model_par, model=model_par)
    state, mask = _toy_state(seed=seed)
    specs = bnn_mlp_tp_rules(state.params)
    base = make_train_step(mask, donate=False)
    step, placed = make_tp_train_step(base, mesh, state, specs, donate=False)
    x, y = _toy_batch(32, seed=seed)
    from jax.sharding import NamedSharding, PartitionSpec as P

    xb = jax.device_put(x, NamedSharding(mesh, P("data")))
    yb = jax.device_put(y, NamedSharding(mesh, P("data")))
    rng = jax.device_put(jax.random.PRNGKey(seed), NamedSharding(mesh, P()))
    _, metrics = step(placed, xb, yb, rng)
    loss = float(metrics["loss"])
    log.info(
        "demo_model_parallel: loss=%.4f mesh=%s", loss,
        dict(zip(mesh.axis_names, mesh.devices.shape)),
    )
    return loss


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    demo_basic()
    demo_checkpoint()
    demo_model_parallel()


if __name__ == "__main__":
    main()
