"""Produce the accuracy artifact (RESULTS.md): train the flagship BNN MLP
for the reference's 5 epochs (mnist-dist2.py defaults: Adam lr=0.01,
batch 64 — :34,88,90) on the available MNIST data, alongside its fp32
twin, and record test accuracy + per-epoch wall times.

The reference published only wall-time CSVs from its real run
(MNIST_EPOCH_TIME(PersonalCom).csv) and never an accuracy; BASELINE.md's
north star asks for "accuracy within 0.5%" of fp32 — this script measures
that gap on identical architecture/data/optimizer.

Run: python -m distributed_mnist_bnns_tpu.examples.accuracy_report \
        [--out RESULTS.md] [--epochs 5] [--models bnn-mlp-large ...]
"""

from __future__ import annotations

import argparse
import json
from datetime import datetime, timezone


def _train_size_sweep(
    data, sizes, epochs, batch_size, lr, seeds, scan_steps
):
    """Learning curve over train-subset sizes (bnn-mlp-large).

    The available split tops out at 9k train images (the 60k blobs are
    stripped from this workspace), far below where MNIST BNNs saturate —
    so the absolute headline accuracy is data-limited. This sweep holds
    everything fixed except train size (subsets are nested and chosen
    once, independent of seed) so the curve isolates the data effect and
    makes the 9k number interpretable against the ~98% full-data
    expectation."""
    import numpy as np

    from ..data.common import ImageClassData
    from ..train import TrainConfig, Trainer

    n_avail = len(data.train_labels)
    bad = [s for s in sizes if s > n_avail]
    if bad:
        raise ValueError(
            f"--sweep-sizes {bad} exceed the {n_avail} available train "
            "images; a truncated subset would mislabel the learning curve"
        )
    pick_all = np.random.RandomState(123).permutation(n_avail)
    out = []
    for size in sizes:
        pick = pick_all[:size]  # nested subsets: 1k ⊂ 3k ⊂ 9k
        sub = ImageClassData(
            data.train_images[pick], data.train_labels[pick],
            data.test_images, data.test_labels,
            source=data.source, name=data.name,
        )
        accs = []
        for seed in seeds:
            trainer = Trainer(
                TrainConfig(
                    model="bnn-mlp-large", epochs=epochs,
                    batch_size=batch_size, optimizer="adam",
                    learning_rate=lr, seed=seed, log_interval=1000,
                    scan_steps=scan_steps,
                )
            )
            accs.append(trainer.fit(sub)[-1]["test_acc"])
        out.append({
            "train_size": size,
            "test_acc_per_seed": [round(a, 2) for a in accs],
            "test_acc_mean": round(sum(accs) / len(accs), 2),
        })
    return out


def run(models, epochs, batch_size, lr, seeds, out_path, scan_steps=1,
        device_data=False, sweep_sizes=None, cache_path=None):
    if epochs < 1:
        raise ValueError(f"epochs must be >= 1, got {epochs}")
    import os

    import jax

    from ..data import load_mnist
    from ..train import TrainConfig, Trainer

    # Per-(model, seed) fit cache: a multi-model multi-seed report is
    # 6+ full training runs, and the TPU tunnel's live windows can be
    # shorter than that — with a cache_path each completed fit persists
    # immediately, so a window that dies mid-report resumes at the next
    # un-fit (model, seed) pair instead of from scratch.
    cache: dict = {}
    if cache_path and os.path.exists(cache_path):
        with open(cache_path) as f:
            cache = json.load(f)

    def _fit_cached(model, seed):
        # platform is part of the key: the report stamps its numbers
        # with the live device, so a CPU-cached fit must never be
        # republished as a TPU measurement (epoch_times_s especially)
        key = (f"{model}|{seed}|{epochs}|{batch_size}|{lr}|{scan_steps}"
               f"|{device_data}|{jax.default_backend()}")
        if key in cache:
            return cache[key]
        trainer = Trainer(
            TrainConfig(
                model=model,
                epochs=epochs,
                batch_size=batch_size,
                optimizer="adam",
                learning_rate=lr,
                seed=seed,
                log_interval=1000,
                scan_steps=scan_steps,
                device_data=device_data,
            )
        )
        history = trainer.fit(data)
        if cache_path:
            cache[key] = json.loads(json.dumps(history, default=float))
            tmp = cache_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(cache, f)
            os.replace(tmp, cache_path)
        return history

    data = load_mnist()
    rows = []
    for model in models:
        per_seed = []
        for seed in seeds:
            per_seed.append(_fit_cached(model, seed))
        # Accuracy on the available 1000-example test split moves ~0.1%
        # per example; a single seed is inside that noise, so the
        # headline figure is the mean over seeds (per-seed values kept).
        history = per_seed[0]
        n = float(len(per_seed))
        rows.append(
            {
                "model": model,
                "epochs": epochs,
                "seeds": list(seeds),
                "test_acc": sum(h[-1]["test_acc"] for h in per_seed) / n,
                "test_acc_per_seed": [
                    round(h[-1]["test_acc"], 2) for h in per_seed
                ],
                "test_acc_top5": sum(
                    h[-1]["test_acc_top5"] for h in per_seed
                ) / n,
                "test_loss": sum(h[-1]["test_loss"] for h in per_seed) / n,
                "epoch_times_s": [round(h["epoch_time_s"], 3) for h in history],
                "per_epoch_acc": [round(h["test_acc"], 2) for h in history],
            }
        )

    # Binarized-vs-fp32 twin pairs (identical topology/data/optimizer —
    # the measured gap is exactly the cost of binarizing). Round 5 adds
    # the conv and transformer families' twins.
    _TWINS = {
        "bnn-mlp-large": "fp32-mlp-large",
        "xnor-resnet18": "fp32-resnet18",
        "bnn-vit-tiny": "fp32-vit-tiny",
        "bnn-vit-small": "fp32-vit-small",
    }
    by_model = {r["model"]: r for r in rows}
    gaps = {
        b: round(by_model[f]["test_acc"] - by_model[b]["test_acc"], 2)
        for b, f in _TWINS.items()
        if b in by_model and f in by_model
    }

    device = str(jax.devices()[0])
    lines = [
        "# RESULTS — recorded training run",
        "",
        f"Produced by `python -m distributed_mnist_bnns_tpu.examples."
        f"accuracy_report` on {datetime.now(timezone.utc).date()} "
        f"(device: {device}).",
        "",
        f"Setup: Adam lr={lr}, batch {batch_size}, {epochs} epochs, "
        f"accuracies averaged over seeds {list(seeds)} (the 1000-example "
        "test split moves ~0.1% per example, so single-seed accuracy is "
        "noise-dominated) — otherwise the reference flagship's "
        "configuration "
        f"(mnist-dist2.py:34,88,90). Data: `{data.source}` "
        f"({len(data.train_labels)} train / {len(data.test_labels)} test; "
        "the full 60k MNIST train images are not shipped in this "
        "workspace — see .MISSING_LARGE_BLOBS — so the deterministic "
        "9k/1k t10k split stands in).",
        "",
        "| model | test acc (top-1, mean) | per-seed | top-5 | test loss | "
        "per-epoch acc (seed 0) | epoch times (s) |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['model']} | {r['test_acc']:.2f}% | "
            f"{', '.join(str(a) for a in r['test_acc_per_seed'])} | "
            f"{r['test_acc_top5']:.2f}% | {r['test_loss']:.4f} | "
            f"{', '.join(str(a) for a in r['per_epoch_acc'])} | "
            f"{', '.join(str(t) for t in r['epoch_times_s'])} |"
        )
    if gaps:
        lines += [""] + [
            f"**{b} vs {_TWINS[b]} accuracy gap (identical "
            f"topology/data/optimizer): {g:+.2f}%**"
            + (" — BASELINE.md's north star asks for the BNN to be "
               "within 0.5%." if b == "bnn-mlp-large" else "")
            for b, g in gaps.items()
        ]
    sweep = None
    if sweep_sizes:
        sweep = _train_size_sweep(
            data, sweep_sizes, epochs, batch_size, lr, seeds, scan_steps
        )
        lines += [
            "",
            "## Train-size learning curve (bnn-mlp-large)",
            "",
            "The absolute headline above is **data-limited**: the full "
            "60k MNIST train set is not shipped in this workspace, and a "
            "BNN MLP of this topology on full MNIST reaches ~98%+. The "
            "curve below varies ONLY the train-subset size (nested "
            "subsets, fixed across seeds; same recipe as the headline) "
            "so the 9k-split number can be read in context — accuracy is "
            "still climbing steeply with data at the sizes available "
            "here, i.e. the deficit vs the full-data expectation is the "
            "split, not the model.",
            "",
            "| train images | test acc per seed | mean |",
            "|---|---|---|",
        ]
        for s in sweep:
            lines.append(
                f"| {s['train_size']} | "
                f"{', '.join(str(a) for a in s['test_acc_per_seed'])} | "
                f"{s['test_acc_mean']:.2f}% |"
            )
    lines += [
        "",
        "Reference comparison: the reference published wall times only "
        "(MNIST_EPOCH_TIME(PersonalCom).csv: ~8.25 s/epoch over 60k images "
        "at batch 64) and no accuracy (mnist-dist2.py prints train loss "
        "only, :144-146).",
        "",
        "```json",
        json.dumps(
            rows if sweep is None
            else rows + [{"train_size_sweep": sweep}],
            indent=1,
        ),
        "```",
        "",
    ]
    with open(out_path, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {out_path}")
    for r in rows:
        print(f"{r['model']}: {r['test_acc']:.2f}%")
    for b, g in gaps.items():
        print(f"gap ({_TWINS[b]} - {b}): {g:+.2f}%")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="RESULTS.md")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seeds", type=int, nargs="+", default=[42, 43, 44])
    p.add_argument("--scan-steps", type=int, default=1,
                   help="fuse N train steps per dispatch (TrainConfig."
                        "scan_steps); identical trajectory, removes "
                        "per-step host dispatch latency")
    p.add_argument("--device-data", action="store_true",
                   help="device-resident dataset, one dispatch per epoch")
    p.add_argument("--sweep-sizes", type=int, nargs="+", default=None,
                   help="also record a train-size learning curve for "
                        "bnn-mlp-large at these subset sizes (context "
                        "for the data-limited headline accuracy)")
    p.add_argument(
        "--platform", default=None, choices=[None, "cpu", "tpu"],
        help="pin the jax platform before backend init (use cpu when the "
             "TPU endpoint is unavailable)",
    )
    p.add_argument(
        "--models", nargs="+",
        default=["bnn-mlp-large", "fp32-mlp-large", "bnn-mlp-small"],
    )
    args = p.parse_args()
    if args.epochs < 1:
        p.error("--epochs must be >= 1")
    if args.platform:
        from ..utils.platform import pin_platform

        if not pin_platform(args.platform):
            raise RuntimeError(
                f"cannot pin platform {args.platform!r}: a jax backend is "
                "already initialized"
            )
    run(args.models, args.epochs, args.batch_size, args.lr, args.seeds,
        args.out, scan_steps=args.scan_steps, device_data=args.device_data,
        sweep_sizes=args.sweep_sizes)


if __name__ == "__main__":
    main()
