"""Train the causal binarized LM on a synthetic character corpus.

Runnable demo of the sequence-modeling family (models/transformer.py
BinarizedLM): next-token training with lm_loss on a periodic synthetic
corpus (predictable, so loss falls fast), optionally with the causal
flash kernel (--attention flash) or sequence-parallel ring attention over
every local device (--ring).

Run: python -m distributed_mnist_bnns_tpu.examples.lm_demo \
        [--steps 200] [--seq-len 32] [--attention xla|flash] [--ring]
"""

from __future__ import annotations

import argparse


def run(steps=200, seq_len=32, batch=16, vocab=64, embed_dim=128, depth=2,
        num_heads=4, lr=3e-3, seed=0, attention="xla", ring=False,
        log_every=25):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ..models import BinarizedLM, latent_clamp_mask, lm_loss
    from ..train import clamp_latent

    attention_fn = None
    if ring:
        from jax.sharding import Mesh

        from ..parallel import make_ring_attention

        devices = jax.devices()
        if seq_len % len(devices):
            raise ValueError(
                f"--ring needs seq_len divisible by {len(devices)} devices"
            )
        mesh = Mesh(np.array(devices), axis_names=("seq",))
        attention_fn = make_ring_attention(mesh, causal=True)

    model = BinarizedLM(
        vocab=vocab, max_len=seq_len, embed_dim=embed_dim, depth=depth,
        num_heads=num_heads, attention=attention, attention_fn=attention_fn,
    )
    rng = np.random.RandomState(seed)
    period = seq_len // 4
    base = rng.randint(0, vocab, (batch, period))
    tokens = jnp.asarray(np.tile(base, (1, seq_len // period)), jnp.int32)

    variables = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        tokens, train=False,
    )
    params = variables["params"]
    clamp_mask = latent_clamp_mask(params)
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            out = model.apply({"params": p}, tokens, train=False)
            return lm_loss(out, tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # The projection half of BNN training (same as the Trainer):
        # without the clamp, latents drift outside [-1, 1] over long runs
        # and the binarization regime degrades.
        return clamp_latent(params, clamp_mask), opt_state, loss

    history = []
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state)
        if i % log_every == 0 or i == steps - 1:
            loss = float(loss)
            history.append(loss)
            print(f"step {i:4d}  next-token loss {loss:.4f} "
                  f"({loss / float(jnp.log(2.0)):.3f} bits/token)")
    return history


def main():
    # Re-assert JAX_PLATFORMS over any sitecustomize that flipped the jax
    # config at interpreter start (same dance as cli/bench) — must run
    # before anything initializes a backend.
    import os

    if os.environ.get("JAX_PLATFORMS"):
        from ..utils.platform import pin_platform

        pin_platform(os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--attention", default="xla", choices=["xla", "flash"])
    p.add_argument("--ring", action="store_true",
                   help="sequence-parallel causal ring attention over all "
                        "local devices")
    a = p.parse_args()
    run(steps=a.steps, seq_len=a.seq_len, batch=a.batch, depth=a.depth,
        lr=a.lr, seed=a.seed, attention=a.attention, ring=a.ring)


if __name__ == "__main__":
    main()
