"""Train the causal binarized LM — synthetic corpus or a real text file.

Runnable entry for the sequence-modeling family (models/transformer.py
BinarizedLM): next-token training with lm_loss, optionally with the
causal flash kernel (--attention flash), sequence-parallel ring
attention over every local device (--ring), or the GPipe model-level
pipeline over the block stack (--pp N). Also reachable as
``python -m distributed_mnist_bnns_tpu.cli lm ...``.

Data: ``--corpus FILE`` trains byte-level (vocab 256) on random windows
of the file; without it, a periodic synthetic corpus (predictable, so
loss falls fast) stands in.

Run: python -m distributed_mnist_bnns_tpu.examples.lm_demo \
        [--steps 200] [--seq-len 32] [--attention xla|flash] [--ring] \
        [--corpus file.txt] [--pp 2]
"""

from __future__ import annotations

import argparse


def run(steps=200, seq_len=32, batch=16, vocab=64, embed_dim=128, depth=2,
        num_heads=4, lr=3e-3, seed=0, attention="xla", ring=False,
        log_every=25, corpus=None, pp=1, sample=0, temperature=0.8,
        export=None):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ..models import BinarizedLM, latent_clamp_mask, lm_loss
    from ..train import clamp_latent

    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if export and (ring or pp > 1):
        # ring installs an attention_fn (rejected by the freezer) and the
        # pipeline re-lays params out stage-major; export the plain model.
        raise ValueError("--export requires the plain model "
                         "(no --ring / --pp)")
    if ring and pp > 1:
        # ring attention's shard_map runs over a 'seq' mesh; inside the
        # pipeline's 'pipe' manual mesh that context clashes.
        raise ValueError("--ring and --pp are mutually exclusive")
    attention_fn = None
    if ring:
        from jax.sharding import Mesh

        from ..parallel import make_ring_attention

        devices = jax.devices()
        if seq_len % len(devices):
            raise ValueError(
                f"--ring needs seq_len divisible by {len(devices)} devices"
            )
        mesh = Mesh(np.array(devices), axis_names=("seq",))
        attention_fn = make_ring_attention(mesh, causal=True)

    rng = np.random.RandomState(seed)
    if corpus is not None:
        # Byte-level LM on a real file: vocab 256, random windows drawn
        # each step (the host sampling is trivially cheap next to the
        # device step).
        data = np.frombuffer(open(corpus, "rb").read(), np.uint8)
        if len(data) <= seq_len:
            raise ValueError(
                f"corpus {corpus!r} has {len(data)} bytes; need more "
                f"than seq_len={seq_len}"
            )
        vocab = 256

        def draw_tokens():
            starts = rng.randint(0, len(data) - seq_len, size=batch)
            return jnp.asarray(
                np.stack([data[s : s + seq_len] for s in starts]),
                jnp.int32,
            )
    else:
        if seq_len < 4:
            raise ValueError(
                f"the synthetic corpus needs seq_len >= 4, got {seq_len}"
            )
        period = seq_len // 4
        base = rng.randint(0, vocab, (batch, period))
        reps = -(-seq_len // period)  # tile up, slice to exact length
        fixed = jnp.asarray(
            np.tile(base, (1, reps))[:, :seq_len], jnp.int32
        )

        def draw_tokens():
            return fixed

    model = BinarizedLM(
        vocab=vocab, max_len=seq_len, embed_dim=embed_dim, depth=depth,
        num_heads=num_heads, attention=attention, attention_fn=attention_fn,
    )
    tokens0 = draw_tokens()
    variables = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        tokens0, train=False,
    )
    params = variables["params"]

    if pp > 1:
        # Model-level GPipe over the block stack (parallel/pipeline_model)
        from jax.sharding import Mesh

        from ..parallel import make_pipelined_apply, pipeline_params

        devices = jax.devices()
        if len(devices) < pp:
            raise ValueError(f"--pp {pp} needs {pp} devices")
        pp_mesh = Mesh(np.asarray(devices[:pp]), axis_names=("pipe",))
        pp_apply = make_pipelined_apply(model, pp_mesh, depth, n_micro=pp)
        params = pipeline_params(params)
        forward = lambda p, toks: pp_apply({"params": p}, toks)
    else:
        forward = lambda p, toks: model.apply(
            {"params": p}, toks, train=False
        )

    clamp_mask = latent_clamp_mask(params)
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        def loss_fn(p):
            return lm_loss(forward(p, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # The projection half of BNN training (same as the Trainer):
        # without the clamp, latents drift outside [-1, 1] over long runs
        # and the binarization regime degrades.
        return clamp_latent(params, clamp_mask), opt_state, loss

    history = []
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, draw_tokens())
        if i % log_every == 0 or i == steps - 1:
            loss = float(loss)
            history.append(loss)
            print(f"step {i:4d}  next-token loss {loss:.4f} "
                  f"({loss / float(jnp.log(2.0)):.3f} bits/token)")

    out: list = []
    if sample > 0:
        # Autoregressive sampling with a fixed-size sliding window (one
        # compiled program: the window shape never changes). The prompt
        # is one more draw from the data stream — a random corpus window
        # (the training rng has advanced, so it varies with --steps) or
        # the fixed synthetic pattern.
        @jax.jit
        def next_token(params, window, key):
            lp = forward(params, window[None])[0, -1]  # (vocab,) log-probs
            if temperature <= 0:
                return jnp.argmax(lp)
            return jax.random.categorical(key, lp / temperature)

        window = draw_tokens()[0]  # (seq_len,)
        key = jax.random.PRNGKey(seed + 2)
        for _ in range(sample):
            key, sub = jax.random.split(key)
            tok = next_token(params, window, sub)
            out.append(int(tok))
            window = jnp.concatenate([window[1:], tok[None]])
        if corpus is not None:  # byte-level: show as text
            text = bytes(out).decode("utf-8", errors="replace")
            print(f"sample ({sample} bytes, T={temperature}): {text!r}")
        else:
            print(f"sample ({sample} tokens, T={temperature}): {out}")

    if export:
        # Freeze to the packed 1-bit serving artifact; serve it with
        # infer.load_packed (full-window) or
        # infer_transformer.make_lm_decoder (KV-cache incremental).
        from ..infer import export_packed

        info = export_packed(model, {"params": params}, export)
        print(
            f"packed artifact -> {export}: {info['compression']}x over "
            f"the fp32 latents ({info['frozen_weight_bytes']} packed "
            "bytes)"
        )
    return history, out


def main():
    # Re-assert JAX_PLATFORMS over any sitecustomize that flipped the jax
    # config at interpreter start (same dance as cli/bench) — must run
    # before anything initializes a backend.
    from ..utils.platform import pin_platform_from_env

    pin_platform_from_env()
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--attention", default="xla", choices=["xla", "flash"])
    p.add_argument("--ring", action="store_true",
                   help="sequence-parallel causal ring attention over all "
                        "local devices")
    p.add_argument("--corpus", default=None,
                   help="text/bytes file for byte-level LM training "
                        "(default: synthetic periodic corpus)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline the block stack over N devices "
                        "(depth %% N == 0)")
    p.add_argument("--sample", type=int, default=0,
                   help="generate N tokens after training (sliding-window "
                        "autoregressive sampling)")
    p.add_argument("--temperature", type=float, default=0.8,
                   help="sampling temperature (0 = greedy)")
    a = p.parse_args()
    run(steps=a.steps, seq_len=a.seq_len, batch=a.batch, depth=a.depth,
        lr=a.lr, seed=a.seed, attention=a.attention, ring=a.ring,
        corpus=a.corpus, pp=a.pp, sample=a.sample,
        temperature=a.temperature)


if __name__ == "__main__":
    main()
