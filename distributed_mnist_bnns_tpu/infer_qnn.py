"""Integer serving for the k-bit QNN family (QnnMLP) — the quantized
counterpart of the 1-bit packed paths (infer.py / infer_conv.py /
infer_transformer.py / infer_moe.py).

No reference counterpart (the reference's ``Quantize`` op was dead code —
models/binarized_modules.py:56-63; this repo made it a trainable family,
models/mlp.py::QnnMLP). The deployment transform: ``quantize`` maps every
value onto the signed 2^(b-1) grid, so for num_bits <= 8 the quantized
weights ARE int8 integers (w_int = w_q * 2^(b-1), exactly representable)
and a hidden layer's GEMM becomes

    y = (x_int @ w_int) / 2^(2(b-1)) + bias

with int8 x int8 -> int32 accumulation — exact integer arithmetic (no
fp32 summation rounding, K * 127^2 << 2^31) that lands on the TPU MXU's
int8 pipeline at 2x the bf16 rate (PERF.md crossover, bench's
precision-matched MFU accounting). Weights ship as int8: 4x smaller than
the fp32 latents (1 byte/param).

BN between layers stays an eval-time affine (the quantizer is not a sign,
so there is no threshold fold here — the VPU elementwise chain
affine -> hardtanh -> quantize is cheap next to the GEMMs); the first
layer takes raw fp32 pixels against the quantized weights, and the head
is the model's plain fp32 Dense.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from .infer import _bn_affine_fn
from .models.mlp import QnnMLP
from .ops.binarize import quantize


def _w_int(kernel: jnp.ndarray, num_bits: int) -> jnp.ndarray:
    """The quantized weight's exact integer representation (int8)."""
    scale = 2.0 ** (num_bits - 1)
    return jnp.round(quantize(kernel, "det", num_bits) * scale).astype(
        jnp.int8
    )


def _freeze_qnn_tensors(model: QnnMLP, variables: Dict) -> Dict[str, Any]:
    if model.num_bits > 8:
        raise ValueError(
            f"int8 serving covers num_bits <= 8, got {model.num_bits}"
        )
    if model.stochastic:
        raise ValueError(
            "stochastic rounding is a train-time feature; freeze the "
            "deterministic eval path"
        )
    params = variables["params"]
    stats = variables["batch_stats"]
    frozen: Dict[str, Any] = {
        "family": "qnn-mlp",
        "num_bits": model.num_bits,
        "layers": [
            {
                "w_int": _w_int(
                    params[f"QuantizedDense_{i}"]["kernel"], model.num_bits
                ),
                "bias": params[f"QuantizedDense_{i}"]["bias"],
            }
            for i in range(3)
        ],
        "bns": [
            {"params": dict(params[f"BatchNorm_{i}"]),
             "stats": dict(stats[f"BatchNorm_{i}"])}
            for i in range(3)
        ],
        "head_w": params["Dense_0"]["kernel"],
        "head_b": params["Dense_0"]["bias"],
    }
    latent = sum(
        int(params[f"QuantizedDense_{i}"]["kernel"].size) for i in range(3)
    ) * 4
    int8_bytes = sum(int(l["w_int"].size) for l in frozen["layers"])
    frozen["info"] = {
        "family": "qnn-mlp",
        "latent_fp32_weight_bytes": latent,
        "frozen_weight_bytes": int8_bytes,
        "compression": round(latent / int8_bytes, 2),
        "packed_layers": [f"QuantizedDense_{i}" for i in range(3)],
    }
    return frozen


def _build_qnn_apply(frozen: Dict[str, Any], interpret: bool) -> Callable:
    """Jitted int8 predictor. ``interpret`` is accepted for load_packed
    API uniformity; this family has no Pallas kernel to interpret —
    XLA's native int8 dot IS the serving path."""
    del interpret
    num_bits = int(frozen["num_bits"])
    scale = 2.0 ** (num_bits - 1)
    layers = [
        (jnp.asarray(l["w_int"], jnp.int8),
         jnp.asarray(l["bias"], jnp.float32))
        for l in frozen["layers"]
    ]
    bns = [
        _bn_affine_fn(b["params"], b["stats"]) for b in frozen["bns"]
    ]
    head_w = jnp.asarray(frozen["head_w"], jnp.float32)
    head_b = jnp.asarray(frozen["head_b"], jnp.float32)

    def apply_fn(images: jnp.ndarray) -> jnp.ndarray:
        x = images.reshape(images.shape[0], -1).astype(jnp.float32)
        # first layer: raw fp32 pixels @ quantized weights
        w0, b0 = layers[0]
        y = jnp.dot(x, w0.astype(jnp.float32),
                    preferred_element_type=jnp.float32) / scale + b0
        for (w, b), bn in zip(layers[1:], bns[:2]):
            h = jax.nn.hard_tanh(bn(y))
            # the live path's own quantize(), lifted to its exact int
            # representation, then integer GEMM (int32 accumulate)
            xi = (quantize(h, "det", num_bits) * scale).astype(jnp.int8)
            acc = jnp.dot(xi, w, preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) / (scale * scale) + b
        # final block: dropout is eval-identity; BN affine + hardtanh
        # feed the fp32 head (dropout-before-bn3 quirk preserved upstream)
        h = jax.nn.hard_tanh(bns[2](y))
        return jax.nn.log_softmax(
            jnp.dot(h, head_w, preferred_element_type=jnp.float32) + head_b
        )

    return jax.jit(apply_fn)


def freeze_qnn_mlp(
    model: QnnMLP, variables: Dict, *, interpret: bool = False
) -> Tuple[Callable, Dict[str, Any]]:
    """Freeze a trained QnnMLP into int8 inference; matches
    ``model.apply(variables, x, train=False)`` up to fp32-summation
    noise (the frozen GEMMs accumulate exactly in int32)."""
    frozen = _freeze_qnn_tensors(model, variables)
    return _build_qnn_apply(frozen, interpret), frozen["info"]
