"""Logging setup — parity with the reference's ``setup_logging``
(utils.py:16-28): DEBUG to a fresh file, INFO to console; plus the
rank-0-only emission pattern used by every reference training loop
(mnist-dist2.py:141-149), expressed as process_index()==0 in JAX.
"""

from __future__ import annotations

import logging
import os


def is_primary_host() -> bool:
    """True on the process that should own logging/checkpoint writes.

    Under the multihost elastic runtime every rank is its OWN jax
    process (process_index()==0 everywhere — inter-host exchange is
    host-side, parallel/hostcomm), so the supervisor-assigned JG_MH_RANK
    decides primacy there; real jax.distributed runs fall through to
    process_index(). Falls back to True when JAX isn't initialized
    (pure-host tooling)."""
    rank = os.environ.get("JG_MH_RANK")
    if rank is not None:
        try:
            return int(rank) == 0
        except ValueError:
            pass  # malformed env: fall through to the jax view
    try:
        import jax

        return jax.process_index() == 0
    except (ImportError, RuntimeError):
        return True


def setup_logging(
    log_file: str = "log.txt", *, level: int = logging.DEBUG,
    console_level: int = logging.INFO, primary_only: bool = True,
) -> logging.Logger:
    """Root logger: DEBUG -> file (truncate), INFO -> console.

    With primary_only (default), non-primary hosts get a WARNING-level
    console logger and no file handler, so multi-host runs produce one
    coherent log stream (the reference achieves this with `if rank == 0`
    guards around every print)."""
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    root.setLevel(level)
    fmt = logging.Formatter(
        "%(asctime)s - %(levelname)s - %(message)s", "%Y-%m-%d %H:%M:%S"
    )
    primary = is_primary_host() or not primary_only
    console = logging.StreamHandler()
    console.setLevel(console_level if primary else logging.WARNING)
    console.setFormatter(fmt)
    root.addHandler(console)
    if primary and log_file:
        os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
        fh = logging.FileHandler(log_file, mode="w")
        fh.setLevel(level)
        fh.setFormatter(fmt)
        root.addHandler(fh)
    return root
