"""JAX platform pinning.

Some images install experimental remote-accelerator PJRT plugins from a
``sitecustomize`` at interpreter start, flipping ``jax_platforms`` in the
jax config; the ``JAX_PLATFORMS`` environment variable alone then no
longer decides platform selection, and CPU-only runs can hang dialing a
remote endpoint. Backends initialize lazily, so re-asserting env + config
*before any computation* restores the documented env-var contract.

Users of this helper: the CLI (honors JAX_PLATFORMS), the accuracy-report
example (--platform), and __graft_entry__'s multichip dryrun (virtual CPU
mesh). tests/conftest.py deliberately keeps its own inline copy: it is the
bootstrap that must run before this package is safe to import.
"""

from __future__ import annotations

import os
import sys


def backend_initialized() -> bool:
    """Has any jax backend already been created (too late to re-pin)?"""
    xb = sys.modules.get("jax._src.xla_bridge")
    return bool(getattr(xb, "_backends", None))


def pin_platform_from_env() -> None:
    """Honor ``JAX_PLATFORMS`` when set, raising if it is too late.

    The shared entry-point preamble (bench.py, the CLI-adjacent scripts,
    examples/lm_demo): with the env var unset this is a no-op (the
    default — possibly remote-TPU — platform wins, which is what a live
    hardware window wants); with it set, the platform is pinned before
    backend init, and a pin that can no longer take effect raises
    instead of letting the run proceed onto the wrong backend (e.g. a
    multi-hour CPU study silently dialing a dead remote endpoint)."""
    platform = os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    if not pin_platform(platform):
        import jax

        raise RuntimeError(
            f"JAX_PLATFORMS={platform!r} requested but a "
            f"{jax.default_backend()!r} backend is already initialized; "
            "pin earlier (before any jax computation/import side effect)"
        )


def enable_persistent_compilation_cache(path: str | None = None) -> str:
    """Point jax's persistent compilation cache at ``path`` (default:
    ``JAX_COMPILATION_CACHE_DIR``, else ``<repo-root>/.jax_cache``
    derived from this package's location, so every entry point shares
    one cache with a no-arg call).

    On the remote-tunneled TPU endpoint a cold compile of the flagship
    train step can consume most of a short hardware-availability window
    (the 2026-08-01 08:31 window died mid-compile with nothing banked),
    so compiled executables are persisted across processes and windows.
    Safe everywhere: when a backend cannot serialize executables the
    cache degrades to a warning, and CPU test runs simply get faster
    re-runs. Returns the directory in use."""
    # Env var wins over the caller's default so an operator-exported
    # cache location is honored by every entry point uniformly.
    cache_dir = (
        os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or path
        or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            ".jax_cache",
        )
    )
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    return cache_dir


def default_aot_store_dir(path: str | None = None) -> str:
    """Resolve the AOT executable store directory (aot/, PERF.md "Cold
    start"): ``JG_AOT_STORE`` wins, then ``path``, then
    ``<repo-root>/.jax_aot`` derived from this package's location — the
    same derivation (and the same env-wins precedence) as the
    ``.jax_cache`` persistent compilation cache above, so every entry
    point (cli serve, cli aot build, bench, tests) shares one store with
    a no-arg call. Unlike the compilation cache this stores fully
    *loaded-and-keyed* executables: a hit skips tracing AND lowering,
    not just the XLA compile."""
    return (
        os.environ.get("JG_AOT_STORE")
        or path
        or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            ".jax_aot",
        )
    )


def pin_platform(
    platform: str, virtual_device_count: int | None = None
) -> bool:
    """Pin jax to ``platform`` via env + config, before backend init.

    ``virtual_device_count`` additionally requests N virtual host devices
    (``--xla_force_host_platform_device_count``, CPU simulation) unless
    XLA_FLAGS already carries a count. When a backend is already live,
    nothing is touched: returns True if it is already on the requested
    platform (no-op success), False otherwise (too late to re-pin)."""
    if backend_initialized():
        import jax

        wanted = platform.split(",")[0].strip().lower()
        if jax.default_backend() != wanted:
            return False
        return (
            virtual_device_count is None
            or jax.local_device_count() >= virtual_device_count
        )
    if virtual_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count="
                f"{virtual_device_count}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)
    return True
