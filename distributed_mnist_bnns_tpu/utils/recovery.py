"""Failure detection / recovery — compat shim.

.. deprecated::
    This module grew into :mod:`distributed_mnist_bnns_tpu.resilience`
    (see RESILIENCE.md): ``resilience.policy.run_with_policy`` adds
    jittered exponential backoff, transient-vs-fatal exception
    classification (a missing dataset is not retried into oblivion),
    preemption-aware resume that doesn't burn the failure budget, and
    structured ``restart`` obs events. ``run_with_recovery`` below is
    kept as a thin adapter over it for existing callers; new code
    should construct a :class:`~..resilience.policy.RetryPolicy`
    directly.

The reference has no elastic runtime; its only recovery artifact is
"checkpoint on one machine, manually resume on another" over a raw TCP
socket pair (mnist change node.py:85-90 -> mnist change master.py:56-59).
This loop automates exactly that: run the training closure, and on
failure rebuild the trainer (which, with ``TrainConfig.resume=True``,
restores the latest *verified* checkpoint generation) and retry.
"""

from __future__ import annotations

from typing import Callable, TypeVar

from ..resilience.policy import (  # re-exported for compat
    RetryPolicy,
    TrainingFailure,
    run_with_policy,
)

T = TypeVar("T")

__all__ = ["TrainingFailure", "run_with_recovery"]


def run_with_recovery(
    make_trainer: Callable[[], "object"],
    run: Callable[[object], T],
    *,
    max_restarts: int = 2,
    backoff_s: float = 1.0,
) -> T:
    """Execute ``run(make_trainer())`` with restart-from-latest retry.

    Adapter over :func:`resilience.policy.run_with_policy`: the old
    constant ``backoff_s`` becomes the base of a jittered exponential
    schedule, and fatal classes (KeyboardInterrupt-adjacent exits,
    missing datasets, config/programming errors) are no longer
    retried."""
    policy = RetryPolicy(max_restarts=max_restarts, base_backoff_s=backoff_s)
    return run_with_policy(make_trainer, run, policy=policy)
