"""Failure detection / recovery.

The reference has no elastic runtime; its only recovery artifact is
"checkpoint on one machine, manually resume on another" over a raw TCP
socket pair (mnist change node.py:85-90 -> mnist change master.py:56-59;
SURVEY §5 deems periodic-checkpoint + restart-from-latest sufficient
parity). This module automates exactly that: run the training closure,
checkpoint every epoch (the Trainer already does), and on failure restart
from the latest checkpoint up to a retry budget.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, TypeVar

log = logging.getLogger(__name__)

T = TypeVar("T")


class TrainingFailure(RuntimeError):
    """Raised when training keeps failing past the retry budget."""


def run_with_recovery(
    make_trainer: Callable[[], "object"],
    run: Callable[[object], T],
    *,
    max_restarts: int = 2,
    backoff_s: float = 1.0,
) -> T:
    """Execute ``run(trainer)``; on exception rebuild the trainer (which,
    with TrainConfig.resume=True, restores the latest checkpoint) and
    retry. This is the cold-restart recovery loop the reference performed
    by hand across its two LAN machines."""
    attempt = 0
    while True:
        trainer = make_trainer()
        try:
            return run(trainer)
        except KeyboardInterrupt:  # pragma: no cover
            raise
        except Exception as e:
            attempt += 1
            if attempt > max_restarts:
                raise TrainingFailure(
                    f"training failed {attempt} times; giving up"
                ) from e
            log.warning(
                "training attempt %d failed (%s: %s); restarting from latest "
                "checkpoint in %.1fs", attempt, type(e).__name__, e, backoff_s,
            )
            time.sleep(backoff_s)
