"""ResultsLog — accumulate per-epoch/step row dicts, persist CSV + a
self-contained HTML report.

Parity with the reference's ResultsLog (utils.py:31-73), which wrote a CSV
and a Bokeh HTML document (its Line plotting was commented out,
utils.py:66-68). Here the HTML is dependency-free: one inline-SVG line chart
per numeric column, so the artifact renders anywhere.
"""

from __future__ import annotations

import html
import os
from typing import Any, Dict, List


class ResultsLog:
    def __init__(self, path: str = "results.csv", plot_path: str | None = None):
        self.path = path
        self.plot_path = plot_path or (os.path.splitext(path)[0] + ".html")
        self.rows: List[Dict[str, Any]] = []

    def add(self, **kwargs: Any) -> None:
        self.rows.append(dict(kwargs))

    # -- persistence --------------------------------------------------------

    def _columns(self) -> List[str]:
        cols: List[str] = []
        for row in self.rows:
            for k in row:
                if k not in cols:
                    cols.append(k)
        return cols

    def save(self, title: str = "training results") -> None:
        cols = self._columns()
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "w") as f:
            f.write(",".join(cols) + "\n")
            for row in self.rows:
                f.write(",".join(str(row.get(c, "")) for c in cols) + "\n")
        with open(self.plot_path, "w") as f:
            f.write(self._render_html(title, cols))

    def load(self, path: str | None = None) -> List[Dict[str, Any]]:
        path = path or self.path
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f if ln.strip()]
        cols = lines[0].split(",")
        self.rows = []
        for ln in lines[1:]:
            vals = ln.split(",")
            row: Dict[str, Any] = {}
            for c, v in zip(cols, vals):
                if v == "":
                    continue
                try:
                    row[c] = float(v) if "." in v or "e" in v.lower() else int(v)
                except ValueError:
                    row[c] = v
            self.rows.append(row)
        return self.rows

    # -- plotting -----------------------------------------------------------

    def _render_html(self, title: str, cols: List[str]) -> str:
        charts = []
        numeric_cols = [
            c
            for c in cols
            if any(isinstance(r.get(c), (int, float)) for r in self.rows)
        ]
        for c in numeric_cols:
            ys = [
                float(r[c])
                for r in self.rows
                if isinstance(r.get(c), (int, float))
            ]
            if len(ys) >= 2:
                charts.append(self._svg_line(c, ys))
        body = "\n".join(charts) or "<p>(not enough data to plot)</p>"
        return (
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title></head>"
            f"<body><h1>{html.escape(title)}</h1>{body}</body></html>"
        )

    @staticmethod
    def _svg_line(name: str, ys: List[float], w: int = 640, h: int = 240) -> str:
        lo, hi = min(ys), max(ys)
        span = (hi - lo) or 1.0
        pts = " ".join(
            f"{40 + i * (w - 60) / max(len(ys) - 1, 1):.1f},"
            f"{h - 30 - (y - lo) / span * (h - 60):.1f}"
            for i, y in enumerate(ys)
        )
        return (
            f"<h3>{html.escape(name)}</h3>"
            f"<svg width='{w}' height='{h}' style='border:1px solid #ccc'>"
            f"<polyline fill='none' stroke='#1f77b4' stroke-width='1.5' "
            f"points='{pts}'/>"
            f"<text x='5' y='15' font-size='11'>{hi:.4g}</text>"
            f"<text x='5' y='{h - 10}' font-size='11'>{lo:.4g}</text>"
            "</svg>"
        )
