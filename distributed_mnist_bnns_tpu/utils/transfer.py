"""Checkpoint shipping between machines over TCP.

Capability parity with the reference's hand-rolled master/node socket pair
(mnist change master.py:117-124 binds/listens and replies with the file
size; mnist change node.py:105-107 connects and ships the checkpoint
filename after saving — code that is broken in the reference, SURVEY §2.8).
On TPU pods the normal path is a shared filesystem/GCS bucket (see
utils/checkpoint.py); this utility covers the no-shared-storage case the
reference's socket pair addressed, with a correct length-prefixed protocol
instead of the reference's filename/size handshake.

Protocol (all big-endian):
    8-byte name length | name utf-8 | 8-byte payload length
    | 32-byte sha256(payload) | payload bytes
The receiver verifies the digest BEFORE the atomic tmp→rename (a
truncated-but-length-matching or bit-flipped ship is rejected, never
silently accepted as a checkpoint), then replies with the 8-byte payload
length + its own 32-byte digest of the written bytes as the ack; the
sender verifies both. Same sha256 the checkpoint integrity layer records
in checkpoint_meta.json (utils/checkpoint.file_digest).
"""

from __future__ import annotations

import hashlib
import logging
import os
import socket
import struct
import time
from typing import Tuple

log = logging.getLogger(__name__)

_LEN = struct.Struct(">Q")
_DIGEST_BYTES = hashlib.sha256().digest_size  # 32

RETRIES_TOTAL = "transfer_retries_total"


def _retry_counter():
    from ..obs import default_registry  # lazy: keep import-time light

    return default_registry().counter(
        RETRIES_TOTAL, "checkpoint-shipping connect retries"
    )


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-transfer")
        buf.extend(chunk)
    return bytes(buf)


def _connect_with_retries(
    host: str, port: int, *, timeout: float,
    retries: int, backoff_s: float,
) -> socket.socket:
    """create_connection with jittered-exponential connect retries —
    the receiver races to bind/listen, so a refused or timed-out
    connect is the expected transient, not an error (the reference's
    node just crashed here). Fatal address errors (gaierror) are not
    retried."""
    from ..resilience.policy import RetryPolicy

    policy = RetryPolicy(
        max_restarts=retries, base_backoff_s=backoff_s, max_backoff_s=10.0
    )
    last: Exception = ConnectionError("no attempt made")
    for attempt in range(retries + 1):
        if attempt:
            delay = policy.backoff(attempt)
            _retry_counter().inc(op="connect")
            log.warning(
                "connect to %s:%d failed (%s: %s); retry %d/%d in %.2fs",
                host, port, type(last).__name__, last, attempt, retries,
                delay,
            )
            time.sleep(delay)
        try:
            return socket.create_connection((host, port), timeout=timeout)
        except (ConnectionError, TimeoutError, socket.timeout) as e:
            last = e
    raise ConnectionError(
        f"could not connect to {host}:{port} after {retries + 1} "
        f"attempts (timeout {timeout}s each): "
        f"{type(last).__name__}: {last}"
    ) from last


def send_file(
    path: str, host: str, port: int, *,
    timeout: float = 30.0, retries: int = 3, backoff_s: float = 0.5,
) -> int:
    """Ship one file to a listening receiver; returns bytes sent.

    Connect failures retry with jittered backoff; a peer that stalls
    mid-transfer surfaces as a ``TimeoutError`` naming the peer, the
    file and the deadline instead of a bare ``socket.timeout``. The ack
    must echo both the payload length and its sha256 — a receiver that
    stored different bytes fails the ship loudly on this side too."""
    name = os.path.basename(path).encode()
    with open(path, "rb") as f:
        payload = f.read()
    # Hash the bytes actually being shipped (one read, no TOCTOU with a
    # concurrent rewrite) — the same sha256 utils/checkpoint.file_digest
    # records in checkpoint_meta.json, so a receiver-side resume can
    # cross-check the shipped artifact against its meta.
    digest = hashlib.sha256(payload).digest()
    with _connect_with_retries(
        host, port, timeout=timeout, retries=retries, backoff_s=backoff_s
    ) as s:
        try:
            s.sendall(
                _LEN.pack(len(name)) + name + _LEN.pack(len(payload))
                + digest
            )
            s.sendall(payload)
            ack = _LEN.unpack(_recv_exact(s, _LEN.size))[0]
            ack_digest = _recv_exact(s, _DIGEST_BYTES)
        except (TimeoutError, socket.timeout) as e:
            raise TimeoutError(
                f"{host}:{port} stalled mid-transfer of {path} "
                f"({len(payload)} bytes, timeout {timeout}s)"
            ) from e
    if ack != len(payload):
        raise IOError(f"receiver acked {ack} bytes, sent {len(payload)}")
    if ack_digest != digest:
        raise IOError(
            f"receiver acked sha256 {ack_digest.hex()[:16]}…, sent "
            f"{digest.hex()[:16]}… — stored bytes differ from {path}"
        )
    log.info("shipped %s (%d bytes) to %s:%d", path, len(payload), host, port)
    return len(payload)


def receive_file(
    out_dir: str, port: int, *, host: str = "", timeout: float = 120.0
) -> Tuple[str, int]:
    """Accept one file; returns (path, bytes). Blocks until a sender
    connects (the master's accept loop in the reference)."""
    os.makedirs(out_dir, exist_ok=True)
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as srv:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(1)
        srv.settimeout(timeout)
        try:
            conn, addr = srv.accept()
        except (TimeoutError, socket.timeout) as e:
            raise TimeoutError(
                f"no sender connected to port {port} within {timeout}s"
            ) from e
        with conn:
            conn.settimeout(timeout)
            try:
                name_len = _LEN.unpack(_recv_exact(conn, _LEN.size))[0]
                if name_len > 4096:
                    raise IOError(f"unreasonable name length {name_len}")
                name = os.path.basename(_recv_exact(conn, name_len).decode())
                size = _LEN.unpack(_recv_exact(conn, _LEN.size))[0]
                expected = _recv_exact(conn, _DIGEST_BYTES)
                payload = _recv_exact(conn, size)
            except (TimeoutError, socket.timeout) as e:
                raise TimeoutError(
                    f"sender {addr} stalled mid-transfer into {out_dir} "
                    f"(timeout {timeout}s)"
                ) from e
            # Verify BEFORE the atomic rename: a corrupt ship must never
            # become the latest-checkpoint file a resume would trust.
            got = hashlib.sha256(payload).digest()
            if got != expected:
                raise IOError(
                    f"sha256 mismatch receiving {name} from {addr}: got "
                    f"{got.hex()[:16]}…, sender declared "
                    f"{expected.hex()[:16]}… ({size} bytes) — rejecting "
                    "before rename"
                )
            out_path = os.path.join(out_dir, name)
            tmp = out_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, out_path)
            conn.sendall(_LEN.pack(size) + got)  # length + digest ack
    log.info("received %s (%d bytes) from %s", out_path, size, addr)
    return out_path, size


def ship_checkpoint(ckpt_dir: str, host: str, port: int) -> int:
    """Send the latest checkpoint artifact (the node side of the pair)."""
    from .checkpoint import LATEST

    return send_file(os.path.join(ckpt_dir, LATEST), host, port)


def receive_checkpoint(ckpt_dir: str, port: int, **kw) -> str:
    """Receive a checkpoint into ``ckpt_dir`` (the master side); the file
    lands under the standard latest-checkpoint name, ready for
    load_checkpoint + resume."""
    path, _ = receive_file(ckpt_dir, port, **kw)
    return path
