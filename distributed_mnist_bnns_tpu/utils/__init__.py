from .logging_utils import setup_logging, is_primary_host
from .meters import AverageMeter
from .results import ResultsLog
from .metrics import accuracy

__all__ = [
    "setup_logging",
    "is_primary_host",
    "AverageMeter",
    "ResultsLog",
    "accuracy",
]
