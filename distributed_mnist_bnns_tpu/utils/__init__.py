from .logging_utils import setup_logging, is_primary_host
from .meters import AverageMeter
from .results import ResultsLog
from .metrics import accuracy
from .checkpoint import (
    AsyncCheckpointer,
    latest_exists,
    load_checkpoint,
    read_meta,
    save_checkpoint,
)
from .profiling import StepTimer, trace, annotate
from .recovery import run_with_recovery, TrainingFailure

__all__ = [
    "setup_logging",
    "is_primary_host",
    "AverageMeter",
    "ResultsLog",
    "accuracy",
    "save_checkpoint",
    "AsyncCheckpointer",
    "load_checkpoint",
    "read_meta",
    "latest_exists",
    "StepTimer",
    "trace",
    "annotate",
    "run_with_recovery",
    "TrainingFailure",
]
