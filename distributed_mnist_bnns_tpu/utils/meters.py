"""AverageMeter — running val/sum/count/avg accumulator, parity with the
reference's utils.py:86-102 (used for per-batch wall-time accounting in the
flagship loop, mnist-dist2.py:115,139-140)."""

from __future__ import annotations


class AverageMeter:
    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val: float, n: int = 1) -> None:
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __repr__(self) -> str:  # pragma: no cover
        return f"AverageMeter(val={self.val:.6g}, avg={self.avg:.6g}, n={self.count})"
