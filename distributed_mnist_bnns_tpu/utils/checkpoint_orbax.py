"""Orbax-backed checkpointing — the pod-scale alternative backend.

The default msgpack backend (utils/checkpoint.py) gathers the full train
state to host on process 0 and writes one file: perfect for the
reference-sized models it mirrors (utils.py:76-83), but at pod scale
(BASELINE.json's "ImageNet-1k XNOR-ResNet-50 on v5p-32") it serializes
hundreds of GB through one host. This backend delegates to Orbax
(``orbax.checkpoint``), which writes **each shard from the process that
owns it** (no gather, no single-writer bottleneck), commits atomically,
and restores **directly onto the template's shardings** — an
FSDP/TP-sharded state comes back sharded, no host round-trip and no
re-placement step.

Selected with ``TrainConfig.checkpoint_backend="orbax"`` /
``--checkpoint-backend orbax``. Directory layout mirrors the msgpack
names (latest/best/per-epoch) with orbax directories instead of files;
the sidecar meta json is identical, so ResultsLog/resume bookkeeping is
backend-agnostic.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Any, Optional

import jax

from .checkpoint import _barrier

log = logging.getLogger(__name__)

LATEST_DIR = "orbax_latest"
BEST_DIR = "orbax_best"
META = "checkpoint_meta.json"


def _link_tree(src: str, dst: str) -> None:
    """Replace ``dst`` with a hardlink-copy of ``src`` (content shared,
    metadata-only cost); plain copy fallback for filesystems without
    link support."""
    shutil.rmtree(dst, ignore_errors=True)
    try:
        shutil.copytree(src, dst, copy_function=os.link)
    except OSError:  # pragma: no cover - FS without hardlinks
        shutil.rmtree(dst, ignore_errors=True)
        shutil.copytree(src, dst)


def _state_arrays(state: Any) -> dict:
    """The serializable slice of a TrainState: pure array pytrees (the
    apply_fn/tx statics are reconstructed by the caller's template)."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


class OrbaxCheckpointer:
    """Same call contract as utils.checkpoint.AsyncCheckpointer (save /
    wait / close, one write in flight, trailing barrier in wait), backed
    by orbax's async multi-host checkpointer."""

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.StandardCheckpointer()  # async under the hood
        self._pending_meta = None  # (path, is_best, epoch, save_all, extra)

    def save(
        self,
        state: Any,
        path: str,
        *,
        is_best: bool = False,
        epoch: Optional[int] = None,
        save_all: bool = False,
        extra_meta: Optional[dict] = None,
    ) -> str:
        self.wait()  # single writer: preserve on-disk ordering
        path = os.path.abspath(path)
        os.makedirs(path, exist_ok=True)
        target = os.path.join(path, LATEST_DIR)
        # Every process participates: each writes the shards it owns.
        self._ckptr.save(target, _state_arrays(state), force=True)
        self._pending_meta = (path, is_best, epoch, save_all, extra_meta,
                              int(jax.device_get(state.step)))
        return target

    def _finalize_meta(self) -> None:
        path, is_best, epoch, save_all, extra, step = self._pending_meta
        self._pending_meta = None
        target = os.path.join(path, LATEST_DIR)
        if jax.process_index() == 0:
            meta = {"epoch": epoch, "step": step, "backend": "orbax"}
            meta.update(extra or {})
            with open(os.path.join(path, META), "w") as f:
                json.dump(meta, f)
            # best / per-epoch copies: HARDLINK the committed payload
            # (os.link as the copy function) so the copy is metadata-only
            # — no re-serialization through one host, no duplicated
            # bytes. Falls back to byte copies only where the filesystem
            # refuses links.
            if is_best:
                _link_tree(target, os.path.join(path, BEST_DIR))
            if save_all and epoch is not None:
                _link_tree(
                    target, os.path.join(path, f"orbax_epoch_{epoch}")
                )
            log.info(
                "saved orbax checkpoint to %s (epoch=%s best=%s)",
                target, epoch, is_best,
            )

    def wait(self) -> None:
        self._ckptr.wait_until_finished()
        if self._pending_meta is not None:
            self._finalize_meta()
            _barrier("orbax_checkpoint_save")

    def close(self) -> None:
        self.wait()
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint_orbax(
    state: Any,
    path: str,
    *,
    is_best: bool = False,
    epoch: Optional[int] = None,
    save_all: bool = False,
    extra_meta: Optional[dict] = None,
) -> str:
    """Blocking orbax save (the async variant is OrbaxCheckpointer)."""
    with OrbaxCheckpointer() as ck:
        return ck.save(
            state, path, is_best=is_best, epoch=epoch, save_all=save_all,
            extra_meta=extra_meta,
        )


def load_checkpoint_orbax(
    state_template: Any, path: str, *, best: bool = False
) -> Any:
    """Restore into the template's structure AND shardings: each leaf
    comes back as a jax.Array placed exactly like the template's (an
    FSDP/TP-sharded state restores sharded, per process, no gather)."""
    import orbax.checkpoint as ocp

    target = os.path.join(
        os.path.abspath(path), BEST_DIR if best else LATEST_DIR
    )

    def abstract(x):
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(
            getattr(x, "shape", ()),
            getattr(x, "dtype", None) or jax.numpy.asarray(x).dtype,
            sharding=sharding,
        )

    template = jax.tree.map(abstract, _state_arrays(state_template))
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(target, template)
    _barrier("orbax_checkpoint_load")
    return state_template.replace(
        step=restored["step"],
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
    )


def latest_exists_orbax(path: str) -> bool:
    return os.path.isdir(os.path.join(path, LATEST_DIR))
