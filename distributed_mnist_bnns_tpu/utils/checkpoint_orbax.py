"""Orbax-backed checkpointing — the pod-scale alternative backend.

The default msgpack backend (utils/checkpoint.py) gathers the full train
state to host on process 0 and writes one file: perfect for the
reference-sized models it mirrors (utils.py:76-83), but at pod scale
(BASELINE.json's "ImageNet-1k XNOR-ResNet-50 on v5p-32") it serializes
hundreds of GB through one host. This backend delegates to Orbax
(``orbax.checkpoint``), which writes **each shard from the process that
owns it** (no gather, no single-writer bottleneck), commits atomically,
and restores **directly onto the template's shardings** — an
FSDP/TP-sharded state comes back sharded, no host round-trip and no
re-placement step.

Selected with ``TrainConfig.checkpoint_backend="orbax"`` /
``--checkpoint-backend orbax``. Directory layout mirrors the msgpack
names (latest/best/per-epoch) with orbax directories instead of files;
the sidecar meta json is identical, so ResultsLog/resume bookkeeping is
backend-agnostic.
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Any, Optional, Tuple

import jax

from .checkpoint import _barrier

log = logging.getLogger(__name__)

LATEST_DIR = "orbax_latest"
BEST_DIR = "orbax_best"
GEN_DIR_PREFIX = "orbax_gen_"
META = "checkpoint_meta.json"


def _link_tree(src: str, dst: str) -> None:
    """Replace ``dst`` with a hardlink-copy of ``src`` (content shared,
    metadata-only cost); plain copy fallback for filesystems without
    link support."""
    shutil.rmtree(dst, ignore_errors=True)
    try:
        shutil.copytree(src, dst, copy_function=os.link)
    except OSError:  # pragma: no cover - FS without hardlinks
        shutil.rmtree(dst, ignore_errors=True)
        shutil.copytree(src, dst)


def _state_arrays(state: Any) -> dict:
    """The serializable slice of a TrainState: pure array pytrees (the
    apply_fn/tx statics are reconstructed by the caller's template)."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


class OrbaxCheckpointer:
    """Same call contract as utils.checkpoint.AsyncCheckpointer (save /
    wait / close, one write in flight, trailing barrier in wait), backed
    by orbax's async multi-host checkpointer."""

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.StandardCheckpointer()  # async under the hood
        self._pending_meta = None  # (path, is_best, epoch, save_all, extra)

    def save(
        self,
        state: Any,
        path: str,
        *,
        is_best: bool = False,
        epoch: Optional[int] = None,
        save_all: bool = False,
        extra_meta: Optional[dict] = None,
        keep_generations: Optional[int] = None,
        chaos: Any = None,
    ) -> str:
        self.wait()  # single writer: preserve on-disk ordering
        path = os.path.abspath(path)
        os.makedirs(path, exist_ok=True)
        target = os.path.join(path, LATEST_DIR)
        # Every process participates: each writes the shards it owns.
        self._ckptr.save(target, _state_arrays(state), force=True)
        self._pending_meta = (path, is_best, epoch, save_all, extra_meta,
                              int(jax.device_get(state.step)),
                              keep_generations, chaos)
        return target

    def _finalize_meta(self) -> None:
        (path, is_best, epoch, save_all, extra, step,
         keep_generations, chaos) = self._pending_meta
        self._pending_meta = None
        target = os.path.join(path, LATEST_DIR)
        if jax.process_index() == 0:
            from .checkpoint import (
                DEFAULT_KEEP_GENERATIONS,
                _write_meta,
                read_meta,
            )

            keep = (
                DEFAULT_KEEP_GENERATIONS if keep_generations is None
                else max(int(keep_generations), 1)
            )
            prev_meta = read_meta(path)
            prev_gen = prev_meta.get("generation")
            generation = int(prev_gen) + 1 if prev_gen is not None else 0
            # No content digest: orbax's commit protocol already
            # detects torn writes (an uncommitted dir never restores);
            # the field stays for schema parity with msgpack metas.
            # In-place damage to a COMMITTED dir is covered by
            # load_checkpoint_orbax_resilient (generation-dir rollback).
            meta = {
                "epoch": epoch, "step": step, "backend": "orbax",
                "digest": None, "generation": generation,
            }
            meta.update(extra or {})
            # Rollback generations, mirroring the msgpack ledger:
            # hardlink-tree copies named by generation, newest `keep`
            # retained. The save_all per-epoch dirs are the USER'S
            # archive and are never pruned (msgpack parity).
            gen_dir = f"{GEN_DIR_PREFIX}{generation}"
            _link_tree(target, os.path.join(path, gen_dir))
            generations = [{"dir": gen_dir, "epoch": epoch, "step": step,
                            "generation": generation}]
            generations += [
                g for g in (prev_meta.get("generations") or [])
                if g.get("dir") and g["dir"] != gen_dir
            ]
            for stale in generations[keep:]:
                shutil.rmtree(
                    os.path.join(path, stale["dir"]), ignore_errors=True
                )
            meta["generations"] = generations[:keep]
            _write_meta(path, meta)
            # best / per-epoch copies: HARDLINK the committed payload
            # (os.link as the copy function) so the copy is metadata-only
            # — no re-serialization through one host, no duplicated
            # bytes. Falls back to byte copies only where the filesystem
            # refuses links.
            if is_best:
                _link_tree(target, os.path.join(path, BEST_DIR))
            if save_all and epoch is not None:
                _link_tree(
                    target, os.path.join(path, f"orbax_epoch_{epoch}")
                )
            if chaos is not None:
                # resilience fault point: corrupts the largest file in
                # the committed payload (RESILIENCE.md).
                chaos.on_checkpoint_written(target, epoch=epoch, step=step)
            log.info(
                "saved orbax checkpoint to %s (epoch=%s best=%s)",
                target, epoch, is_best,
            )

    def wait(self) -> None:
        self._ckptr.wait_until_finished()
        if self._pending_meta is not None:
            self._finalize_meta()
            _barrier("orbax_checkpoint_save")

    def close(self) -> None:
        self.wait()
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint_orbax(
    state: Any,
    path: str,
    *,
    is_best: bool = False,
    epoch: Optional[int] = None,
    save_all: bool = False,
    extra_meta: Optional[dict] = None,
    keep_generations: Optional[int] = None,
    chaos: Any = None,
) -> str:
    """Blocking orbax save (the async variant is OrbaxCheckpointer)."""
    with OrbaxCheckpointer() as ck:
        return ck.save(
            state, path, is_best=is_best, epoch=epoch, save_all=save_all,
            extra_meta=extra_meta, keep_generations=keep_generations,
            chaos=chaos,
        )


def _restore_target(state_template: Any, target: str) -> Any:
    """Restore one orbax checkpoint dir into the template's structure
    AND shardings: each leaf comes back as a jax.Array placed exactly
    like the template's (an FSDP/TP-sharded state restores sharded, per
    process, no gather). No barrier — callers barrier once they commit
    to a candidate."""
    import orbax.checkpoint as ocp

    def abstract(x):
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(
            getattr(x, "shape", ()),
            getattr(x, "dtype", None) or jax.numpy.asarray(x).dtype,
            sharding=sharding,
        )

    template = jax.tree.map(abstract, _state_arrays(state_template))
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(target, template)
    return state_template.replace(
        step=restored["step"],
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
    )


def load_checkpoint_orbax(
    state_template: Any, path: str, *, best: bool = False
) -> Any:
    """Restore the latest (or best) orbax checkpoint (see
    ``_restore_target``)."""
    state = _restore_target(
        state_template,
        os.path.join(os.path.abspath(path), BEST_DIR if best else LATEST_DIR),
    )
    _barrier("orbax_checkpoint_load")
    return state


def load_checkpoint_orbax_resilient(
    state_template: Any, path: str
) -> Tuple[Any, dict]:
    """The orbax counterpart of ``checkpoint.load_checkpoint_resilient``
    — same ``(state, info)`` contract. Orbax has no content digests
    (its commit protocol rejects torn/uncommitted writes), so candidate
    order is: the latest dir, then the generation ledger's hardlink-tree
    copies newest-first, then the ``save_all_epochs`` archive as a last
    resort; a restore failure — e.g. in-place damage to a committed dir
    — moves on to the next. Raises
    :class:`~.checkpoint.CheckpointCorruptionError` when nothing
    restores."""
    from .checkpoint import CheckpointCorruptionError, read_meta

    base = os.path.abspath(path)
    top_meta = read_meta(path)
    candidates = [(LATEST_DIR,
                   {k: v for k, v in top_meta.items()
                    if k != "generations"})]
    for g in top_meta.get("generations") or []:
        if g.get("dir"):
            candidates.append((g["dir"], {k: v for k, v in g.items()
                                          if k != "dir"}))
    epochs = []
    for name in os.listdir(base) if os.path.isdir(base) else []:
        if name.startswith("orbax_epoch_"):
            try:
                epochs.append(int(name.rsplit("_", 1)[1]))
            except ValueError:
                continue
    for e in sorted(epochs, reverse=True):
        candidates.append((f"orbax_epoch_{e}", {"epoch": e}))
    errors = []
    for i, (name, meta) in enumerate(candidates):
        target = os.path.join(base, name)
        if not os.path.isdir(target):
            continue
        try:
            state = _restore_target(state_template, target)
        except Exception as e:
            # Orbax surfaces damage as a zoo of error types; any of
            # them just means "try the previous copy".
            errors.append(f"{name}: {type(e).__name__}: {e}")
            continue
        if errors:
            log.warning(
                "orbax checkpoint rollback: restored %s after skipping "
                "%s", name, "; ".join(errors),
            )
        _barrier("orbax_checkpoint_load")
        return state, {
            "file": name,
            "digest_verified": None,
            "rolled_back": i > 0,
            "errors": errors,
            "meta": dict(meta),
        }
    raise CheckpointCorruptionError(
        f"no loadable orbax checkpoint under {path}: "
        + ("; ".join(errors) if errors else "no checkpoint dirs")
    )


def latest_exists_orbax(path: str) -> bool:
    return os.path.isdir(os.path.join(path, LATEST_DIR))
