"""Checkpoint / resume with single-writer + barrier semantics.

Covers all three reference patterns (SURVEY §5 "Checkpoint / resume"):
  1. ``save_checkpoint``-style latest/best/per-epoch copies
     (utils.py:76-83: checkpoint.pth.tar, model_best.pth.tar,
     checkpoint_epoch_N);
  2. combined model+optimizer state in one artifact with resume
     (mnist change node.py:85-89 / master.py:56-59 — minus the raw-TCP
     shipping: a shared filesystem path replaces the socket pair);
  3. DDP-correct distributed save/load: process 0 writes, everyone
     barriers, all processes load the same bytes
     (mnist-distributed-BNNS2.py:163-175 rank-0-save + dist.barrier +
     map_location load; here the "map_location" remap is unnecessary —
     restored pytrees are host arrays placed by the caller's shardings).

Serialization is flax.serialization msgpack of the full train-state pytree
(params incl. fp32 latent masters, batch_stats, optimizer state, step) —
written atomically (tmp + rename) so a crash mid-write never corrupts the
latest checkpoint.

Integrity + rollback (resilience/, RESILIENCE.md): every save records a
sha256 content digest and a monotonically increasing **generation**
number in ``checkpoint_meta.json``, and hardlinks the artifact to
``checkpoint_gen_<g>.msgpack`` (metadata-only cost; byte-copy fallback),
keeping the newest ``keep_generations``. ``load_checkpoint_resilient``
verifies the digest on restore and falls back generation by generation
past truncated/corrupt artifacts — atomic rename protects against *our*
crash mid-write, digests + generations protect against everything else
(torn NFS writes, bitrot, a chaos-injected corruption).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from .logging_utils import is_primary_host

log = logging.getLogger(__name__)

LATEST = "checkpoint.msgpack"
BEST = "model_best.msgpack"
META = "checkpoint_meta.json"
GEN_PREFIX = "checkpoint_gen_"
DEFAULT_KEEP_GENERATIONS = 3


class CheckpointCorruptionError(RuntimeError):
    """No checkpoint generation under the directory could be verified
    and deserialized."""


class CheckpointTemplateMismatch(ValueError):
    """A digest-VERIFIED artifact failed to deserialize into the
    caller's state template — the checkpoint is intact but the
    model/config changed. A ValueError so the retry policy classifies
    it fatal: rolling back (or restarting fresh) would silently discard
    a healthy run's checkpoints."""


class CheckpointWorldMismatch(ValueError):
    """A digest-verified artifact deserialized cleanly but its array
    shapes differ from the trainer's state template. flax's
    ``from_bytes`` validates pytree STRUCTURE, not leaf shapes — it
    hands back the stored arrays — so before this check, the classic
    cause (a data-parallel world-size change re-shaping the
    ``(world, ...)`` compression/ZeRO rows in opt state) surfaced only
    later as an opaque shape error deep inside jax placement. A
    ValueError so the retry policy classifies it fatal; an elastic run
    (``TrainConfig.elastic`` / resilience.elastic) restores with
    ``on_shape_mismatch="return"`` and re-places the rows instead
    (parallel/remesh)."""


def _barrier(name: str) -> None:
    """Cross-host barrier (no-op single-process) — the dist.barrier() in
    the reference's demo_checkpoint (mnist-distributed-BNNS2.py:171)."""
    if jax.process_count() > 1:  # pragma: no cover - multihost only
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _to_host(tree: Any) -> Any:
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _write_meta(path: str, meta: dict) -> None:
    """Atomic (tmp+rename) meta-sidecar write: the meta now decides
    which artifact to trust (digest, generation ledger, mid-epoch
    resume position), so a kill mid-write must leave the previous
    sidecar intact, not a truncated one that read_meta degrades to {}
    — which would silently disable verification/rollback and restart
    the epoch/generation bookkeeping."""
    target = os.path.join(path, META)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, target)


def _link_or_copy(src: str, dst: str) -> None:
    """Hardlink ``src`` to ``dst`` (content shared, metadata-only cost),
    replacing any stale ``dst``; byte-copy fallback for filesystems
    without link support."""
    try:
        if os.path.exists(dst):
            os.remove(dst)
        os.link(src, dst)
    except OSError:  # pragma: no cover - FS without hardlinks
        shutil.copyfile(src, dst)


def _write_checkpoint(
    host_state: Any,
    path: str,
    is_best: bool,
    epoch: Optional[int],
    save_all: bool,
    extra_meta: Optional[dict],
    keep_generations: Optional[int] = None,
    chaos: Any = None,
) -> str:
    """Serialize an already-host-resident state pytree and write it
    atomically (process 0 only). Pure host work — safe to run on a
    background thread (AsyncCheckpointer) or inline (save_checkpoint).

    ``chaos``: an optional resilience.ChaosController whose
    checkpoint-write fault point runs after the artifact lands — the
    injection site the integrity/rollback machinery is tested against.
    """
    os.makedirs(path, exist_ok=True)
    target = os.path.join(path, LATEST)
    # Primary-host gate, not process_index()==0: multihost elastic ranks
    # are separate single-process jax runtimes sharing one checkpoint
    # store — only JG_MH_RANK 0 may write it (utils/logging_utils).
    if is_primary_host():
        keep = (
            DEFAULT_KEEP_GENERATIONS if keep_generations is None
            else max(int(keep_generations), 1)
        )
        data = serialization.to_bytes(host_state)
        digest = hashlib.sha256(data).hexdigest()
        prev_meta = read_meta(path)
        prev_gen = prev_meta.get("generation")
        generation = int(prev_gen) + 1 if prev_gen is not None else 0
        step = (
            int(np.asarray(host_state.step))
            if hasattr(host_state, "step") else None
        )
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, target)  # atomic
        meta = {
            "epoch": epoch,
            "step": step,
            "digest": digest,
            "generation": generation,
        }
        meta.update(extra_meta or {})
        gen_file = f"{GEN_PREFIX}{generation}.msgpack"
        _link_or_copy(target, os.path.join(path, gen_file))
        # Generation ledger, newest first: each record is the meta of
        # its save (digest included) so a rollback restores the right
        # epoch/step/best_acc bookkeeping, not the latest's.
        generations = [{"file": gen_file, **meta}]
        generations += [
            g for g in (prev_meta.get("generations") or [])
            if g.get("file") and g["file"] != gen_file
        ]
        for stale in generations[keep:]:
            try:
                os.remove(os.path.join(path, stale["file"]))
            except OSError as e:
                log.warning(
                    "could not prune generation %s: %s", stale["file"], e
                )
        meta["generations"] = generations[:keep]
        _write_meta(path, meta)
        if is_best:
            shutil.copyfile(target, os.path.join(path, BEST))
        if save_all and epoch is not None:
            shutil.copyfile(
                target, os.path.join(path, f"checkpoint_epoch_{epoch}.msgpack")
            )
        if chaos is not None:
            chaos.on_checkpoint_written(target, epoch=epoch, step=step)
        log.info("saved checkpoint to %s (epoch=%s best=%s)", target, epoch, is_best)
    return target


def save_checkpoint(
    state: Any,
    path: str,
    *,
    is_best: bool = False,
    epoch: Optional[int] = None,
    save_all: bool = False,
    extra_meta: Optional[dict] = None,
    keep_generations: Optional[int] = None,
    chaos: Any = None,
) -> str:
    """Write the latest checkpoint (+ best / per-epoch copies).

    Only process 0 writes; every process passes the trailing barrier so no
    one races ahead to read a half-written file."""
    target = _write_checkpoint(
        _to_host(state), path, is_best, epoch, save_all, extra_meta,
        keep_generations, chaos,
    )
    _barrier("checkpoint_save")
    return target


class AsyncCheckpointer:
    """Checkpointing that overlaps serialization + disk IO with training.

    ``save`` snapshots the state to host arrays synchronously (the only
    part that must happen before the training loop mutates/donates the
    device buffers) and hands msgpack serialization, the atomic write and
    the best/per-epoch copies to a single background thread — training
    resumes immediately instead of stalling for the write (the role
    Orbax's async checkpointing plays in production JAX training; the
    reference always blocks, utils.py:76-83).

    Ordering/visibility contract:
      * one write in flight at a time — a new ``save`` first joins the
        previous one, so on-disk "latest" order always matches call order;
      * ``wait()`` joins the in-flight write, re-raises any background
        exception, and runs the cross-host barrier (moved out of ``save``
        — multi-process callers that need the file visible call
        ``wait()``; Trainer does this at end of fit and before resume);
      * usable as a context manager (``close`` on exit).
    """

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-writer"
        )
        self._inflight = None

    def save(
        self,
        state: Any,
        path: str,
        *,
        is_best: bool = False,
        epoch: Optional[int] = None,
        save_all: bool = False,
        extra_meta: Optional[dict] = None,
        keep_generations: Optional[int] = None,
        chaos: Any = None,
    ) -> str:
        self.wait()  # single writer: preserve on-disk ordering
        host_state = _to_host(state)  # sync snapshot; copies off device
        self._inflight = self._executor.submit(
            _write_checkpoint, host_state, path, is_best, epoch, save_all,
            extra_meta, keep_generations, chaos,
        )
        return os.path.join(path, LATEST)

    def wait(self) -> None:
        if self._inflight is not None:
            inflight, self._inflight = self._inflight, None
            inflight.result()  # re-raises background write errors
            _barrier("checkpoint_save")

    def close(self) -> None:
        self.wait()
        self._executor.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_checkpoint(state_template: Any, path: str, *, best: bool = False) -> Any:
    """Restore a checkpoint into the structure of ``state_template``.

    All processes read the same bytes (shared path); placement/sharding of
    the restored arrays is inherited from whatever the caller does next
    (device_put / jitted step in_shardings) — the functional analogue of
    the reference's map_location remap."""
    fname = os.path.join(path, BEST if best else LATEST)
    with open(fname, "rb") as f:
        data = f.read()
    restored = serialization.from_bytes(_to_host(state_template), data)
    _barrier("checkpoint_load")
    return restored


def read_meta(path: str) -> dict:
    try:
        with open(os.path.join(path, META)) as f:
            return json.load(f)
    except FileNotFoundError:
        return {}
    except json.JSONDecodeError as e:
        # A torn meta write must degrade like a missing meta (epoch-0
        # bookkeeping), not poison every resume attempt.
        log.warning("unreadable checkpoint meta under %s: %s", path, e)
        return {}


def latest_exists(path: str) -> bool:
    return os.path.exists(os.path.join(path, LATEST))


def file_digest(path: str) -> str:
    """Streaming sha256 of a file (checkpoints can be many GB)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_checkpoint(
    path: str, *, file: str = LATEST, digest: Optional[str] = None
) -> bool:
    """True iff ``file`` under the checkpoint dir matches ``digest``
    (default: the digest recorded in the meta sidecar). A missing
    digest (pre-integrity checkpoint) verifies vacuously True so old
    artifacts stay loadable."""
    fpath = os.path.join(path, file)
    if not os.path.exists(fpath):
        return False
    if digest is None:
        digest = read_meta(path).get("digest")
    if not digest:
        return True
    return file_digest(fpath) == digest


def shape_mismatches(template: Any, restored: Any) -> list:
    """``["path: checkpoint (8, 128) vs run (4, 256)", ...]`` for every
    leaf whose shape differs between two same-structured pytrees.
    ``from_bytes`` restores STORED shapes regardless of the template's,
    so this is the only place a world-size (or model) drift can be
    caught before it detonates inside jax placement/dispatch."""
    out = []
    t_flat = jax.tree_util.tree_flatten_with_path(template)[0]
    r_flat = jax.tree.leaves(restored)
    for (keypath, t), r in zip(t_flat, r_flat):
        ts, rs = np.shape(t), np.shape(r)
        if ts != rs:
            out.append(
                f"{jax.tree_util.keystr(keypath)}: checkpoint {rs} "
                f"vs run {ts}"
            )
    return out


def load_checkpoint_resilient(
    state_template: Any, path: str, *, on_shape_mismatch: str = "raise"
) -> Tuple[Any, dict]:
    """Restore the newest checkpoint generation that verifies and
    deserializes, rolling back past truncated/corrupt artifacts.

    Candidates, newest first: the latest artifact (against the
    top-level meta digest), then each record in the meta's
    ``generations`` ledger. Digest mismatch or a deserialization error
    moves on to the next candidate; pre-integrity checkpoints (no
    digest) skip verification. Assumes the multi-host shared-filesystem
    contract of this module (all processes see the same bytes, so all
    roll back to the same generation).

    Returns ``(state, info)`` where ``info`` carries ``file``,
    ``digest_verified`` (None = no digest recorded), ``rolled_back``,
    ``errors`` (what was skipped, for the rollback event),
    ``shape_mismatches`` (leaf shapes that differ from the template —
    see below) and ``meta`` (the record of the generation actually
    restored — its epoch/step, not the corrupt latest's). Raises
    :class:`CheckpointCorruptionError` when nothing under ``path``
    loads.

    ``on_shape_mismatch``: a restored artifact whose leaf SHAPES differ
    from the template deserialized fine (flax restores stored shapes)
    but cannot run — the classic cause is a data-parallel world-size
    change re-shaping the ``(world, ...)`` compression/ZeRO opt-state
    rows. ``"raise"`` (default) fails fast with
    :class:`CheckpointWorldMismatch` instead of letting the mismatch
    detonate later as an opaque jax placement error; ``"return"`` hands
    the mismatched state back with ``info["shape_mismatches"]`` set —
    the elastic restore path (TrainConfig.elastic) re-places the rows
    via parallel/remesh."""
    if on_shape_mismatch not in ("raise", "return"):
        raise ValueError(
            f"on_shape_mismatch must be 'raise' or 'return', "
            f"got {on_shape_mismatch!r}"
        )
    meta = read_meta(path)
    candidates = []
    if os.path.exists(os.path.join(path, LATEST)):
        candidates.append(
            {"file": LATEST,
             **{k: v for k, v in meta.items() if k != "generations"}}
        )
    for record in meta.get("generations") or []:
        if record.get("file") and os.path.exists(
            os.path.join(path, record["file"])
        ):
            candidates.append(record)
    template = _to_host(state_template)
    errors = []
    tried: list = []  # inodes already rejected (latest and the newest
    #                   generation are hardlinks — don't re-hash GBs)
    for i, record in enumerate(candidates):
        fname = record["file"]
        fpath = os.path.join(path, fname)
        try:
            if any(os.path.samefile(fpath, t) for t in tried):
                errors.append(f"{fname}: same file as a rejected candidate")
                continue
        except OSError:
            pass  # racing deletion; the open below reports it
        # One read serves both the digest check and the deserialize —
        # checkpoints are GBs and this is the resume hot path; a
        # streaming-hash-then-reread would double the IO.
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError as e:
            errors.append(f"{fname}: {type(e).__name__}: {e}")
            continue
        digest = record.get("digest")
        verified: Optional[bool] = None
        if digest:
            if hashlib.sha256(data).hexdigest() != digest:
                errors.append(f"{fname}: digest mismatch")
                tried.append(fpath)
                continue
            verified = True
        try:
            restored = serialization.from_bytes(template, data)
        except Exception as e:
            if verified:
                # Intact bytes that don't fit the template: the MODEL
                # changed, not the file. Falling back would walk every
                # generation, "succeed" as a fresh start, and let the
                # next saves prune the healthy checkpoints.
                raise CheckpointTemplateMismatch(
                    f"{fname} under {path} is digest-verified but does "
                    f"not deserialize into the trainer's state template "
                    f"({type(e).__name__}: {e}) — model/config mismatch "
                    "with the checkpoint, not corruption"
                ) from e
            # Corrupt msgpack surfaces as a zoo of parse/ValueError
            # types; any of them just means "next generation".
            errors.append(f"{fname}: {type(e).__name__}: {e}")
            tried.append(fpath)
            continue
        mismatches = shape_mismatches(template, restored)
        if mismatches and on_shape_mismatch == "raise":
            ckpt_world = record.get("world_size")
            hint = (
                f"checkpoint meta records world_size={ckpt_world}"
                if ckpt_world is not None
                else "no world_size recorded in the checkpoint meta"
            )
            raise CheckpointWorldMismatch(
                f"{fname} under {path} is intact but {len(mismatches)} "
                "leaf(s) have different shapes than the trainer's state "
                f"template (e.g. {'; '.join(mismatches[:3])}); {hint}. "
                "world-size mismatch: ran remesh? An elastic run "
                "re-places the (world, ...) compression/ZeRO rows — "
                "resume with --elastic (TrainConfig.elastic) or rebuild "
                "the trainer at the checkpoint's world. A genuine "
                "model/config change needs a fresh checkpoint dir."
            )
        if errors:
            log.warning(
                "checkpoint rollback: restored %s after skipping %s",
                fname, "; ".join(errors),
            )
        _barrier("checkpoint_load")
        return restored, {
            "file": fname,
            "digest_verified": verified,
            "rolled_back": i > 0,
            "errors": errors,
            "shape_mismatches": mismatches,
            "meta": dict(record),
        }
    raise CheckpointCorruptionError(
        f"no loadable checkpoint under {path}: "
        + ("; ".join(errors) if errors else "no checkpoint files")
    )
