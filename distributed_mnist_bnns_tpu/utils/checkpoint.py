"""Checkpoint / resume with single-writer + barrier semantics.

Covers all three reference patterns (SURVEY §5 "Checkpoint / resume"):
  1. ``save_checkpoint``-style latest/best/per-epoch copies
     (utils.py:76-83: checkpoint.pth.tar, model_best.pth.tar,
     checkpoint_epoch_N);
  2. combined model+optimizer state in one artifact with resume
     (mnist change node.py:85-89 / master.py:56-59 — minus the raw-TCP
     shipping: a shared filesystem path replaces the socket pair);
  3. DDP-correct distributed save/load: process 0 writes, everyone
     barriers, all processes load the same bytes
     (mnist-distributed-BNNS2.py:163-175 rank-0-save + dist.barrier +
     map_location load; here the "map_location" remap is unnecessary —
     restored pytrees are host arrays placed by the caller's shardings).

Serialization is flax.serialization msgpack of the full train-state pytree
(params incl. fp32 latent masters, batch_stats, optimizer state, step) —
written atomically (tmp + rename) so a crash mid-write never corrupts the
latest checkpoint.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

log = logging.getLogger(__name__)

LATEST = "checkpoint.msgpack"
BEST = "model_best.msgpack"
META = "checkpoint_meta.json"


def _barrier(name: str) -> None:
    """Cross-host barrier (no-op single-process) — the dist.barrier() in
    the reference's demo_checkpoint (mnist-distributed-BNNS2.py:171)."""
    if jax.process_count() > 1:  # pragma: no cover - multihost only
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _to_host(tree: Any) -> Any:
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _write_checkpoint(
    host_state: Any,
    path: str,
    is_best: bool,
    epoch: Optional[int],
    save_all: bool,
    extra_meta: Optional[dict],
) -> str:
    """Serialize an already-host-resident state pytree and write it
    atomically (process 0 only). Pure host work — safe to run on a
    background thread (AsyncCheckpointer) or inline (save_checkpoint)."""
    os.makedirs(path, exist_ok=True)
    target = os.path.join(path, LATEST)
    if jax.process_index() == 0:
        data = serialization.to_bytes(host_state)
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, target)  # atomic
        meta = {
            "epoch": epoch,
            "step": int(np.asarray(host_state.step))
            if hasattr(host_state, "step") else None,
        }
        meta.update(extra_meta or {})
        with open(os.path.join(path, META), "w") as f:
            json.dump(meta, f)
        if is_best:
            shutil.copyfile(target, os.path.join(path, BEST))
        if save_all and epoch is not None:
            shutil.copyfile(
                target, os.path.join(path, f"checkpoint_epoch_{epoch}.msgpack")
            )
        log.info("saved checkpoint to %s (epoch=%s best=%s)", target, epoch, is_best)
    return target


def save_checkpoint(
    state: Any,
    path: str,
    *,
    is_best: bool = False,
    epoch: Optional[int] = None,
    save_all: bool = False,
    extra_meta: Optional[dict] = None,
) -> str:
    """Write the latest checkpoint (+ best / per-epoch copies).

    Only process 0 writes; every process passes the trailing barrier so no
    one races ahead to read a half-written file."""
    target = _write_checkpoint(
        _to_host(state), path, is_best, epoch, save_all, extra_meta
    )
    _barrier("checkpoint_save")
    return target


class AsyncCheckpointer:
    """Checkpointing that overlaps serialization + disk IO with training.

    ``save`` snapshots the state to host arrays synchronously (the only
    part that must happen before the training loop mutates/donates the
    device buffers) and hands msgpack serialization, the atomic write and
    the best/per-epoch copies to a single background thread — training
    resumes immediately instead of stalling for the write (the role
    Orbax's async checkpointing plays in production JAX training; the
    reference always blocks, utils.py:76-83).

    Ordering/visibility contract:
      * one write in flight at a time — a new ``save`` first joins the
        previous one, so on-disk "latest" order always matches call order;
      * ``wait()`` joins the in-flight write, re-raises any background
        exception, and runs the cross-host barrier (moved out of ``save``
        — multi-process callers that need the file visible call
        ``wait()``; Trainer does this at end of fit and before resume);
      * usable as a context manager (``close`` on exit).
    """

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-writer"
        )
        self._inflight = None

    def save(
        self,
        state: Any,
        path: str,
        *,
        is_best: bool = False,
        epoch: Optional[int] = None,
        save_all: bool = False,
        extra_meta: Optional[dict] = None,
    ) -> str:
        self.wait()  # single writer: preserve on-disk ordering
        host_state = _to_host(state)  # sync snapshot; copies off device
        self._inflight = self._executor.submit(
            _write_checkpoint, host_state, path, is_best, epoch, save_all,
            extra_meta,
        )
        return os.path.join(path, LATEST)

    def wait(self) -> None:
        if self._inflight is not None:
            inflight, self._inflight = self._inflight, None
            inflight.result()  # re-raises background write errors
            _barrier("checkpoint_save")

    def close(self) -> None:
        self.wait()
        self._executor.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_checkpoint(state_template: Any, path: str, *, best: bool = False) -> Any:
    """Restore a checkpoint into the structure of ``state_template``.

    All processes read the same bytes (shared path); placement/sharding of
    the restored arrays is inherited from whatever the caller does next
    (device_put / jitted step in_shardings) — the functional analogue of
    the reference's map_location remap."""
    fname = os.path.join(path, BEST if best else LATEST)
    with open(fname, "rb") as f:
        data = f.read()
    restored = serialization.from_bytes(_to_host(state_template), data)
    _barrier("checkpoint_load")
    return restored


def read_meta(path: str) -> dict:
    try:
        with open(os.path.join(path, META)) as f:
            return json.load(f)
    except FileNotFoundError:
        return {}


def latest_exists(path: str) -> bool:
    return os.path.exists(os.path.join(path, LATEST))
