"""Tracing / profiling — the reference's only tracing is manual wall-clock
timing (datetime/time.time deltas through AverageMeter,
mnist-dist2.py:109-115,139-150; SURVEY §5). Here that pattern is kept
(StepTimer) and upgraded with real device-level tracing via jax.profiler —
traces are viewable in TensorBoard/Perfetto and capture XLA fusion, HBM
traffic and ICI collectives, which wall-clock timing cannot see.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Iterator, Optional

import jax

from .meters import AverageMeter

log = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None) -> Iterator[None]:
    """Device-level profiler trace: with trace('tb_logs'): step(...)

    No-op when log_dir is None, so call sites can be left in place."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a trace (shows up in the profiler timeline)."""
    with jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Per-step wall-clock accounting (the AverageMeter timing pattern of
    the flagship loop) with optional device sync.

    sync=False measures dispatch time only (keeps the device pipeline
    full — the right default in a hot loop); sync=True blocks on the given
    arrays for true step latency (use at log boundaries / benchmarks).

    ``metric``: a metric name feeds every stop() into the obs registry's
    histogram of that name (obs/registry.py), so ad-hoc timers and the
    telemetry layer read from one store — percentiles included."""

    def __init__(self, metric: Optional[str] = None, **labels: str) -> None:
        self.meter = AverageMeter()
        self._t0: Optional[float] = None
        self._hist = None
        self._labels = labels
        if metric is not None:
            from ..obs import default_registry

            self._hist = default_registry().histogram(
                metric, "StepTimer wall-clock latency"
            )

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, sync_on=None) -> float:
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        self.meter.update(dt)
        if self._hist is not None:
            self._hist.observe(dt, **self._labels)
        return dt

    @property
    def avg(self) -> float:
        return self.meter.avg
