"""Classification metrics — parity with the reference's ``accuracy``
(utils.py:142-155): precision@k for a tuple of k values, as percentages."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp


def accuracy(
    output: jnp.ndarray, target: jnp.ndarray, topk: Sequence[int] = (1,)
) -> list[jnp.ndarray]:
    """precision@k over a batch of logits/log-probs.

    Returns a list of scalars in [0, 100], one per k (the reference's
    percentage convention)."""
    maxk = max(topk)
    topk_idx = jnp.argsort(output, axis=-1)[:, ::-1][:, :maxk]
    correct = topk_idx == target[:, None]
    res = []
    for k in topk:
        res.append(correct[:, :k].any(axis=-1).mean() * 100.0)
    return res
