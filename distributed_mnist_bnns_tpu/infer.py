"""Frozen-model inference for trained BNNs — the packed-bitplane serving
path.

No reference counterpart (the reference never deploys its BNNs; training
scripts only). This is the capability binarization exists for: once
training ends, the fp32 latent masters (models/binarized_modules.py:77-79)
are dead weight — serving needs only the ±1 weights, which pack to 1 bit
per parameter (``ops.prepack_weights``), 32x smaller than fp32 and 16x
smaller than bf16, and the GEMMs run on the bitplane XNOR kernel that wins
the bandwidth-bound small-batch regime (PERF.md).

The classic XNOR-net folding applies between layers: at eval time
``binarize(hardtanh(BN(y)))`` collapses to a per-channel integer threshold
compare, because hardtanh preserves sign and ``binarize`` is the sign:

    sign(BN(y)) = sign(g) * sign(y - theta),  theta = mu - b*sqrt(var+eps)/g

so hidden layers never materialize BN/activation tensors at all: integer
GEMM -> threshold -> ±1 bits -> next packed GEMM. Only the final block
(whose hardtanh values feed the fp32 head) computes the real affine.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .models.mlp import BnnMLP
from .ops.binarize import binarize_ste
from .ops.xnor_gemm import (
    prepack_weights,
    xnor_matmul_packed_affine,
    xnor_matmul_packed_sign,
)

_BN_EPS = 1e-5  # matches BnnMLP's BatchNorm epsilon


def _bn_sign_epilogue(
    bn_params: Dict, bn_stats: Dict
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``binarize(hardtanh(BN(y)))`` as an (a, t) threshold encoding:
    out = where(a*y >= t, +1, -1) with a=+1/t=theta (g>0: y >= theta),
    a=-1/t=-theta (g<0: y <= theta), a=0/t=-c (g==0: the constant sign
    of the BN bias — 0 >= -c picks c). theta = mu - b*sqrt(var+eps)/g.
    Single source of the folding math for both the elementwise compare
    (``_bn_sign_fn``) and the fused kernel epilogue
    (ops.xnor_matmul_packed_sign)."""
    g = bn_params["scale"]
    b = bn_params["bias"]
    mu = bn_stats["mean"]
    s = jnp.sqrt(bn_stats["var"] + _BN_EPS)
    theta = mu - b * s / jnp.where(g == 0.0, 1.0, g)
    a = jnp.sign(g)
    c = jnp.where(b >= 0.0, 1.0, -1.0)
    t = jnp.where(g > 0.0, theta, jnp.where(g < 0.0, -theta, -c))
    return a.astype(jnp.float32), t.astype(jnp.float32)


def _bn_sign_fn(bn_params: Dict, bn_stats: Dict) -> Callable:
    """binarize(hardtanh(BN(y))) as a threshold compare returning ±1 —
    the elementwise form of ``_bn_sign_epilogue``'s encoding."""
    a, t = _bn_sign_epilogue(bn_params, bn_stats)
    return lambda y: jnp.where(a * y >= t, 1.0, -1.0).astype(jnp.float32)


def _bn_affine_params(
    bn_params: Dict, bn_stats: Dict
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Eval-time BN as per-channel (a, c): BN(y) = a*y + c. Shared by the
    elementwise form (``_bn_affine_fn``) and the fused kernel epilogue
    (ops.xnor_matmul_packed_affine)."""
    g = bn_params["scale"]
    b = bn_params["bias"]
    mu = bn_stats["mean"]
    s = jnp.sqrt(bn_stats["var"] + _BN_EPS)
    return g / s, b - g * mu / s


def _bn_affine_fn(bn_params: Dict, bn_stats: Dict) -> Callable:
    """Eval-time BN as a precomputed per-channel affine: a*y + c."""
    a, c = _bn_affine_params(bn_params, bn_stats)
    return lambda y: a * y + c


def _freeze_tensors(model: BnnMLP, variables: Dict) -> Dict[str, Any]:
    """Extract the serializable frozen artifact from trained variables:
    ±1 first-layer weights, packed hidden bitplanes, raw BN params/stats
    (thresholds are rebuilt at load — they are cheap and keeping the raw
    moments makes the artifact inspectable), fp32 head."""
    if not model.binarized:
        raise ValueError("freeze_bnn_mlp requires a binarized BnnMLP")
    if model.stochastic:
        raise ValueError(
            "stochastic activation binarization is a train-time feature; "
            "freeze the deterministic eval path"
        )
    params = variables["params"]
    stats = variables["batch_stats"]
    layers = []
    for name in ("BinarizedDense_1", "BinarizedDense_2"):
        wp, k, n = prepack_weights(binarize_ste(params[name]["kernel"]))
        layers.append({
            "wp": wp, "k": k, "n": n, "bias": params[name]["bias"],
        })
    frozen = {
        "family": "bnn-mlp",
        "w1": binarize_ste(params["BinarizedDense_0"]["kernel"]),
        "b1": params["BinarizedDense_0"]["bias"],
        "bn0": {"params": dict(params["BatchNorm_0"]),
                "stats": dict(stats["BatchNorm_0"])},
        "layers": layers,
        "bn1": {"params": dict(params["BatchNorm_1"]),
                "stats": dict(stats["BatchNorm_1"])},
        "bn2": {"params": dict(params["BatchNorm_2"]),
                "stats": dict(stats["BatchNorm_2"])},
        "head_w": params["Dense_0"]["kernel"],
        "head_b": params["Dense_0"]["bias"],
    }
    latent_bytes = sum(
        int(params[n]["kernel"].size) * 4
        for n in ("BinarizedDense_0", "BinarizedDense_1", "BinarizedDense_2")
    )
    packed_bytes = int(frozen["w1"].size) * 4 + sum(
        int(l["wp"].size) * 4 for l in layers
    )
    frozen["info"] = {
        "family": "bnn-mlp",
        "latent_fp32_weight_bytes": latent_bytes,
        "frozen_weight_bytes": packed_bytes,
        "compression": round(latent_bytes / packed_bytes, 2),
        "packed_layers": ["BinarizedDense_1", "BinarizedDense_2"],
    }
    return frozen


def _build_apply(frozen: Dict[str, Any], interpret: bool) -> Callable:
    """Packed inference function from a frozen artifact (in-memory or
    restored from disk)."""
    w1 = jnp.asarray(frozen["w1"], jnp.float32)  # disk artifact: int8 ±1
    b1 = jnp.asarray(frozen["b1"])
    sign1 = _bn_sign_fn(frozen["bn0"]["params"], frozen["bn0"]["stats"])
    packed = [
        (jnp.asarray(l["wp"]), int(l["k"]), int(l["n"]),
         jnp.asarray(l["bias"]))
        for l in frozen["layers"]
    ]
    # hidden layers fuse their epilogues into the packed GEMM kernels —
    # the (M, N) fp32 pre-activations never round-trip HBM: the middle
    # layer emits the next layer's ±1 bits (BN-threshold-sign epilogue),
    # the final packed layer emits the head's hardtanh values (eval-BN
    # affine + clip epilogue; dropout is identity at eval).
    a_mid, t_mid = _bn_sign_epilogue(
        frozen["bn1"]["params"], frozen["bn1"]["stats"]
    )
    a_fin, c_fin = _bn_affine_params(
        frozen["bn2"]["params"], frozen["bn2"]["stats"]
    )
    wh = jnp.asarray(frozen["head_w"])
    bh = jnp.asarray(frozen["head_b"])

    def apply_fn(images: jnp.ndarray) -> jnp.ndarray:
        x = images.reshape(images.shape[0], -1).astype(jnp.float32)
        y = jnp.dot(x, w1, preferred_element_type=jnp.float32) + b1
        bits = sign1(y)
        wp, k, n, b2 = packed[0]
        bits = xnor_matmul_packed_sign(
            bits, wp, k, n, a_mid, t_mid, b2, interpret=interpret
        )
        wp, k, n, b3 = packed[1]
        h = xnor_matmul_packed_affine(
            bits, wp, k, n, a_fin, c_fin, b3, interpret=interpret
        )
        logits = jnp.dot(h, wh, preferred_element_type=jnp.float32) + bh
        return jax.nn.log_softmax(logits)

    return jax.jit(apply_fn)


def freeze_bnn_mlp(
    model: BnnMLP, variables: Dict, *, interpret: bool = False
) -> Tuple[Callable, Dict[str, Any]]:
    """Freeze a trained binarized BnnMLP into a packed inference function.

    Returns (apply_fn, info): ``apply_fn(images) -> log-probs`` computes
    exactly what ``model.apply(variables, images, train=False)`` computes
    (up to measure-zero threshold ties), with hidden weights stored as
    packed bitplanes and BN/hardtanh/binarize folded into thresholds.
    ``info`` reports the packed weight footprint vs the fp32 masters.
    """
    frozen = _freeze_tensors(model, variables)
    return _build_apply(frozen, interpret), frozen["info"]


def _freeze_any(model, variables, input_shape=None) -> Dict[str, Any]:
    """Family dispatch: frozen-tensor dict for every freezable model."""
    from .infer_conv import _freeze_cnn_tensors, _freeze_resnet_tensors
    from .infer_transformer import _freeze_lm_tensors, _freeze_vit_tensors
    from .models.bnn_cnn import BinarizedCNN
    from .models.resnet import XnorResNet
    from .models.transformer import BinarizedLM, BinarizedTransformer

    if isinstance(model, BnnMLP):
        return _freeze_tensors(model, variables)
    if isinstance(model, BinarizedCNN):
        return _freeze_cnn_tensors(
            model, variables, input_shape or (28, 28, 1)
        )
    if isinstance(model, XnorResNet):
        return _freeze_resnet_tensors(
            model, variables, input_shape or (32, 32, 3)
        )
    if isinstance(model, BinarizedTransformer):
        return _freeze_vit_tensors(model, variables)
    if isinstance(model, BinarizedLM):
        return _freeze_lm_tensors(model, variables)
    from .infer_moe import _freeze_moe_tensors
    from .models.moe import BnnMoEMLP

    if isinstance(model, BnnMoEMLP):
        return _freeze_moe_tensors(model, variables)
    from .infer_qnn import _freeze_qnn_tensors
    from .models.mlp import QnnMLP

    if isinstance(model, QnnMLP):
        return _freeze_qnn_tensors(model, variables)
    raise ValueError(
        f"no packed freeze for {type(model).__name__} (freezable: BnnMLP, "
        "BinarizedCNN, XnorResNet, BinarizedTransformer, BinarizedLM, "
        "BnnMoEMLP, QnnMLP)"
    )


def _build_any(frozen: Dict[str, Any], interpret: bool) -> Callable:
    family = frozen.get("family", "bnn-mlp")
    if family == "bnn-mlp":
        return _build_apply(frozen, interpret)
    from .infer_conv import _build_cnn_apply, _build_resnet_apply

    if family == "bnn-cnn":
        return _build_cnn_apply(frozen, interpret)
    if family == "xnor-resnet":
        return _build_resnet_apply(frozen, interpret)
    if family == "bnn-transformer":
        from .infer_transformer import _build_transformer_apply

        return _build_transformer_apply(frozen, interpret)
    if family == "bnn-moe-mlp":
        from .infer_moe import _build_moe_apply

        return _build_moe_apply(frozen, interpret)
    if family == "qnn-mlp":
        from .infer_qnn import _build_qnn_apply

        return _build_qnn_apply(frozen, interpret)
    raise ValueError(f"unknown packed-artifact family {family!r}")


def export_packed(
    model, variables: Dict, path: str, *, input_shape=None
) -> Dict[str, Any]:
    """Write the frozen packed artifact to ``path`` (msgpack). The file
    holds the 1-bit hidden weights, ±1 first layer, raw BN moments and the
    fp32 head — everything ``load_packed`` needs, nothing else (no latent
    masters, no optimizer state). Covers the MLP, CNN, XNOR-ResNet
    (basic-block and bottleneck, CIFAR or ImageNet stem), transformer
    (vit + causal LM) and MoE families — a ``family`` key dispatches at
    load; conv artifacts additionally carry their freeze-time input
    resolution and padding-correction inputs, transformer artifacts
    their LN/embed fp32 tensors, MoE artifacts the fp32 router and
    routing hyperparameters. Returns the size-info dict."""
    from flax import serialization

    frozen = _freeze_any(model, variables, input_shape)
    frozen = jax.tree.map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x, frozen
    )
    if "w1" in frozen:
        # On disk the ±1 first layer goes as int8 (4x smaller artifact);
        # the runtime still dots it in fp32 (load_packed casts back).
        frozen["w1"] = frozen["w1"].astype(np.int8)
    if "conv1_w" in frozen:
        frozen["conv1_w"] = frozen["conv1_w"].astype(np.int8)
    if "w_embed" in frozen:
        frozen["w_embed"] = frozen["w_embed"].astype(np.int8)
    with open(path, "wb") as f:
        f.write(serialization.msgpack_serialize(frozen))
    return frozen["info"]


def load_packed(
    path: str, *, interpret: bool = False
) -> Tuple[Callable, Dict[str, Any]]:
    """Restore an ``export_packed`` artifact into a jitted predictor."""
    from flax import serialization

    with open(path, "rb") as f:
        frozen = serialization.msgpack_restore(f.read())
    return _build_any(frozen, interpret), dict(frozen["info"])


def make_sharded_predictor(
    frozen: Dict[str, Any], mesh, *, axis: str = "data",
    interpret: bool = False,
) -> Callable:
    """Batch-shard a frozen predictor over a device mesh — offline /
    high-throughput serving as explicit SPMD.

    Each device runs the family's packed kernels on its batch shard with
    the frozen weights broadcast (shard_map closure constants are
    replicated), so the Pallas bitplane calls partition correctly —
    GSPMD cannot auto-partition a ``pallas_call``, which is why this is
    a ``shard_map`` and not a sharding-annotated jit. No collectives:
    inference is embarrassingly data-parallel.

    ``fn(images) -> log-probs`` with the global batch divisible by the
    mesh's ``axis`` size. Accepts the in-memory frozen dict or anything
    ``load_packed`` produced it from.

    Equal to the single-device frozen forward for every family EXCEPT
    ``bnn-moe-mlp``: MoE expert capacity is computed from the batch the
    router sees (infer_moe.py), which under shard_map is the per-device
    shard — the expert-parallel deployment semantic. Sharded MoE output
    therefore equals the per-shard single-device forwards concatenated
    (tested), not the global-batch routing.
    """
    from jax.sharding import PartitionSpec as P

    # shard_map the UN-jitted body (the builders return jit(apply_fn));
    # one outer jit, same as the repo's other shard_map wrappers.
    local_fn = _build_any(frozen, interpret)
    local_fn = getattr(local_fn, "__wrapped__", local_fn)
    from .parallel.compat import shard_map

    shmapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis),),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(shmapped)
