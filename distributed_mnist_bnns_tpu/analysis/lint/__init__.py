"""AST-based JAX-footgun linter (rules JG001-JG006). See ANALYSIS.md."""

from .core import (
    Finding,
    LintModule,
    fix_suppressions,
    format_human,
    format_json,
    run_paths,
    run_source,
)
from .rules import RULES

__all__ = [
    "Finding",
    "LintModule",
    "RULES",
    "fix_suppressions",
    "format_human",
    "format_json",
    "run_paths",
    "run_source",
]
