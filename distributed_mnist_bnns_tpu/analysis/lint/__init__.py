"""AST-based linter: the JAX-footgun pack (JG001-JG006) and the
concurrency pack (JG007-JG011, ``analysis/concurrency/``). See
ANALYSIS.md."""

from .core import (
    Finding,
    LintModule,
    changed_py_files,
    fix_suppressions,
    format_human,
    format_json,
    format_sarif,
    run_paths,
    run_source,
)
from .rules import RULES

__all__ = [
    "Finding",
    "LintModule",
    "RULES",
    "changed_py_files",
    "fix_suppressions",
    "format_human",
    "format_json",
    "format_sarif",
    "run_paths",
    "run_source",
]
