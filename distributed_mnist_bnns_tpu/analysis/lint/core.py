"""Linter engine: AST analysis shared by the rules, suppression comments,
file walking and output formatting.

The rules themselves live in ``rules.py``; this module gives them a
parsed, pre-analyzed view of one source file (``LintModule``) with the
JAX-specific groundwork done once:

* a parent map over the AST,
* per-scope name -> FunctionDef/Lambda/assignment resolution,
* the set of *traced* function definitions — functions that run under a
  trace (``jax.jit``/``pmap``/``shard_map`` wrapping or decoration,
  ``lax.scan``/``fori_loop``/``while_loop``/``cond`` bodies), plus
  everything lexically nested inside one.

Suppression syntax (checked by tests/test_analysis.py)::

    x = float(y)  # jg: disable=JG001 -- y is a static python scalar here

A ``# jg: disable=...`` comment suppresses the listed rules (or ``all``)
on its own line; a comment-only line suppresses them on the next code
line. The ``--`` reason is mandatory — an unexplained suppression is
itself reported as unsuppressable ``JG000``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*jg:\s*disable=(?P<rules>[A-Za-z0-9,* ]+?)"
    r"\s*(?:--\s*(?P<reason>.*\S))?\s*$"
)

#: callables whose function-valued arguments run under a trace:
#: name-of-last-dotted-segment -> indices of the traced arguments.
TRACING_WRAPPERS: Dict[str, Tuple[int, ...]] = {
    "jit": (0,),
    "pmap": (0,),
    "shard_map": (0,),
    "scan": (0,),
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "cond": (1, 2),
    "switch": (1, 2, 3, 4, 5),
    "checkpoint": (0,),
    "remat": (0,),
    "vmap": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        if self.reason is None:
            d.pop("reason")
        return d


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.random.PRNGKey' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def parse_suppressions(lines: Sequence[str]) -> Dict[int, Tuple[Set[str], Optional[str]]]:
    """1-based line -> (rule ids or {'all'}, reason). A comment-only
    suppression line covers the next line as well."""
    out: Dict[int, Tuple[Set[str], Optional[str]]] = {}
    for i, raw in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {
            r.strip().upper() if r.strip() != "all" else "all"
            for r in m.group("rules").replace("*", "all").split(",")
            if r.strip()
        }
        entry = (rules, m.group("reason"))
        out[i] = entry
        if raw.lstrip().startswith("#"):  # standalone: covers next line
            out[i + 1] = entry
    return out


class LintModule:
    """One parsed source file plus the shared analyses rules consume."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = parse_suppressions(self.lines)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._index_scopes()
        self._find_traced()

    # -- scopes and name resolution -----------------------------------------

    def _index_scopes(self) -> None:
        """Map each function/module scope to its locally-bound callables
        and simple assignments (last lexical binding wins)."""
        self.scope_defs: Dict[ast.AST, Dict[str, ast.AST]] = {}
        self.scope_assigns: Dict[ast.AST, Dict[str, ast.AST]] = {}
        for node in ast.walk(self.tree):
            scope = self.enclosing_scope(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.scope_defs.setdefault(scope, {})[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    if isinstance(node.value, ast.Lambda):
                        self.scope_defs.setdefault(scope, {})[
                            tgt.id
                        ] = node.value
                    self.scope_assigns.setdefault(scope, {})[
                        tgt.id
                    ] = node.value

    def enclosing_scope(self, node: ast.AST) -> ast.AST:
        """Nearest enclosing function scope (or the module)."""
        cur = self.parents.get(node)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)
        ):
            cur = self.parents.get(cur)
        return cur if cur is not None else self.tree

    def enclosing_scopes(self, node: ast.AST) -> Iterable[ast.AST]:
        scope = self.enclosing_scope(node)
        while True:
            yield scope
            if isinstance(scope, ast.Module):
                return
            scope = self.enclosing_scope(scope)

    def resolve_callable(self, node: ast.AST) -> Optional[ast.AST]:
        """Resolve an expression used as a function value to its
        FunctionDef/Lambda: direct lambdas, names bound in an enclosing
        scope, and a one-hop look-through of ``name = shard_map(f, ...)``
        / ``name = jax.jit(f, ...)`` style wrapper assignments."""
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
        if not isinstance(node, ast.Name):
            return None
        for scope in self.enclosing_scopes(node):
            if node.id in self.scope_defs.get(scope, {}):
                return self.scope_defs[scope][node.id]
            if node.id in self.scope_assigns.get(scope, {}):
                value = self.scope_assigns[scope][node.id]
                if (
                    isinstance(value, ast.Call)
                    and last_segment(value.func) in TRACING_WRAPPERS
                    and value.args
                ):
                    inner = value.args[0]
                    if isinstance(inner, ast.Lambda):
                        return inner
                    if isinstance(inner, ast.Name) and inner.id != node.id:
                        return self._lookup_from(scope, inner.id)
                return None
        return None

    def _lookup_from(self, scope: ast.AST, name: str) -> Optional[ast.AST]:
        while True:
            if name in self.scope_defs.get(scope, {}):
                return self.scope_defs[scope][name]
            if isinstance(scope, ast.Module):
                return None
            scope = self.enclosing_scope(scope)

    # -- traced-function analysis -------------------------------------------

    def _find_traced(self) -> None:
        """Mark FunctionDefs/Lambdas that run under a JAX trace."""
        traced: Set[ast.AST] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec
                    if isinstance(dec, ast.Call):
                        # functools.partial(jax.jit, ...) or jax.jit(...)
                        if last_segment(dec.func) == "partial" and dec.args:
                            target = dec.args[0]
                        else:
                            target = dec.func
                    if last_segment(target) in ("jit", "pmap"):
                        traced.add(node)
            elif isinstance(node, ast.Call):
                seg = last_segment(node.func)
                if seg in TRACING_WRAPPERS:
                    for idx in TRACING_WRAPPERS[seg]:
                        if idx < len(node.args):
                            fn = self.resolve_callable(node.args[idx])
                            if fn is not None:
                                traced.add(fn)
        self.traced = traced

    def is_traced(self, node: ast.AST) -> bool:
        """True when ``node`` executes under a trace: inside (or being)
        a traced def, including lexical nesting."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in self.traced:
                return True
            cur = self.parents.get(cur)
        return False

    def nearest_def(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            cur = self.parents.get(cur)
        return cur

    def is_test_file(self) -> bool:
        base = os.path.basename(self.path)
        return base.startswith(("test_", "conftest")) or (
            os.sep + "tests" + os.sep
        ) in self.path


def apply_suppressions(module: LintModule, findings: List[Finding]) -> List[Finding]:
    """Mark suppressed findings. A disable with no ``-- reason``, or a
    placeholder ``TODO`` reason (what ``--fix-suppressions`` writes),
    does NOT suppress — the finding stays active and a companion
    ``JG000`` records the bad suppression itself, so the gate cannot be
    neutralized without writing a real justification."""
    extra: List[Finding] = []
    for f in findings:
        entry = module.suppressions.get(f.line)
        if entry is None:
            continue
        rules, reason = entry
        if "all" in rules or f.rule in rules:
            if not reason or reason.strip().upper().startswith("TODO"):
                what = "without a '-- reason'" if not reason else (
                    "with a placeholder TODO reason"
                )
                extra.append(
                    Finding(
                        rule="JG000", path=f.path, line=f.line, col=f.col,
                        message=(
                            f"suppression of {f.rule} {what} does not "
                            "suppress — write the actual justification"
                        ),
                    )
                )
                continue
            f.suppressed = True
            f.reason = reason
    return findings + extra


def run_source(
    source: str,
    path: str = "<string>",
    rule_ids: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint one source string. Returns findings with suppressions applied
    (suppressed ones included, flagged)."""
    from .rules import RULES

    module = LintModule(path, source)
    selected = (
        {r.upper() for r in rule_ids} if rule_ids else set(RULES.keys())
    )
    findings: List[Finding] = []
    for rule_id, rule in RULES.items():
        if rule_id not in selected:
            continue
        findings.extend(rule.check(module))
    # A rule may visit the same node through two traced roots: dedup.
    seen = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        key = (f.rule, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return apply_suppressions(module, unique)


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".venv")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def run_paths(
    paths: Sequence[str],
    rule_ids: Optional[Sequence[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            findings.extend(run_source(source, path, rule_ids))
        except SyntaxError as e:
            findings.append(
                Finding(
                    rule="JG000", path=path, line=e.lineno or 0, col=0,
                    message=f"could not parse: {e.msg}",
                )
            )
    return findings


def format_human(findings: Sequence[Finding], *, show_suppressed: bool = False) -> str:
    out: List[str] = []
    shown = 0
    for f in findings:
        if f.suppressed and not show_suppressed:
            continue
        shown += 1
        tag = f" (suppressed: {f.reason})" if f.suppressed else ""
        out.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}{tag}")
    n_sup = sum(1 for f in findings if f.suppressed)
    n_active = len(findings) - n_sup
    out.append(
        f"{n_active} finding(s), {n_sup} suppressed"
        + ("" if show_suppressed or not n_sup else " (hidden)")
    )
    return "\n".join(out)


def format_json(findings: Sequence[Finding]) -> str:
    n_sup = sum(1 for f in findings if f.suppressed)
    return json.dumps(
        {
            "findings": [f.to_dict() for f in findings],
            "unsuppressed": len(findings) - n_sup,
            "suppressed": n_sup,
        },
        indent=2,
    )


def _sarif_source_root() -> str:
    """The base result URIs are relativized against: the git toplevel
    when available (what GitHub code scanning resolves URIs from),
    else the working directory."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=10,
        )
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip()
    except OSError:
        pass
    return os.getcwd()


def format_sarif(findings: Sequence[Finding]) -> str:
    """SARIF 2.1.0 — the schema CI annotation uploaders (GitHub code
    scanning, `sarif-tools`) consume. Result URIs are repo-relative
    (code scanning matches them against checkout paths; an absolute
    runner path would silently anchor nothing). Suppressed findings
    are carried with ``suppressions`` entries (SARIF's own mechanism)
    so the reasons survive into the annotation UI; unsuppressed ones
    become ``error``-level results, matching the exit-code gate."""
    from .rules import RULES

    rules_meta = [
        {
            "id": rule.id,
            "name": rule.name,
            "shortDescription": {"text": rule.description},
        }
        for rule in RULES.values()
    ]
    known = {r["id"] for r in rules_meta}
    root = _sarif_source_root()
    results = []
    for f in findings:
        rel = os.path.relpath(os.path.abspath(f.path), root)
        uri = f.path if rel.startswith("..") else rel  # outside root: keep
        result = {
            "ruleId": f.rule,
            "level": "note" if f.suppressed else "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": uri.replace(os.sep, "/"),
                        },
                        "region": {
                            "startLine": max(f.line, 1),
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if f.suppressed:
            result["suppressions"] = [
                {
                    "kind": "inSource",
                    "justification": f.reason or "",
                }
            ]
        results.append(result)
        if f.rule not in known:  # JG000 meta-findings
            known.add(f.rule)
            rules_meta.append({
                "id": f.rule,
                "name": "meta",
                "shortDescription": {
                    "text": "linter meta-finding (bad suppression or "
                            "unparsable file)",
                },
            })
    return json.dumps(
        {
            "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
            "version": "2.1.0",
            "runs": [
                {
                    "tool": {
                        "driver": {
                            "name": "jg-lint",
                            "rules": rules_meta,
                        }
                    },
                    "results": results,
                }
            ],
        },
        indent=2,
    )


def changed_py_files(
    base: str = "HEAD", repo_root: Optional[str] = None
) -> List[str]:
    """Python files changed vs ``base`` per git (staged, unstaged and
    untracked), for ``cli lint --changed-only``. Paths come back
    absolute and existing-only (a deleted file has nothing to lint).
    Raises ``RuntimeError`` when git is unavailable or the diff fails —
    the caller decides whether that falls back to a full lint (CI
    wants loud, a laptop wants convenient)."""
    import subprocess

    def run(cwd: str, *argv: str) -> List[str]:
        proc = subprocess.run(
            argv, cwd=cwd, capture_output=True, text=True, timeout=60,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(argv)} failed: {proc.stderr.strip()}"
            )
        return [ln for ln in proc.stdout.splitlines() if ln.strip()]

    try:
        top = run(
            repo_root or os.getcwd(), "git", "rev-parse", "--show-toplevel",
        )[0]
        # Diff against the merge base (three-dot semantics), not the
        # ref's tip: `--base origin/main` must scope to what THIS
        # branch changed, not every file other PRs landed on main.
        merge_base = run(top, "git", "merge-base", base, "HEAD")[0]
        # Both listings run from the toplevel: `diff --name-only` is
        # toplevel-relative regardless, but `ls-files --others` is
        # cwd-relative AND cwd-scoped — from a subdirectory it would
        # miss untracked files elsewhere and mis-join the rest.
        listed = run(
            top, "git", "diff", "--name-only", "--diff-filter=d",
            merge_base, "--",
        )
        listed += run(
            top, "git", "ls-files", "--others", "--exclude-standard",
        )
    except (OSError, RuntimeError, IndexError) as e:
        raise RuntimeError(f"cannot compute changed files: {e}") from e
    out = []
    for rel in dict.fromkeys(listed):  # dedup, keep order
        if not rel.endswith(".py"):
            continue
        path = os.path.join(top, rel)
        if os.path.isfile(path):
            out.append(path)
    return out


def fix_suppressions(findings: Sequence[Finding]) -> int:
    """Append a TODO suppression comment to every unsuppressed finding's
    line (skipping lines that already carry a jg: comment). Returns the
    number of edited lines. An annotator for burning down a large
    backlog: TODO reasons deliberately do NOT suppress (the finding
    stays active plus a JG000 for the placeholder), so the gate only
    goes green once every reason is actually written."""
    by_file: Dict[str, List[Finding]] = {}
    for f in findings:
        if not f.suppressed and f.rule != "JG000":
            by_file.setdefault(f.path, []).append(f)
    edited = 0
    for path, file_findings in by_file.items():
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines(keepends=True)
        rules_by_line: Dict[int, Set[str]] = {}
        for f in file_findings:
            rules_by_line.setdefault(f.line, set()).add(f.rule)
        for lineno, rules in rules_by_line.items():
            idx = lineno - 1
            if idx >= len(lines) or "jg:" in lines[idx]:
                continue
            body = lines[idx].rstrip("\n")
            lines[idx] = (
                f"{body}  # jg: disable={','.join(sorted(rules))} "
                "-- TODO: justify or fix\n"
            )
            edited += 1
        with open(path, "w", encoding="utf-8") as fh:
            fh.writelines(lines)
    return edited
