"""The repo-tailored JAX-footgun rules.

Each rule is pure AST analysis over one ``LintModule``; none of them
import jax. They are deliberately conservative — a rule that cries wolf
gets suppressed wholesale and teaches nothing — so each encodes the
narrow shape of a footgun this codebase (or its reference) actually hit.
ANALYSIS.md carries the catalog with rationale and examples.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from .core import Finding, LintModule, dotted_name, last_segment


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    description: str
    check: Callable[[LintModule], List[Finding]]


def _finding(module: LintModule, rule_id: str, node: ast.AST, msg: str) -> Finding:
    return Finding(
        rule=rule_id,
        path=module.path,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0),
        message=msg,
    )


# --------------------------------------------------------------------------
# JG001 — host sync inside a traced function
# --------------------------------------------------------------------------

_NUMPY_ALIASES = {"np", "numpy", "onp"}
_SYNC_ATTRS = {"item", "block_until_ready", "tolist", "copy_to_host_async"}


def check_host_sync(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not module.is_traced(node):
            continue
        func = node.func
        if isinstance(func, ast.Name) and func.id == "float" and node.args:
            out.append(
                _finding(
                    module, "JG001", node,
                    "float() on a traced value — host sync / trace-time "
                    "concretization inside a jitted function",
                )
            )
        elif isinstance(func, ast.Attribute) and func.attr in _SYNC_ATTRS:
            out.append(
                _finding(
                    module, "JG001", node,
                    f".{func.attr}() inside a traced function forces a "
                    "device->host sync (or fails to trace)",
                )
            )
        elif (
            isinstance(func, ast.Attribute)
            and func.attr in ("asarray", "array")
            and isinstance(func.value, ast.Name)
            and func.value.id in _NUMPY_ALIASES
        ):
            out.append(
                _finding(
                    module, "JG001", node,
                    f"{func.value.id}.{func.attr}() inside a traced "
                    "function pulls the value to host numpy — use jnp",
                )
            )
    return out


# --------------------------------------------------------------------------
# JG002 — PRNG key hygiene
# --------------------------------------------------------------------------

_SAMPLERS = {
    "normal", "uniform", "randint", "bernoulli", "categorical",
    "permutation", "choice", "gumbel", "truncated_normal", "laplace",
    "exponential", "poisson", "gamma", "beta", "dirichlet", "cauchy",
    "rademacher", "bits", "ball", "loggamma", "maxwell", "t",
}


def _in_test_function(module: LintModule, node: ast.AST) -> bool:
    cur = module.nearest_def(node)
    while cur is not None:
        if getattr(cur, "name", "").startswith("test"):
            return True
        cur = module.nearest_def(cur)
    return False


def _jax_random_names(module: LintModule):
    """(dotted-prefix aliases of jax.random, bare names imported from
    it) — so `random.uniform(lo, hi)` from the *stdlib* is never
    mistaken for a PRNG sampler. `import jax` always contributes the
    canonical 'jax.random' prefix."""
    prefixes = {"jax.random"}
    bare = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    prefixes.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        prefixes.add(a.asname or "random")
            elif node.module == "jax.random":
                for a in node.names:
                    bare.add(a.asname or a.name)
    return prefixes, bare


def check_prng_hygiene(module: LintModule) -> List[Finding]:
    if module.is_test_file():
        return []
    jr_prefixes, jr_bare = _jax_random_names(module)
    out: List[Finding] = []
    # (a) hardcoded seeds
    for node in ast.walk(module.tree):
        if (
            isinstance(node, ast.Call)
            and last_segment(node.func) == "PRNGKey"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, int)
            and not _in_test_function(module, node)
        ):
            out.append(
                _finding(
                    module, "JG002", node,
                    f"hardcoded PRNGKey({node.args[0].value}) in library "
                    "code — accept or derive the seed (split/fold_in) so "
                    "runs are reproducible *and* controllable",
                )
            )
    # (b) key reuse: the same name fed to >= 2 sampling calls with no
    # rebinding in between (per scope, lexical order)
    uses: Dict[tuple, List[int]] = {}
    rebinds: Dict[tuple, List[int]] = {}
    for node in ast.walk(module.tree):
        scope = module.enclosing_scope(node)
        if isinstance(node, ast.Call):
            seg = last_segment(node.func)
            dn = dotted_name(node.func) or ""
            from_jax_random = (
                any(dn == f"{p}.{seg}" for p in jr_prefixes)
                or (dn == seg and seg in jr_bare)
            )
            if (
                seg in _SAMPLERS
                and from_jax_random
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                uses.setdefault((scope, node.args[0].id), []).append(
                    node.lineno
                )
        for tgt_name, lineno in _assigned_names(node):
            rebinds.setdefault((scope, tgt_name), []).append(lineno)
    for (scope, name), lines in uses.items():
        lines = sorted(lines)
        bind_lines = sorted(rebinds.get((scope, name), []))
        for prev, cur in zip(lines, lines[1:]):
            if not any(prev < b <= cur for b in bind_lines):
                out.append(
                    Finding(
                        rule="JG002", path=module.path, line=cur, col=0,
                        message=(
                            f"PRNG key {name!r} reused by a second "
                            f"sampling call (first use line {prev}) "
                            "without split/fold_in — identical randomness"
                        ),
                    )
                )
    return out


def _assigned_names(node: ast.AST):
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    yield n.id, node.lineno
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and isinstance(
        node.target, ast.Name
    ):
        yield node.target.id, node.lineno
    elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
        yield node.target.id, node.lineno


# --------------------------------------------------------------------------
# JG003 — jit-boundary hygiene
# --------------------------------------------------------------------------

_ARRAY_MAKERS = {
    "zeros", "ones", "arange", "asarray", "array", "full", "linspace",
    "eye", "normal", "uniform", "PRNGKey",
}


def _is_train_step_shaped(name: Optional[str], fn: Optional[ast.AST]) -> bool:
    """The shapes we insist donate their input state: a 'step' that is
    explicitly a *train/update* step, or whose first parameter is the
    optimizer-carrying ``state``. Eval steps are excluded — their state
    argument is reused across batches and must NOT be donated.

    Both the jitted binding name AND the resolved callable's own name
    are considered: step builders that jit a shard_map-wrapped body
    (``shmapped = shard_map(compressed_train_step, ...); jax.jit(
    shmapped)``) would otherwise hide a train step behind a wrapper
    binding the name check can't see through — the compressed-DP step
    family is exactly this shape."""
    labels = []
    if name:
        labels.append(name.lower())
    first_param = None
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        labels.append(fn.name.lower())
        if fn.args.args:
            first_param = fn.args.args[0].arg
    if any("eval" in label for label in labels):
        return False
    if not any("step" in label for label in labels):
        return False
    return first_param == "state" or any(
        "train" in label or "update" in label for label in labels
    )


def check_jit_boundary(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        seg = last_segment(node.func)
        if seg == "jit" and node.args:
            arg = node.args[0]
            arg_name = arg.id if isinstance(arg, ast.Name) else None
            fn = module.resolve_callable(arg)
            kwarg_names = {k.arg for k in node.keywords}
            if (
                _is_train_step_shaped(arg_name, fn)
                and "donate_argnums" not in kwarg_names
                and "donate_argnames" not in kwarg_names
            ):
                out.append(
                    _finding(
                        module, "JG003", node,
                        f"jit of train-step-shaped {arg_name or 'function'!s} "
                        "without donate_argnums — the old state buffer "
                        "stays live, doubling param+opt memory",
                    )
                )
            # non-hashable defaults behind static_argnums/names
            if fn is not None and isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                out.extend(_check_static_hashable(module, node, fn))
        elif seg == "shard_map" and node.args:
            out.extend(_check_shardmap_closure(module, node))
    return out


def _check_static_hashable(
    module: LintModule, call: ast.Call, fn: ast.FunctionDef
) -> List[Finding]:
    out: List[Finding] = []
    params = [a.arg for a in fn.args.args]
    defaults = fn.args.defaults
    default_by_param = dict(zip(params[len(params) - len(defaults):], defaults))
    static: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    static.append(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    if 0 <= n.value < len(params):
                        static.append(params[n.value])
    for name in static:
        default = default_by_param.get(name)
        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
            out.append(
                _finding(
                    module, "JG003", call,
                    f"static arg {name!r} defaults to an unhashable "
                    f"{type(default).__name__.lower()} — jit static args "
                    "must be hashable (use a tuple/frozenset)",
                )
            )
    return out


def _check_shardmap_closure(module: LintModule, call: ast.Call) -> List[Finding]:
    """Array values captured by a shard_map body from an enclosing
    function become replicated closure constants — usually an unintended
    broadcast (and a silent resharding hazard)."""
    fn = module.resolve_callable(call.args[0])
    if fn is None or isinstance(fn, ast.Lambda):
        body = fn.body if fn is not None else None
        params = {a.arg for a in fn.args.args} if fn is not None else set()
        body_nodes = list(ast.walk(body)) if body is not None else []
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        params = {a.arg for a in fn.args.args}
        body_nodes = [n for stmt in fn.body for n in ast.walk(stmt)]
    else:
        return []
    if not body_nodes:
        return []
    # names bound from array-creating calls in enclosing function scopes
    array_names: Dict[str, int] = {}
    scope = module.enclosing_scope(fn)
    while not isinstance(scope, ast.Module):
        for name, value in module.scope_assigns.get(scope, {}).items():
            if (
                isinstance(value, ast.Call)
                and last_segment(value.func) in _ARRAY_MAKERS
            ):
                dn = dotted_name(value.func) or ""
                root = dn.split(".")[0]
                if root in ("jnp", "np", "numpy", "jax") or dn.startswith(
                    "jax.random"
                ):
                    array_names.setdefault(name, value.lineno)
        scope = module.enclosing_scope(scope)
    if not array_names:
        return []
    locals_bound = set(params)
    for n in body_nodes:
        for name, _ in _assigned_names(n):
            locals_bound.add(name)
    out = []
    seen = set()
    for n in body_nodes:
        if (
            isinstance(n, ast.Name)
            and isinstance(n.ctx, ast.Load)
            and n.id in array_names
            and n.id not in locals_bound
            and n.id not in seen
        ):
            seen.add(n.id)
            out.append(
                _finding(
                    module, "JG003", n,
                    f"shard_map body closes over array {n.id!r} (built at "
                    f"line {array_names[n.id]}) — closure constants are "
                    "replicated to every device; pass it as an argument "
                    "with an explicit in_spec",
                )
            )
    return out


# --------------------------------------------------------------------------
# JG004 — Python control flow on traced values
# --------------------------------------------------------------------------


def check_tracer_control_flow(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for fn in module.traced:
        if isinstance(fn, ast.Lambda):
            continue  # lambdas cannot contain statements
        params = {a.arg for a in fn.args.args}
        params |= {a.arg for a in fn.args.kwonlyargs}
        own_nodes = [
            n for stmt in fn.body for n in ast.walk(stmt)
            if module.nearest_def(n) is fn
        ]
        for n in own_nodes:
            if not isinstance(n, (ast.If, ast.While)):
                continue
            bad = _tracer_names_in_test(n.test, params)
            if bad:
                kind = "if" if isinstance(n, ast.If) else "while"
                out.append(
                    _finding(
                        module, "JG004", n,
                        f"python `{kind}` on traced argument(s) "
                        f"{sorted(bad)} — this branches at trace time "
                        "(ConcretizationTypeError or silent "
                        "specialization); use lax.cond/select, or mark "
                        "the arg static",
                    )
                )
    return out


def _tracer_names_in_test(test: ast.AST, params: set) -> set:
    """Bare parameter names whose runtime *value* steers the branch.
    `x is None`, `isinstance(x, ...)`, and attribute probes like
    `x.ndim == 3` are trace-time-static idioms and excluded."""
    if isinstance(test, ast.Compare):
        ops_static = all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        )
        if ops_static:
            return set()
    bad = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            seg = last_segment(n.func)
            if seg in ("isinstance", "len", "getattr", "hasattr", "callable"):
                return set()
        if isinstance(n, ast.Name) and n.id in params:
            parent_attr = False
            # attribute probes (x.ndim / x.shape / x.dtype) are static
            # under jit; walking from the test we can't see parents, so
            # re-scan: a Name that only appears as an Attribute value
            # with a static attr is fine.
            for m in ast.walk(test):
                if (
                    isinstance(m, ast.Attribute)
                    and m.value is n
                    and m.attr in ("shape", "ndim", "dtype", "size", "sharding")
                ):
                    parent_attr = True
            if not parent_attr:
                bad.add(n.id)
    return bad


# --------------------------------------------------------------------------
# JG005 — silent broad except
# --------------------------------------------------------------------------

_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
}


def check_silent_except(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = node.type is None or (
            last_segment(node.type) in ("Exception", "BaseException")
        )
        if not broad:
            continue
        body_nodes = [n for stmt in node.body for n in ast.walk(stmt)]
        reraises = any(isinstance(n, ast.Raise) for n in body_nodes)
        logs = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in _LOG_METHODS
            for n in body_nodes
        )
        uses_exc = node.name is not None and any(
            isinstance(n, ast.Name) and n.id == node.name
            for n in body_nodes
        )
        if not (reraises or logs or uses_exc):
            what = (
                "bare except" if node.type is None
                else f"except {last_segment(node.type)}"
            )
            out.append(
                _finding(
                    module, "JG005", node,
                    f"{what} swallows the error (no re-raise, no logging, "
                    "exception unused) — narrow the type or log why "
                    "ignoring is safe",
                )
            )
    return out


# --------------------------------------------------------------------------
# JG006 — direct jax.shard_map access (version-compat shim exists)
# --------------------------------------------------------------------------


def check_shard_map_compat(module: LintModule) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Attribute):
            dn = dotted_name(node)
            if dn in ("jax.shard_map", "jax.experimental.shard_map"):
                out.append(
                    _finding(
                        module, "JG006", node,
                        f"direct {dn} access breaks across jax versions "
                        "(moved in 0.5, kwarg renamed) — import "
                        "parallel.compat.shard_map instead",
                    )
                )
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            names = (
                [node.module] if isinstance(node, ast.ImportFrom)
                else [a.name for a in node.names]
            )
            for name in names:
                if name and name.startswith("jax.experimental.shard_map"):
                    out.append(
                        _finding(
                            module, "JG006", node,
                            "import of jax.experimental.shard_map — gone "
                            "on newer jax; import "
                            "parallel.compat.shard_map instead",
                        )
                    )
    return out


# --------------------------------------------------------------------------
# SPMD pack (JG012-JG016) — collective-divergence hazards in shard_map /
# jit bodies. The bug class: a collective executed by some processes but
# not others does not error on a multi-host fleet, it hangs it.
# analysis/spmd.py is the runtime half (per-process schedule recording +
# the lockstep checker); these rules catch the same shapes statically.
# --------------------------------------------------------------------------

_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter",
    "all_gather", "all_to_all", "ppermute", "pshuffle",
}


def _is_collective(node: ast.AST) -> bool:
    """A ``jax.lax.<collective>`` / ``lax.<collective>`` call. Bare names
    are accepted only for the unambiguous ops (``psum``/``all_gather``/
    ``all_to_all``/``ppermute``) — short names like ``pmax`` are too easy
    to collide with user helpers."""
    if not isinstance(node, ast.Call):
        return False
    seg = last_segment(node.func)
    if seg not in _COLLECTIVES:
        return False
    dn = dotted_name(node.func) or ""
    if dn.endswith(f"lax.{seg}"):
        return True
    return dn == seg and seg in (
        "psum", "all_gather", "all_to_all", "ppermute",
    )


def _axis_expr(call: ast.Call) -> Optional[ast.AST]:
    """The axis-name argument of a collective call: second positional,
    or the ``axis_name`` keyword."""
    if len(call.args) > 1:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    return None


def _axis_repr(node: Optional[ast.AST]) -> str:
    if node is None:
        return "?"
    try:
        return ast.unparse(node)
    except (ValueError, RecursionError):  # pragma: no cover
        return "?"


def _resolve_str(module: LintModule, node: Optional[ast.AST]) -> Optional[str]:
    """Resolve an axis expression to a concrete string when statically
    evident: a string literal, or a Name bound to one — via a simple
    assignment in an enclosing scope, or as a parameter whose default is
    a string literal (the repo's ``axis: str = \"data\"`` builder
    idiom). Anything else is unknown (None)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if not isinstance(node, ast.Name):
        return None
    for scope in module.enclosing_scopes(node):
        value = module.scope_assigns.get(scope, {}).get(node.id)
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            return value.value
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = scope.args.args
            defaults = scope.args.defaults
            by_param = dict(
                zip([a.arg for a in params][len(params) - len(defaults):],
                    defaults)
            )
            for a, d in zip(scope.args.kwonlyargs, scope.args.kw_defaults):
                if d is not None:
                    by_param.setdefault(a.arg, d)
            d = by_param.get(node.id)
            if isinstance(d, ast.Constant) and isinstance(d.value, str):
                return d.value
    return None


def _body_nodes(fn: ast.AST) -> List[ast.AST]:
    if isinstance(fn, ast.Lambda):
        return list(ast.walk(fn.body))
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return [n for stmt in fn.body for n in ast.walk(stmt)]
    return []


def _collective_sequence(
    module: LintModule, fn: Optional[ast.AST], depth: int = 1
) -> List[ast.Call]:
    """Lexically-ordered collective calls inside ``fn``, following
    same-module function calls one hop (the wrapper-call machinery JG001
    relies on) so a body that delegates to a helper still shows its
    collective schedule."""
    if fn is None:
        return []
    out: List[ast.Call] = []
    for n in _body_nodes(fn):
        if _is_collective(n):
            out.append(n)
        elif isinstance(n, ast.Call) and depth > 0:
            inner = module.resolve_callable(n.func)
            if inner is not None and inner is not fn:
                out.extend(_collective_sequence(module, inner, depth - 1))
    return out


def _sequence_sig(module: LintModule, calls: List[ast.Call]) -> List[tuple]:
    return [
        (last_segment(c.func), _axis_repr(_axis_expr(c))) for c in calls
    ]


_LAX_COND_NAMES = {"jax.lax.cond", "lax.cond", "jax.lax.switch", "lax.switch"}


def _is_lax_cond(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func) or ""
    return dn in _LAX_COND_NAMES or (
        last_segment(node.func) in ("cond", "switch")
        and dn.endswith((".cond", ".switch"))
        and "lax" in dn
    )


def _mentions_process_index(test: ast.AST) -> bool:
    return any(
        isinstance(n, (ast.Name, ast.Attribute))
        and last_segment(n) in ("process_index", "host_id")
        for n in ast.walk(test)
    )


def _branch_sequences(
    module: LintModule, node: ast.Call
) -> Optional[List[List[ast.Call]]]:
    """Per-branch collective sequences of a lax.cond/switch call, or
    None when any branch fails to resolve (an imported callable, a
    partial, ...) — unknown bodies must stay un-flagged."""
    if last_segment(node.func) == "cond":
        branch_exprs = node.args[1:3]
    else:  # switch(index, branches_sequence, *operands)
        seq = node.args[1] if len(node.args) > 1 else None
        if not isinstance(seq, (ast.Tuple, ast.List)):
            return None
        branch_exprs = list(seq.elts)
    if len(branch_exprs) < 2:
        return None
    seqs = []
    for arg in branch_exprs:
        fn = module.resolve_callable(arg)
        if fn is None:
            return None
        seqs.append(_collective_sequence(module, fn))
    return seqs


def check_collective_divergence(module: LintModule) -> List[Finding]:
    """JG012: a collective reachable from only one side of data-dependent
    control flow — a Python ``if``/``while`` on traced values (or on
    ``process_index()``) inside a traced function, or exactly one branch
    of a ``lax.cond``/``switch``. On one host this is wasted or wrong
    work; on a multi-host fleet the processes that skip the collective
    leave the others blocked in it forever."""
    out: List[Finding] = []
    for fn in module.traced:
        if isinstance(fn, ast.Lambda):
            continue
        params = {a.arg for a in fn.args.args}
        params |= {a.arg for a in fn.args.kwonlyargs}
        for n in _body_nodes(fn):
            if not isinstance(n, (ast.If, ast.While)):
                continue
            data_dep = bool(_tracer_names_in_test(n.test, params)) or (
                _mentions_process_index(n.test)
            )
            if not data_dep:
                continue
            branch_colls = [
                [c for stmt in part for c in ast.walk(stmt)
                 if _is_collective(c)]
                for part in (n.body, n.orelse)
            ]
            have = [bc for bc in branch_colls if bc]
            if len(have) == 1 and not all(branch_colls):
                for c in have[0]:
                    op = last_segment(c.func)
                    out.append(
                        _finding(
                            module, "JG012", c,
                            f"collective `{op}` under a data-dependent "
                            "`if`/`while` with no matching collective on "
                            "the other path — processes that skip it "
                            "leave the rest hung in the collective "
                            "(multi-host deadlock)",
                        )
                    )
    for node in ast.walk(module.tree):
        if not _is_lax_cond(node):
            continue
        seqs = _branch_sequences(module, node)
        if seqs is None:
            continue
        nonempty = [s for s in seqs if s]
        if len(seqs) >= 2 and len(nonempty) >= 1 and len(nonempty) < len(seqs):
            ops = {last_segment(c.func) for s in nonempty for c in s}
            out.append(
                _finding(
                    module, "JG012", node,
                    f"collective(s) {sorted(ops)} in one branch of "
                    "lax.cond/switch but not the other(s) — if devices "
                    "disagree on the predicate, the branch without the "
                    "collective deadlocks the branch with it; hoist the "
                    "collective out of the conditional",
                )
            )
    return out


def check_collective_order(module: LintModule) -> List[Finding]:
    """JG014: branches of the same conditional issue *different*
    collective sequences (both non-empty). Cross-branch order/op
    mismatches compile, but any predicate disagreement across the fleet
    pairs mismatched collectives — undefined results or a hang."""
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if _is_lax_cond(node):
            seqs = _branch_sequences(module, node) or []
        elif isinstance(node, ast.If) and module.is_traced(node):
            fn = module.nearest_def(node)
            params = (
                {a.arg for a in fn.args.args}
                | {a.arg for a in fn.args.kwonlyargs}
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                else set()
            )
            if not (
                _tracer_names_in_test(node.test, params)
                or _mentions_process_index(node.test)
            ):
                continue
            seqs = [
                [c for stmt in part for c in ast.walk(stmt)
                 if _is_collective(c)]
                for part in (node.body, node.orelse)
            ]
        else:
            continue
        nonempty = [s for s in seqs if s]
        if len(nonempty) < 2:
            continue  # one-sided is JG012's finding
        sigs = [_sequence_sig(module, s) for s in nonempty]
        if any(sig != sigs[0] for sig in sigs[1:]):
            out.append(
                _finding(
                    module, "JG014", node,
                    "branches of the same conditional issue different "
                    f"collective sequences ({' vs '.join(str(s) for s in sigs)})"
                    " — divergent schedules deadlock or mis-pair when "
                    "devices disagree on the predicate",
                )
            )
    return out


def _spec_axis_exprs(call: ast.Call) -> Tuple[set, set, bool]:
    """(literal axis strings, symbolic axis Name ids, any_specs_seen)
    from a shard_map call's in_specs/out_specs ``P(...)`` arguments."""
    literals: set = set()
    names: set = set()
    seen = False
    for kw in call.keywords:
        if kw.arg not in ("in_specs", "out_specs"):
            continue
        for n in ast.walk(kw.value):
            if isinstance(n, ast.Call) and last_segment(n.func) in (
                "P", "PartitionSpec",
            ):
                seen = True
                for a in n.args:
                    for leaf in ast.walk(a):
                        if isinstance(leaf, ast.Constant) and isinstance(
                            leaf.value, str
                        ):
                            literals.add(leaf.value)
                        elif isinstance(leaf, ast.Name):
                            names.add(leaf.id)
    return literals, names, seen


def check_axis_name_validity(module: LintModule) -> List[Finding]:
    """JG013: a collective inside a shard_map body names an axis that
    the enclosing shard_map's specs never bind. Only flagged when both
    sides resolve to concrete strings — symbolic matches (the same
    ``axis`` variable on both sides) and unresolvable names are
    trusted."""
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not (
            isinstance(node, ast.Call)
            and last_segment(node.func) == "shard_map"
            and node.args
        ):
            continue
        literals, sym_names, seen = _spec_axis_exprs(node)
        if not seen or not (literals or sym_names):
            continue  # no axis evidence: nothing to check against
        declared = set(literals)
        unresolved_decl = False
        for nm_id in sym_names:
            nm_node = next(
                (
                    n for kw in node.keywords
                    if kw.arg in ("in_specs", "out_specs")
                    for n in ast.walk(kw.value)
                    if isinstance(n, ast.Name) and n.id == nm_id
                ),
                None,
            )
            val = _resolve_str(module, nm_node)
            if val is None:
                unresolved_decl = True
            else:
                declared.add(val)
        body = module.resolve_callable(node.args[0])
        for c in _collective_sequence(module, body):
            ax = _axis_expr(c)
            if ax is None:
                continue
            if isinstance(ax, ast.Name) and ax.id in sym_names:
                continue  # symbolically the same expression as the spec
            val = _resolve_str(module, ax)
            if val is None or val in declared or unresolved_decl:
                continue
            op = last_segment(c.func)
            out.append(
                _finding(
                    module, "JG013", c,
                    f"collective `{op}` over axis {val!r} but the "
                    "enclosing shard_map's specs only bind "
                    f"{sorted(declared) or sorted(sym_names)} — an "
                    "unbound axis name fails at trace time (or silently "
                    "no-ops under vmapped reuse)",
                )
            )
    return out


def _donated_argnums(call: ast.Call) -> List[int]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return [
                n.value for n in ast.walk(kw.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, int)
            ]
    return []


def check_donation_use(module: LintModule) -> List[Finding]:
    """JG015: an argument donated to a jitted call is read again after
    the call with no rebinding in between. Donated buffers are freed
    (aliased into the outputs); depending on backend/jaxlib the read
    returns garbage, raises, or — the PR 8 AOT shape — double-frees."""
    out: List[Finding] = []
    donate_calls: List[Tuple[ast.Call, List[int]]] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if last_segment(node.func) == "jit":
            continue  # the jit() wrapper itself, not a step call
        donated: List[int] = []
        if isinstance(node.func, ast.Name):
            for scope in module.enclosing_scopes(node):
                value = module.scope_assigns.get(scope, {}).get(node.func.id)
                if value is not None:
                    if isinstance(value, ast.Call) and (
                        last_segment(value.func) == "jit"
                    ):
                        donated = _donated_argnums(value)
                    break
        elif isinstance(node.func, ast.Call) and (
            last_segment(node.func.func) == "jit"
        ):
            donated = _donated_argnums(node.func)
        if donated:
            donate_calls.append((node, donated))
    for call, donated in donate_calls:
        scope = module.enclosing_scope(call)
        scope_nodes = (
            [n for stmt in scope.body for n in ast.walk(stmt)]
            if hasattr(scope, "body") and isinstance(scope.body, list)
            else list(ast.walk(scope))
        )
        rebind_lines: Dict[str, List[int]] = {}
        for n in scope_nodes:
            for nm, lineno in _assigned_names(n):
                rebind_lines.setdefault(nm, []).append(lineno)
        for idx in donated:
            if idx >= len(call.args) or not isinstance(
                call.args[idx], ast.Name
            ):
                continue
            nm = call.args[idx].id
            for n in scope_nodes:
                if (
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id == nm
                    and n.lineno > call.lineno
                    and module.enclosing_scope(n) is scope
                    and not any(
                        call.lineno <= b <= n.lineno
                        for b in rebind_lines.get(nm, [])
                    )
                ):
                    out.append(
                        _finding(
                            module, "JG015", n,
                            f"{nm!r} read after being donated to the "
                            f"jitted call at line {call.lineno} "
                            "(donate_argnums) — the buffer was freed "
                            "into the outputs; rebind the result or "
                            "drop the donation (the PR 8 double-free)",
                        )
                    )
                    break  # first use is enough per call/arg
    return out


def check_spec_arity(module: LintModule) -> List[Finding]:
    """JG016: shard_map in_specs/out_specs tuple arity vs the wrapped
    function's signature. Checked only when the specs are literal
    tuples/lists and the body resolves — pytree-valued specs are out of
    static reach and stay silent."""
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not (
            isinstance(node, ast.Call)
            and last_segment(node.func) == "shard_map"
            and node.args
        ):
            continue
        fn = module.resolve_callable(node.args[0])
        if fn is None or not isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        if fn.args.vararg is not None:
            continue
        n_params = len(fn.args.args)
        n_required = n_params - len(fn.args.defaults)
        kw = {k.arg: k.value for k in node.keywords}
        in_specs = kw.get("in_specs")
        if isinstance(in_specs, (ast.Tuple, ast.List)):
            n_specs = len(in_specs.elts)
            if n_specs > n_params or n_specs < n_required:
                out.append(
                    _finding(
                        module, "JG016", in_specs,
                        f"in_specs has {n_specs} entries but the wrapped "
                        f"function takes {n_params} positional "
                        "argument(s) — shard_map zips them; the "
                        "mismatch fails at call time with a pytree "
                        "structure error",
                    )
                )
        out_specs = kw.get("out_specs")
        if isinstance(out_specs, (ast.Tuple, ast.List)) and isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            ret_lens = {
                len(n.value.elts)
                for n in _body_nodes(fn)
                if isinstance(n, ast.Return)
                and isinstance(n.value, ast.Tuple)
            }
            explicit_returns = [
                n for n in _body_nodes(fn)
                if isinstance(n, ast.Return) and n.value is not None
            ]
            if (
                len(ret_lens) == 1
                and len(explicit_returns) == sum(
                    1 for n in _body_nodes(fn)
                    if isinstance(n, ast.Return)
                    and isinstance(n.value, ast.Tuple)
                )
            ):
                (ret_len,) = ret_lens
                if ret_len != len(out_specs.elts):
                    out.append(
                        _finding(
                            module, "JG016", out_specs,
                            f"out_specs has {len(out_specs.elts)} entries "
                            f"but the wrapped function returns "
                            f"{ret_len}-tuples — the mismatch fails at "
                            "trace time with a pytree structure error",
                        )
                    )
    return out


# --------------------------------------------------------------------------
# Event-schema contracts (JG017/JG018) — emit() call sites checked
# against obs/events.py's EVENT_KINDS registry and envelope fields.
# --------------------------------------------------------------------------

_events_registry_cache: Optional[Tuple[Optional[dict], Tuple[str, ...]]] = None


def _event_registry() -> Tuple[Optional[dict], Tuple[str, ...]]:
    """(EVENT_KINDS dict, ENVELOPE_FIELDS tuple) parsed out of the
    package's own obs/events.py with ``ast.literal_eval`` — the linter
    stays import-free (no jax, no package import). Returns (None,
    fallback-envelope) when the module can't be read, in which case
    JG017 stays silent rather than flagging everything unknown."""
    global _events_registry_cache
    if _events_registry_cache is not None:
        return _events_registry_cache
    import os

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        os.pardir, os.pardir, "obs", "events.py",
    )
    kinds: Optional[dict] = None
    envelope: Tuple[str, ...] = ("v", "kind", "ts")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for node in tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            names = {t.id for t in targets if isinstance(t, ast.Name)}
            if "EVENT_KINDS" in names and node.value is not None:
                kinds = ast.literal_eval(node.value)
            elif "ENVELOPE_FIELDS" in names and node.value is not None:
                envelope = tuple(ast.literal_eval(node.value))
    except (OSError, SyntaxError, ValueError):
        kinds = None
    _events_registry_cache = (kinds, envelope)
    return _events_registry_cache


def check_event_kinds(module: LintModule) -> List[Finding]:
    """JG017: an ``emit("<kind>", ...)`` call site whose kind literal is
    missing from obs/events.py's EVENT_KINDS registry. Readers
    (``summarize``, ``cli trace``, SLO monitors) key on kind strings —
    an unregistered kind is invisible to all of them and to the
    OBSERVABILITY.md contract."""
    if module.is_test_file():
        return []
    kinds, _ = _event_registry()
    if not kinds:
        return []
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        kind = node.args[0].value
        if kind not in kinds:
            out.append(
                _finding(
                    module, "JG017", node,
                    f"emit of unregistered event kind {kind!r} — add it "
                    "to obs/events.py EVENT_KINDS (and the "
                    "OBSERVABILITY.md event table) or use a registered "
                    "kind; unregistered kinds are invisible to every "
                    "reader",
                )
            )
    return out


def check_event_envelope(module: LintModule) -> List[Finding]:
    """JG018: an ``emit()`` payload key that collides with the event
    envelope (``v``/``kind``/``ts``) — as an explicit keyword or inside
    a ``**{...}`` literal. The collision silently clobbers the
    envelope's field; it shipped twice (PR 4 ``reload``, PR 6 ``cli
    export``) before the payloads were nested."""
    _, envelope = _event_registry()
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit"
        ):
            continue
        for kw in node.keywords:
            if kw.arg in envelope:
                out.append(
                    _finding(
                        module, "JG018", kw.value,
                        f"emit payload key {kw.arg!r} collides with the "
                        "event envelope — it would clobber the "
                        f"record's own {kw.arg!r} field; nest it "
                        "(e.g. under `info`) or rename it",
                    )
                )
            elif kw.arg is None and isinstance(kw.value, ast.Dict):
                for k in kw.value.keys:
                    if isinstance(k, ast.Constant) and k.value in envelope:
                        out.append(
                            _finding(
                                module, "JG018", k,
                                f"emit **payload key {k.value!r} collides "
                                "with the event envelope — nest or "
                                "rename it",
                            )
                        )
    return out


from ..concurrency.rules import (  # noqa: E402 — after Rule is defined
    check_blocking_in_lock,
    check_callback_in_lock,
    check_check_then_act,
    check_lock_discipline,
    check_wait_predicate,
)

RULES: Dict[str, Rule] = {
    r.id: r
    for r in [
        Rule(
            "JG001", "host-sync-in-trace",
            "float()/np.asarray/.item()/.block_until_ready inside a "
            "jitted / shard_mapped / scanned function",
            check_host_sync,
        ),
        Rule(
            "JG002", "prng-hygiene",
            "hardcoded PRNGKey(literal) in library code; key reuse "
            "across sampling calls without split/fold_in",
            check_prng_hygiene,
        ),
        Rule(
            "JG003", "jit-boundary",
            "train-step jits without donate_argnums; unhashable static "
            "args; shard_map bodies closing over arrays",
            check_jit_boundary,
        ),
        Rule(
            "JG004", "tracer-control-flow",
            "python if/while on traced argument values",
            check_tracer_control_flow,
        ),
        Rule(
            "JG005", "silent-except",
            "broad except that neither re-raises, logs, nor uses the "
            "exception",
            check_silent_except,
        ),
        Rule(
            "JG006", "shard-map-compat",
            "direct jax.shard_map / jax.experimental.shard_map use "
            "instead of the version shim",
            check_shard_map_compat,
        ),
        # Concurrency pack (analysis/concurrency/rules.py): lock
        # discipline for the threaded serving/telemetry stack.
        Rule(
            "JG007", "lock-discipline",
            "guarded attribute (locked writes or '# guarded-by:') read "
            "or written outside its lock in a lock-owning class",
            check_lock_discipline,
        ),
        Rule(
            "JG008", "check-then-act",
            "state checked under a lock but acted on after release and "
            "re-acquisition (TOCTOU window)",
            check_check_then_act,
        ),
        Rule(
            "JG009", "blocking-in-lock",
            "blocking call (IO, sleep, thread join, jitted dispatch, "
            "EventLog.emit) while holding a lock",
            check_blocking_in_lock,
        ),
        Rule(
            "JG010", "callback-in-lock",
            "user/transition callback invoked under a held lock "
            "(reentrancy deadlock hazard)",
            check_callback_in_lock,
        ),
        Rule(
            "JG011", "wait-needs-predicate",
            "untimed Condition.wait() outside a while-predicate loop",
            check_wait_predicate,
        ),
        # SPMD pack (this module, above): collective-divergence hazards
        # — the multi-host hang class. analysis/spmd.py is the runtime
        # half (lockstep schedule checker).
        Rule(
            "JG012", "collective-divergence",
            "collective reachable from only one branch of "
            "data-dependent control flow (python if/while on traced "
            "values, or lax.cond/switch) — the multi-host hang",
            check_collective_divergence,
        ),
        Rule(
            "JG013", "collective-axis-validity",
            "collective names an axis the enclosing shard_map's "
            "in_specs/out_specs never bind",
            check_axis_name_validity,
        ),
        Rule(
            "JG014", "collective-order-consistency",
            "branches of the same conditional issue different "
            "collective sequences",
            check_collective_order,
        ),
        Rule(
            "JG015", "donation-use-after-donate",
            "argument listed in donate_argnums read again after the "
            "jitted call without rebinding (freed-buffer read)",
            check_donation_use,
        ),
        Rule(
            "JG016", "shard-map-spec-arity",
            "in_specs/out_specs tuple arity mismatched against the "
            "wrapped function's signature / return tuples",
            check_spec_arity,
        ),
        # Event-schema contracts (this module, above): emit() call
        # sites vs obs/events.py's EVENT_KINDS registry + envelope.
        Rule(
            "JG017", "event-kind-registry",
            "emit() of an event kind missing from obs/events.py's "
            "EVENT_KINDS registry",
            check_event_kinds,
        ),
        Rule(
            "JG018", "event-envelope-collision",
            "emit() payload key colliding with the event envelope "
            "(v/kind/ts)",
            check_event_envelope,
        ),
    ]
}
